"""JaxDevicePort: the shipping DevicePort over jax/XLA (ISSUE 14).

Every jitted data-plane program the parameter manager dispatches lives
HERE — moved from core/store.py, tier/coldpath.py, tier/promote.py and
ops/dequant.py, bit-for-bit unchanged — together with the donation-aware
pool allocation, the restore launder, and the program constructors the
fused-step and collective layers use. Programs are module-level so the
jit cache is shared across stores and port instances; the port wraps
each dispatch in the process-wide sharded-dispatch gate
(docs/EXECUTOR.md) so per-device enqueue orders stay identical under
concurrent callers.

Padding convention (unchanged): index entries carrying `OOB` are
dropped by scatters (mode="drop") and zero-filled by gathers
(mode="fill"). A negative index would WRAP on device — only large
positive out-of-range values are safe sentinels (docs/MEMORY.md).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..exec import dispatch_gate
from .port import DevicePort

# THE sharded-dispatch gate (adapm_tpu/exec, docs/EXECUTOR.md): every
# sharded program dispatched by the port funnels through this one
# process-wide mutex, so programs land on every device of the set in a
# single global order. Reentrant and held for the ENQUEUE only (JAX
# dispatch is asynchronous).
_GATE = dispatch_gate()

# Out-of-range slot index for padding / masked entries: dropped by
# scatters (mode="drop"), zero-filled by gathers (mode="fill").
OOB = np.int32(2**31 - 2)

# largest finite fp16 value: the compression wire formats clip to this
# before any f16 cast (values/scales beyond it would cast to inf and
# poison the EF loop with inf/NaN) — shared with tier/quant.py, whose
# host transforms must match the device programs bitwise
F16_MAX = 65504.0


# ---------------------------------------------------------------------------
# jitted data-plane programs (module level: jit cache shared process-wide)
# ---------------------------------------------------------------------------

@jax.jit
def _gather(main, cache, delta, o_shard, o_slot, c_shard, c_slot, use_cache):
    """Pull: main rows for owner-served keys, cache+delta for replica-served
    keys (o_slot is OOB for the latter to avoid pointless remote traffic)."""
    m = main.at[o_shard, o_slot].get(mode="fill", fill_value=0)
    c = (cache.at[c_shard, c_slot].get(mode="fill", fill_value=0)
         + delta.at[c_shard, c_slot].get(mode="fill", fill_value=0))
    return jnp.where(use_cache[:, None], c, m)


def _pool_rows(rows, seg, out, pooling):
    """Reduce gathered member rows into per-bag vectors (inlined by the
    _gather_pool* programs). Sum accumulates in BATCH ORDER — the same
    order `np.add.at` applies on host (core/tier/coldpath.py contract),
    so a fused pooled read is bit-identical to host-pooling the same
    gathered rows. Mean divides the batch-order sum by the member count
    once (single fp division; the host twin divides identically).
    Padding members carry seg=OOB and drop from both scatters."""
    summed = out.at[seg].add(rows, mode="drop")
    if pooling == "sum":
        return summed
    cnt = jnp.zeros(out.shape[0], rows.dtype).at[seg].add(
        jnp.ones(seg.shape[0], rows.dtype), mode="drop")
    return jnp.where(cnt[:, None] > 0, summed / cnt[:, None],
                     jnp.zeros_like(summed))


@partial(jax.jit, static_argnames=("pooling",))
def _gather_pool(main, cache, delta, o_shard, o_slot, c_shard, c_slot,
                 use_cache, seg, out, *, pooling):
    """Fused embedding-bag read (ISSUE 16): `_gather`'s member-row read
    followed by the in-program segment reduction — one dispatch per
    (length class, pooling) instead of gather + host pool. Nothing is
    donated (the `out` buffer is a fresh host array per call), so the
    family contributes empty entries to APM005's auto-derived donation
    map by construction."""
    m = main.at[o_shard, o_slot].get(mode="fill", fill_value=0)
    c = (cache.at[c_shard, c_slot].get(mode="fill", fill_value=0)
         + delta.at[c_shard, c_slot].get(mode="fill", fill_value=0))
    rows = jnp.where(use_cache[:, None], c, m)
    return _pool_rows(rows, seg, out, pooling)


@partial(jax.jit, donate_argnums=(0, 1))
def _scatter_add(main, delta, o_shard, o_slot, d_shard, d_slot, vals):
    """Push: each row routed either to main (owner path; d_slot=OOB) or to a
    local replica's delta row (o_slot=OOB). Duplicate keys accumulate."""
    main = main.at[o_shard, o_slot].add(vals, mode="drop")
    delta = delta.at[d_shard, d_slot].add(vals, mode="drop")
    return main, delta


@partial(jax.jit, donate_argnums=(0, 1, 2))
def _set_rows(main, cache, delta, o_shard, o_slot, vals, c_shard, c_slot):
    """Set: overwrite the main copy; refresh the writer's local replica (if
    any) and clear its pending delta so a local read observes the set value."""
    main = main.at[o_shard, o_slot].set(vals, mode="drop")
    cache = cache.at[c_shard, c_slot].set(vals, mode="drop")
    delta = delta.at[c_shard, c_slot].set(jnp.zeros_like(vals), mode="drop")
    return main, cache, delta


@partial(jax.jit, donate_argnums=(1, 2))
def _replica_create(main, cache, delta, o_shard, o_slot, c_shard, c_slot):
    """Materialize replicas: copy current main rows into cache slots and zero
    their deltas (reference registerNewIntentsForKeyUnsafe + first refresh,
    handle.h:484-532, 776-840 — one program, since the single-controller
    planner creates replicas synchronously)."""
    rows = main.at[o_shard, o_slot].get(mode="fill", fill_value=0)
    cache = cache.at[c_shard, c_slot].set(rows, mode="drop")
    delta = delta.at[c_shard, c_slot].set(jnp.zeros_like(rows), mode="drop")
    return cache, delta


@partial(jax.jit, donate_argnums=(0, 1, 2))
def _sync_replicas(main, cache, delta, r_shard, r_cslot, o_shard, o_slot):
    """One sync round over a batch of replicas (reference SyncManager
    startSync/ProcessSyncMessage, sync_manager.h:291-382, 553-799): extract
    deltas -> merge into owners (scatter-add; multiple replicas of one key
    all land) -> gather fresh values -> refresh bases, clear deltas."""
    dvals = delta.at[r_shard, r_cslot].get(mode="fill", fill_value=0)
    main = main.at[o_shard, o_slot].add(dvals, mode="drop")
    fresh = main.at[o_shard, o_slot].get(mode="fill", fill_value=0)
    cache = cache.at[r_shard, r_cslot].set(fresh, mode="drop")
    delta = delta.at[r_shard, r_cslot].set(jnp.zeros_like(fresh), mode="drop")
    return main, cache, delta


@partial(jax.jit, donate_argnums=(0, 1, 2), static_argnames=("mode",))
def _sync_replicas_compressed(main, cache, delta, r_shard, r_cslot,
                              o_shard, o_slot, threshold, *, mode):
    """_sync_replicas shipping QUANTIZED deltas with per-key error
    feedback (--sys.sync.compress; ISSUE 8 tentpole, half b). The wire
    transform is applied in-program: the owner merges what a receiver
    would reconstruct from the fp16 / int8+fp16-scale payload — half /
    quarter the future-DCN bytes per round — and the quantization
    remainder is PARKED IN THE REPLICA'S DELTA ROW instead of zeroed
    (the EF-SGD residual loop): it rides into the next shipped round,
    so the main copy's long-run sum stays unbiased and a replica read
    (cache + delta = fresh + residual) keeps read-your-writes to
    within half a grid step. Sub-grid residuals of replicas that go
    CLEAN are flushed exactly by the drop/quiesce paths, which bypass
    compression (core/kv.py _sync_replicas). threshold composes like
    _sync_replicas_thresholded: held rows keep their full delta.
    Returns (main, cache, delta, max-abs parked residual) — the norm
    feeds the sync.ef_residual_norm gauge without a blocking readback
    (converted lazily at snapshot time)."""
    dvals = delta.at[r_shard, r_cslot].get(mode="fill", fill_value=0)
    ship = jnp.max(jnp.abs(dvals), axis=1) >= threshold
    # overflow guard (must match quant.py's host twins bitwise): a
    # delta beyond the fp16 range would cast to inf, merge an inf into
    # the owner row FOREVER and park a -inf residual — clip to the
    # format's max instead; the clipped excess rides the residual and
    # ships over subsequent rounds (the EF loop absorbs saturation the
    # same way it absorbs rounding)
    if mode == "fp16":
        shipped = jnp.clip(dvals, -F16_MAX, F16_MAX).astype(
            jnp.float16).astype(dvals.dtype)
    else:  # int8, symmetric per-row scale rounded through the f16 wire
        s = jnp.clip(jnp.max(jnp.abs(dvals), axis=1) / 127.0,
                     0.0, F16_MAX).astype(jnp.float16).astype(dvals.dtype)
        safe = jnp.where(s > 0, s, 1.0)
        q = jnp.clip(jnp.round(dvals / safe[:, None]), -127, 127)
        shipped = q.astype(jnp.int8).astype(dvals.dtype) * s[:, None]
    resid = dvals - shipped
    rs = jnp.where(ship, r_cslot, OOB)
    osl = jnp.where(ship, o_slot, OOB)
    main = main.at[o_shard, osl].add(shipped, mode="drop")
    fresh = main.at[o_shard, osl].get(mode="fill", fill_value=0)
    cache = cache.at[r_shard, rs].set(fresh, mode="drop")
    new_delta = jnp.where(ship[:, None], resid, dvals)
    delta = delta.at[r_shard, r_cslot].set(new_delta, mode="drop")
    resid_norm = jnp.max(jnp.where(ship[:, None], jnp.abs(resid), 0.0))
    return main, cache, delta, resid_norm


@partial(jax.jit, donate_argnums=(0, 1, 2))
def _sync_replicas_thresholded(main, cache, delta, r_shard, r_cslot,
                               o_shard, o_slot, threshold):
    """_sync_replicas with the reference's sync threshold
    (--sys.sync.threshold, handle.h:601-662, sync_manager.h:805-814): a
    replica whose pending delta is small (max-abs below threshold) is left
    out of the round entirely — no owner merge, no refresh — so tiny updates
    keep accumulating locally instead of paying sync traffic. The delta is
    never lost: it ships in a later round once it grows, or unconditionally
    on drop/quiesce."""
    dvals = delta.at[r_shard, r_cslot].get(mode="fill", fill_value=0)
    ship = jnp.max(jnp.abs(dvals), axis=1) >= threshold
    r_cslot = jnp.where(ship, r_cslot, OOB)
    o_slot = jnp.where(ship, o_slot, OOB)
    main = main.at[o_shard, o_slot].add(dvals, mode="drop")
    fresh = main.at[o_shard, o_slot].get(mode="fill", fill_value=0)
    cache = cache.at[r_shard, r_cslot].set(fresh, mode="drop")
    delta = delta.at[r_shard, r_cslot].set(jnp.zeros_like(fresh), mode="drop")
    return main, cache, delta


@jax.jit
def _read_rows_at(arr, sh, sl):
    return arr.at[sh, sl].get(mode="fill", fill_value=0)


@partial(jax.jit, donate_argnums=(0, 1))
def _install_rows(cache, delta, c_shard, c_slot, vals):
    """Install replica base rows received from a remote owner: set the base,
    zero the pending delta (cross-process replica creation; the local-owner
    twin is _replica_create)."""
    cache = cache.at[c_shard, c_slot].set(vals, mode="drop")
    delta = delta.at[c_shard, c_slot].set(jnp.zeros_like(vals), mode="drop")
    return cache, delta


@partial(jax.jit, donate_argnums=(0, 1))
def _refresh_after_sync(cache, delta, c_shard, c_slot, fresh, shipped):
    """Finish a cross-process sync round: install the owner's fresh value as
    the new base and subtract exactly the shipped delta (pushes that landed
    between extraction and refresh stay pending). Readers see base+delta
    throughout, so a local value never dips below what this worker already
    pushed — the moral equivalent of the reference keeping `val` intact and
    only advancing `sync_state` (handle.h:601-662)."""
    cache = cache.at[c_shard, c_slot].set(fresh, mode="drop")
    delta = delta.at[c_shard, c_slot].add(-shipped, mode="drop")
    return cache, delta


@partial(jax.jit, donate_argnums=(0, 1))
def _relocate(main, delta, old_shard, old_slot, new_shard, new_slot,
              rc_shard, rc_slot):
    """Relocation: move rows old->new; if the destination shard held a
    replica, merge its pending delta (replica->owner upgrade, reference
    refreshUpgradeReplicaUnsafe handle.h:776-840). All gathers happen before
    all scatters, so intra-batch slot reuse is safe."""
    rows = main.at[old_shard, old_slot].get(mode="fill", fill_value=0)
    rows = rows + delta.at[rc_shard, rc_slot].get(mode="fill", fill_value=0)
    main = main.at[new_shard, new_slot].set(rows, mode="drop")
    delta = delta.at[rc_shard, rc_slot].set(jnp.zeros_like(rows), mode="drop")
    return main, delta


# ---------------------------------------------------------------------------
# tiered cold-path programs (host-supplied row overrides + refresh halves)
# ---------------------------------------------------------------------------


@jax.jit
def _gather_cold(main, cache, delta, o_shard, o_row, c_shard, c_slot,
                 use_cache, cold_vals, use_cold):
    """`_gather` with a host-supplied row override: entries whose owner
    row is cold read `cold_vals` (bit-exact select — `jnp.where`, never
    `+ 0`: addition maps -0.0 to +0.0)."""
    m = main.at[o_shard, o_row].get(mode="fill", fill_value=0)
    m = jnp.where(use_cold[:, None], cold_vals, m)
    c = (cache.at[c_shard, c_slot].get(mode="fill", fill_value=0)
         + delta.at[c_shard, c_slot].get(mode="fill", fill_value=0))
    return jnp.where(use_cache[:, None], c, m)


@partial(jax.jit, static_argnames=("pooling",))
def _gather_pool_cold(main, cache, delta, o_shard, o_row, c_shard,
                      c_slot, use_cache, cold_vals, use_cold, seg, out,
                      *, pooling):
    """`_gather_pool` with `_gather_cold`'s host-supplied row override
    for cold owner members."""
    m = main.at[o_shard, o_row].get(mode="fill", fill_value=0)
    m = jnp.where(use_cold[:, None], cold_vals, m)
    c = (cache.at[c_shard, c_slot].get(mode="fill", fill_value=0)
         + delta.at[c_shard, c_slot].get(mode="fill", fill_value=0))
    rows = jnp.where(use_cache[:, None], c, m)
    return _pool_rows(rows, seg, out, pooling)


@partial(jax.jit, donate_argnums=(0,))
def _clear_rows(arr, sh, sl):
    """Zero rows (relocation's replica-delta consume on the host path)."""
    return arr.at[sh, sl].set(
        jnp.zeros((sh.shape[0], arr.shape[-1]), arr.dtype), mode="drop")


@partial(jax.jit, donate_argnums=(0, 1))
def _install_cache_rows(cache, delta, c_shard, c_slot, vals):
    """Set replica bases to `vals` and zero their deltas (the cold
    sync's refresh half; same program shape as _install_rows but
    without the cross-process tracking semantics)."""
    cache = cache.at[c_shard, c_slot].set(vals, mode="drop")
    delta = delta.at[c_shard, c_slot].set(jnp.zeros_like(vals), mode="drop")
    return cache, delta


@partial(jax.jit, donate_argnums=(0, 1))
def _install_cache_rows_resid(cache, delta, c_shard, c_slot, vals, resid):
    """Compressed cold-owner sync refresh: install the fresh base and
    PARK the quantization residual in the delta row instead of zeroing
    it (the EF loop's host twin of _sync_replicas_compressed)."""
    cache = cache.at[c_shard, c_slot].set(vals, mode="drop")
    delta = delta.at[c_shard, c_slot].set(resid, mode="drop")
    return cache, delta


# ---------------------------------------------------------------------------
# wire-row ingest (Tensor Casting co-design; host twins in tier/quant.py)
# ---------------------------------------------------------------------------


@jax.jit
def _gather_cold_fp16(main, cache, delta, o_shard, o_row, c_shard,
                      c_slot, use_cache, cold_q, use_cold):
    """_gather with an fp16 wire override for cold owner rows
    (cold_q: [b, L] f16). The f16->f32 convert is exact — fp16 cold
    rows read the same bits everywhere."""
    m = main.at[o_shard, o_row].get(mode="fill", fill_value=0)
    m = jnp.where(use_cold[:, None], cold_q.astype(main.dtype), m)
    c = (cache.at[c_shard, c_slot].get(mode="fill", fill_value=0)
         + delta.at[c_shard, c_slot].get(mode="fill", fill_value=0))
    return jnp.where(use_cache[:, None], c, m)


@jax.jit
def _gather_cold_int8(main, cache, delta, o_shard, o_row, c_shard,
                      c_slot, use_cache, cold_q, cold_scale, use_cold):
    """_gather with an int8+per-row-scale wire override for cold
    owner rows (cold_q: [b, L] i8, cold_scale: [b] f32)."""
    m = main.at[o_shard, o_row].get(mode="fill", fill_value=0)
    deq = cold_q.astype(main.dtype) * cold_scale[:, None]
    m = jnp.where(use_cold[:, None], deq, m)
    c = (cache.at[c_shard, c_slot].get(mode="fill", fill_value=0)
         + delta.at[c_shard, c_slot].get(mode="fill", fill_value=0))
    return jnp.where(use_cache[:, None], c, m)


@partial(jax.jit, static_argnames=("pooling",))
def _gather_pool_cold_fp16(main, cache, delta, o_shard, o_row, c_shard,
                           c_slot, use_cache, cold_q, use_cold, seg,
                           out, *, pooling):
    """Bag read over fp16 wire cold rows: dequant + pooling fused."""
    m = main.at[o_shard, o_row].get(mode="fill", fill_value=0)
    m = jnp.where(use_cold[:, None], cold_q.astype(main.dtype), m)
    c = (cache.at[c_shard, c_slot].get(mode="fill", fill_value=0)
         + delta.at[c_shard, c_slot].get(mode="fill", fill_value=0))
    rows = jnp.where(use_cache[:, None], c, m)
    return _pool_rows(rows, seg, out, pooling)


@partial(jax.jit, static_argnames=("pooling",))
def _gather_pool_cold_int8(main, cache, delta, o_shard, o_row, c_shard,
                           c_slot, use_cache, cold_q, cold_scale,
                           use_cold, seg, out, *, pooling):
    """Bag read over int8+scale wire cold rows."""
    m = main.at[o_shard, o_row].get(mode="fill", fill_value=0)
    deq = cold_q.astype(main.dtype) * cold_scale[:, None]
    m = jnp.where(use_cold[:, None], deq, m)
    c = (cache.at[c_shard, c_slot].get(mode="fill", fill_value=0)
         + delta.at[c_shard, c_slot].get(mode="fill", fill_value=0))
    rows = jnp.where(use_cache[:, None], c, m)
    return _pool_rows(rows, seg, out, pooling)


@partial(jax.jit, donate_argnums=(0,))
def _write_main_rows(main, sh, row, vals):
    """Install host rows into the hot pool (promotion upload; padding
    rows carry OOB and are dropped)."""
    return main.at[sh, row].set(vals, mode="drop")


@partial(jax.jit, donate_argnums=(0,))
def _write_main_rows_fp16(main, sh, row, qvals):
    """Promotion upload, fp16 wire: dequantize fused into the donated
    hot-pool scatter (padding rows carry OOB and drop)."""
    return main.at[sh, row].set(qvals.astype(main.dtype), mode="drop")


@partial(jax.jit, donate_argnums=(0,))
def _write_main_rows_int8(main, sh, row, qvals, scales):
    """Promotion upload, int8 wire (scales: [b] f32 per-row)."""
    vals = qvals.astype(main.dtype) * scales[:, None]
    return main.at[sh, row].set(vals, mode="drop")


# the restore launder (utils/checkpoint.py restore path): jnp.copy, NOT
# `a + 0` — addition maps -0.0 to +0.0, breaking the exact round-trip
_launder_fn = jax.jit(lambda a: jnp.copy(a))


# ---------------------------------------------------------------------------


class JaxDevicePort(DevicePort):
    """The jax/XLA DevicePort (see port.py for the contract). Stateless
    beyond accounting: the jit caches are module-level, so any number of
    port instances share compiled programs."""

    name = "jax"

    def __init__(self):
        # lock-free liveness-grade counters (the store.gathers
        # convention): a racing increment may be lost; these feed the
        # `device` snapshot section + idle guards, not billing
        self.programs = 0
        self.wire_ingest_rows = 0

    def stats(self) -> dict:
        return {"backend": self.name,
                "programs_total": int(self.programs),
                "wire_ingest_rows_total": int(self.wire_ingest_rows)}

    # -- data-plane programs -------------------------------------------------

    def gather(self, main, cache, delta, o_shard, o_slot, c_shard,
               c_slot, use_cache):
        self.programs += 1
        with _GATE:
            return _gather(main, cache, delta, o_shard, o_slot,
                           c_shard, c_slot, use_cache)

    def gather_pool(self, main, cache, delta, o_shard, o_slot, c_shard,
                    c_slot, use_cache, seg, out, pooling="sum"):
        self.programs += 1
        with _GATE:
            return _gather_pool(main, cache, delta, o_shard, o_slot,
                                c_shard, c_slot, use_cache, seg, out,
                                pooling=pooling)

    def scatter_add(self, main, delta, o_shard, o_slot, d_shard,
                    d_slot, vals):
        self.programs += 1
        with _GATE:
            return _scatter_add(main, delta, o_shard, o_slot, d_shard,
                                d_slot, vals)

    def set_rows(self, main, cache, delta, o_shard, o_slot, vals,
                 c_shard, c_slot):
        self.programs += 1
        with _GATE:
            return _set_rows(main, cache, delta, o_shard, o_slot, vals,
                             c_shard, c_slot)

    def replica_create(self, main, cache, delta, o_shard, o_slot,
                       c_shard, c_slot):
        self.programs += 1
        with _GATE:
            return _replica_create(main, cache, delta, o_shard, o_slot,
                                   c_shard, c_slot)

    def sync_replicas(self, main, cache, delta, r_shard, r_cslot,
                      o_shard, o_slot, threshold: float = 0.0,
                      compress: str = "off"):
        # one single-program helper per variant: the donated pool args
        # must not be mentioned after a donating call in the same
        # function scope (adapm-lint APM005 reasons lexically)
        self.programs += 1
        if compress != "off":
            return self._sync_compressed(main, cache, delta, r_shard,
                                         r_cslot, o_shard, o_slot,
                                         threshold, compress)
        if threshold > 0.0:
            return self._sync_thresholded(main, cache, delta, r_shard,
                                          r_cslot, o_shard, o_slot,
                                          threshold)
        return self._sync_plain(main, cache, delta, r_shard, r_cslot,
                                o_shard, o_slot)

    @staticmethod
    def _sync_compressed(main, cache, delta, r_shard, r_cslot, o_shard,
                         o_slot, threshold, compress):
        thr = jnp.asarray(threshold, main.dtype)
        with _GATE:
            return _sync_replicas_compressed(main, cache, delta,
                                             r_shard, r_cslot, o_shard,
                                             o_slot, thr, mode=compress)

    @staticmethod
    def _sync_thresholded(main, cache, delta, r_shard, r_cslot,
                          o_shard, o_slot, threshold):
        thr = jnp.asarray(threshold, main.dtype)
        with _GATE:
            return _sync_replicas_thresholded(main, cache, delta,
                                              r_shard, r_cslot,
                                              o_shard, o_slot, thr)

    @staticmethod
    def _sync_plain(main, cache, delta, r_shard, r_cslot, o_shard,
                    o_slot):
        with _GATE:
            return _sync_replicas(main, cache, delta, r_shard, r_cslot,
                                  o_shard, o_slot)

    def read_rows_at(self, arr, sh, sl):
        self.programs += 1
        with _GATE:
            return _read_rows_at(arr, sh, sl)

    def install_rows(self, cache, delta, c_shard, c_slot, vals):
        self.programs += 1
        with _GATE:
            return _install_rows(cache, delta, c_shard, c_slot, vals)

    def refresh_after_sync(self, cache, delta, c_shard, c_slot, fresh,
                           shipped):
        self.programs += 1
        with _GATE:
            return _refresh_after_sync(cache, delta, c_shard, c_slot,
                                       fresh, shipped)

    def relocate(self, main, delta, old_shard, old_slot, new_shard,
                 new_slot, rc_shard, rc_slot):
        self.programs += 1
        with _GATE:
            return _relocate(main, delta, old_shard, old_slot,
                             new_shard, new_slot, rc_shard, rc_slot)

    # -- tiered cold path + wire ingest --------------------------------------

    def gather_cold(self, main, cache, delta, o_shard, o_row, c_shard,
                    c_slot, use_cache, cold_vals, use_cold):
        self.programs += 1
        with _GATE:
            return _gather_cold(main, cache, delta, o_shard, o_row,
                                c_shard, c_slot, use_cache, cold_vals,
                                use_cold)

    def gather_cold_wire(self, mode: str, main, cache, delta, o_shard,
                         o_row, c_shard, c_slot, use_cache, cold_q,
                         cold_scale, use_cold):
        self.programs += 1
        # count REAL wire rows (use_cold marks them): the padded bucket
        # is mostly zeros and would inflate the gauge by the padding
        # factor
        self.wire_ingest_rows += int(np.count_nonzero(
            np.asarray(use_cold)))
        with _GATE:
            if mode == "fp16":
                return _gather_cold_fp16(main, cache, delta, o_shard,
                                         o_row, c_shard, c_slot,
                                         use_cache, cold_q, use_cold)
            return _gather_cold_int8(main, cache, delta, o_shard,
                                     o_row, c_shard, c_slot, use_cache,
                                     cold_q, cold_scale, use_cold)

    def gather_pool_cold(self, main, cache, delta, o_shard, o_row,
                         c_shard, c_slot, use_cache, cold_vals,
                         use_cold, seg, out, pooling="sum"):
        self.programs += 1
        with _GATE:
            return _gather_pool_cold(main, cache, delta, o_shard,
                                     o_row, c_shard, c_slot, use_cache,
                                     cold_vals, use_cold, seg, out,
                                     pooling=pooling)

    def gather_pool_cold_wire(self, mode: str, main, cache, delta,
                              o_shard, o_row, c_shard, c_slot,
                              use_cache, cold_q, cold_scale, use_cold,
                              seg, out, pooling="sum"):
        self.programs += 1
        # real wire rows only, same convention as gather_cold_wire
        self.wire_ingest_rows += int(np.count_nonzero(
            np.asarray(use_cold)))
        with _GATE:
            if mode == "fp16":
                return _gather_pool_cold_fp16(
                    main, cache, delta, o_shard, o_row, c_shard,
                    c_slot, use_cache, cold_q, use_cold, seg, out,
                    pooling=pooling)
            return _gather_pool_cold_int8(
                main, cache, delta, o_shard, o_row, c_shard, c_slot,
                use_cache, cold_q, cold_scale, use_cold, seg, out,
                pooling=pooling)

    def write_main_rows(self, main, sh, row, vals):
        self.programs += 1
        with _GATE:
            return _write_main_rows(main, sh, row, vals)

    def write_main_rows_wire(self, mode: str, main, sh, row, qvals,
                             scales=None):
        self.programs += 1
        # real wire rows only (padding rows carry OOB and drop)
        self.wire_ingest_rows += int(np.count_nonzero(
            np.asarray(row) != OOB))
        if mode == "fp16":
            return self._write_wire_fp16(main, sh, row, qvals)
        return self._write_wire_int8(main, sh, row, qvals, scales)

    @staticmethod
    def _write_wire_fp16(main, sh, row, qvals):
        with _GATE:
            return _write_main_rows_fp16(main, sh, row, qvals)

    @staticmethod
    def _write_wire_int8(main, sh, row, qvals, scales):
        with _GATE:
            return _write_main_rows_int8(main, sh, row, qvals, scales)

    def clear_rows(self, arr, sh, sl):
        self.programs += 1
        with _GATE:
            return _clear_rows(arr, sh, sl)

    def install_cache_rows(self, cache, delta, c_shard, c_slot, vals,
                           resid=None):
        self.programs += 1
        if resid is None:
            return self._install_cache_plain(cache, delta, c_shard,
                                             c_slot, vals)
        return self._install_cache_resid(cache, delta, c_shard, c_slot,
                                         vals, resid)

    @staticmethod
    def _install_cache_plain(cache, delta, c_shard, c_slot, vals):
        with _GATE:
            return _install_cache_rows(cache, delta, c_shard, c_slot,
                                       vals)

    @staticmethod
    def _install_cache_resid(cache, delta, c_shard, c_slot, vals,
                             resid):
        with _GATE:
            return _install_cache_rows_resid(cache, delta, c_shard,
                                             c_slot, vals, resid)

    # -- buffer allocation / transfer ----------------------------------------

    def alloc_pool(self, shape, dtype, sharding):
        return jax.device_put(jnp.zeros(shape, dtype), sharding)

    def install_pool(self, arr, sharding):
        return self.launder(jax.device_put(arr, sharding))

    def launder(self, x):
        """Route a transfer-produced buffer through one XLA program
        before it re-enters the donated chain: this image's XLA CPU
        intermittently SEGFAULTS when a donating program consumes a raw
        host->device transfer (r6; observed ~50% of checkpoint
        sessions). Bit-exact (jnp.copy)."""
        self.programs += 1
        with _GATE:  # sharded program: one enqueue order per device set
            return _launder_fn(x)

    def put_replicated(self, arr, sharding):
        # numpy in, asynchronous device_put out — the staging rule
        # (docs/PERF.md "Host-array staging")
        return jax.device_put(np.asarray(arr), sharding)

    def put_single(self, arr, device):
        return jax.device_put(arr, device)

    # -- program construction ------------------------------------------------

    def compile(self, fn, **jit_kwargs):
        return jax.jit(fn, **jit_kwargs)

    def compile_collective(self, fn, mesh, in_specs, out_specs):
        # jax.shard_map graduated from jax.experimental.shard_map; this
        # image's jax predates the top-level alias
        shard_map = getattr(jax, "shard_map", None)
        if shard_map is None:
            from jax.experimental.shard_map import shard_map
        return jax.jit(partial(shard_map, mesh=mesh, in_specs=in_specs,
                               out_specs=out_specs)(fn))
