"""The DevicePort protocol: the narrow device-plane surface (ISSUE 14).

Every accelerator interaction the parameter manager performs — data-plane
gathers/scatters, the sync/relocation programs, the tiered wire-row
ingest, donation-aware pool allocation, fused-step program construction,
and the collective exchange constructor — goes through ONE port object.
The rest of the tree never calls `jax.jit` / `jax.device_put` /
`shard_map` directly (mechanically enforced by adapm-lint APM008:
device-API confinement), so a real-accelerator backend is one new port
implementation, not a tree-wide edit.

The surface is deliberately narrow and index-shaped: port methods take
pool arrays plus padded (shard, slot/row) index buffers — exactly what
`ShardedStore` already computes — and return the replacement pool
arrays. Semantics every implementation must preserve:

  - **bit-exactness**: a port method's result is IEEE-f32 bit-identical
    to the reference `JaxDevicePort` programs (the storm tests compare
    tiered/episodic/compressed execution against shadows bitwise; a
    port that rounds differently fails them);
  - **padding**: index entries carrying `core.store.OOB` are no-ops —
    dropped by scatters, zero-filled by gathers;
  - **donation**: pool arguments documented as donated are CONSUMED by
    the call — the caller must rebind from the returned arrays and
    never read the old reference again (adapm-lint APM005);
  - **asynchrony**: methods ENQUEUE device work and return; callers
    hold the process-wide dispatch gate discipline inside the port
    (docs/EXECUTOR.md), never across device execution;
  - **wire ingest**: the `*_wire` methods accept still-quantized
    fp16/int8 payloads (tier/quant.py wire formats) and invert them
    in-program — the Tensor Casting co-design point; host twins in
    tier/quant.py must match bitwise.
"""
from __future__ import annotations

from typing import Optional


class DevicePort:
    """Abstract device-plane port (see module docstring). The shipping
    implementation is `JaxDevicePort` (device/jaxport.py); a GPU/TPU
    backend specializes by overriding program construction — the call
    sites in core/ops/tier never change."""

    # -- identity / health ---------------------------------------------------

    name = "abstract"

    def stats(self) -> dict:
        """Host-side accounting for the `device` snapshot section."""
        raise NotImplementedError

    # -- data-plane programs (core/store.py ShardedStore) --------------------

    def gather(self, main, cache, delta, o_shard, o_slot, c_shard,
               c_slot, use_cache):
        raise NotImplementedError

    def gather_pool(self, main, cache, delta, o_shard, o_slot, c_shard,
                    c_slot, use_cache, seg, out, pooling="sum"):
        """Fused embedding-bag read (ISSUE 16): gather member rows
        exactly as `gather` and reduce them into `out[seg[i]]` in ONE
        program — sum pooling accumulates in batch order (the same
        order `np.add.at` uses on host, so fused-vs-host-pooled results
        are bit-identical by construction); mean divides the batch-order
        sum by the per-bag member count once. `seg` carries OOB for
        padding members (dropped by the pooling scatter); `out` is a
        zeroed [n_bags_bucket, L] host buffer fixing the output shape."""
        raise NotImplementedError

    def scatter_add(self, main, delta, o_shard, o_slot, d_shard,
                    d_slot, vals):
        """Donates (main, delta); returns (main, delta)."""
        raise NotImplementedError

    def set_rows(self, main, cache, delta, o_shard, o_slot, vals,
                 c_shard, c_slot):
        """Donates (main, cache, delta); returns the triple."""
        raise NotImplementedError

    def replica_create(self, main, cache, delta, o_shard, o_slot,
                       c_shard, c_slot):
        """Donates (cache, delta); returns (cache, delta)."""
        raise NotImplementedError

    def sync_replicas(self, main, cache, delta, r_shard, r_cslot,
                      o_shard, o_slot, threshold: float = 0.0,
                      compress: str = "off"):
        """One sync round. Donates (main, cache, delta). Returns the
        triple, plus the max-abs parked residual when `compress` is a
        wire mode (the EF audit scalar) — i.e. a 3- or 4-tuple."""
        raise NotImplementedError

    def read_rows_at(self, arr, sh, sl):
        raise NotImplementedError

    def install_rows(self, cache, delta, c_shard, c_slot, vals):
        """Donates (cache, delta); returns (cache, delta)."""
        raise NotImplementedError

    def refresh_after_sync(self, cache, delta, c_shard, c_slot, fresh,
                           shipped):
        """Donates (cache, delta); returns (cache, delta)."""
        raise NotImplementedError

    def relocate(self, main, delta, old_shard, old_slot, new_shard,
                 new_slot, rc_shard, rc_slot):
        """Donates (main, delta); returns (main, delta)."""
        raise NotImplementedError

    # -- tiered cold path + wire-row ingest (tier/, ops/dequant twins) -------

    def gather_cold(self, main, cache, delta, o_shard, o_row, c_shard,
                    c_slot, use_cache, cold_vals, use_cold):
        raise NotImplementedError

    def gather_cold_wire(self, mode: str, main, cache, delta, o_shard,
                         o_row, c_shard, c_slot, use_cache, cold_q,
                         cold_scale, use_cold):
        """Cold-miss gather with still-quantized cold rows (`mode` in
        fp16/int8); dequant fuses into the program."""
        raise NotImplementedError

    def gather_pool_cold(self, main, cache, delta, o_shard, o_row,
                         c_shard, c_slot, use_cache, cold_vals,
                         use_cold, seg, out, pooling="sum"):
        """`gather_pool` with the host-supplied cold-row override
        (`gather_cold` semantics for the member gather half)."""
        raise NotImplementedError

    def gather_pool_cold_wire(self, mode: str, main, cache, delta,
                              o_shard, o_row, c_shard, c_slot,
                              use_cache, cold_q, cold_scale, use_cold,
                              seg, out, pooling="sum"):
        """`gather_pool` over still-quantized cold rows (`mode` in
        fp16/int8): dequant AND pooling both fuse into one program."""
        raise NotImplementedError

    def write_main_rows(self, main, sh, row, vals):
        """Promotion upload (donates main; returns main)."""
        raise NotImplementedError

    def write_main_rows_wire(self, mode: str, main, sh, row, qvals,
                             scales=None):
        """Promotion upload from wire rows (donates main; returns
        main)."""
        raise NotImplementedError

    def clear_rows(self, arr, sh, sl):
        """Zero rows (donates arr; returns arr)."""
        raise NotImplementedError

    def install_cache_rows(self, cache, delta, c_shard, c_slot, vals,
                           resid=None):
        """Cold-owner sync refresh: install bases; zero the deltas, or
        park `resid` in them (EF loop). Donates (cache, delta)."""
        raise NotImplementedError

    # -- buffer allocation / transfer (donation-aware) -----------------------

    def alloc_pool(self, shape, dtype, sharding):
        """A zeroed device pool in `sharding` — the donated-chain root.
        Implementations must return a buffer that is SAFE to enter the
        donating program chain immediately (see launder)."""
        raise NotImplementedError

    def install_pool(self, arr, sharding):
        """Host array -> device pool, laundered for the donated chain
        (checkpoint restore)."""
        raise NotImplementedError

    def launder(self, x):
        """Bit-exact copy through a device program: a transfer-produced
        buffer must not enter the donated chain raw (r6 lesson)."""
        raise NotImplementedError

    def put_replicated(self, arr, sharding):
        """Stage a host array committed + replicated (the staging rule,
        docs/PERF.md)."""
        raise NotImplementedError

    def put_single(self, arr, device):
        """Host array -> one device (collective block staging)."""
        raise NotImplementedError

    # -- program construction ------------------------------------------------

    def compile(self, fn, **jit_kwargs):
        """Construct a device program from a traceable body (fused
        steps, app-scale fills). Accepts jax.jit keywords
        (donate_argnums, static_argnames, ...)."""
        raise NotImplementedError

    def compile_collective(self, fn, mesh, in_specs, out_specs):
        """Construct a per-shard collective program (shard_map + jit):
        `fn` runs per mesh shard with collective primitives available."""
        raise NotImplementedError


_default: Optional[DevicePort] = None


def default_port() -> DevicePort:
    """The process-wide port (one per process, like the dispatch gate:
    in-process device sets share one backend, so one port serves every
    server). Construction is lazy — importing the package never touches
    the device stack."""
    global _default
    if _default is None:
        from .jaxport import JaxDevicePort
        _default = JaxDevicePort()
    return _default


def set_default_port(port: Optional[DevicePort]) -> None:
    """Install a custom port (tests / alternative backends). None
    resets to lazy JaxDevicePort construction."""
    global _default
    _default = port
