"""Episodic execution (ISSUE 14 tentpole, half 2; GraphVite, PAPERS.md).

GraphVite's CPU-GPU hybrid structure applied to the PM's fused-step
path: the step stream is partitioned into **episodes** — consecutive
windows of step batches whose union working set is pinned device-hot as
a unit — and host-side preparation of episode N+1 overlaps device
compute of episode N:

    episode stream (`episode`, host prep, the caller thread):
        - resolve episode N+1's per-class key unions,
        - pin + promote its hot set through the EXISTING TierManager
          promotion path (intent-pinned rows first, then by decayed
          access score — the replacement signal residency.py already
          fuses; cold rows upload in the r13 STILL-QUANTIZED wire
          format through the port's `write_main_rows_wire` ingest),
        - pre-stage each batch's key upload (`prefetch_keys`);
    commit stream (`episode_commit`, an executor program):
        - run episode N's fused steps, in submission order, exactly as
          a sequential caller would.

At most ONE commit is in flight (the r11 `tier`/`tier_commit`
double-buffering, generalized): the driver submits commit N, preps
N+1 on its own thread (tracked as `episode`-stream occupancy for the
exec.overlap_fraction gauge), then joins commit N before submitting
N+1 — so nothing runs unboundedly ahead and the step order is the
SEQUENTIAL order.

Bit-identity (the tentpole contract, pinned by tests/test_episode.py's
storm): episodic execution changes WHEN values move — promotions are
bit-exact residency moves, key staging uploads raw keys, and the
runner's own RNG stream is consumed in step order because commits never
overlap each other — never WHAT a read returns. A server without the
tier (or a serialized/closing executor) degrades to inline prep +
inline commit: same results, no overlap.

Anti-thrash interaction (docs/MEMORY.md): prep promotes with
`force=False`, so episode N+1's working set can never evict episode
N's still-pinned rows; when the hot pool cannot hold both episodes the
surplus stays cold and the step's own forced pin covers it — slower,
never wrong.
"""
from __future__ import annotations

import time
from functools import partial
from typing import Dict, List, Optional, Sequence

import numpy as np


class Episode:
    """One episode: a window of step batches + its staged state."""

    __slots__ = ("index", "batches", "auxes")

    def __init__(self, index: int, batches, auxes):
        self.index = index
        self.batches = batches
        self.auxes = auxes


def plan_episodes(batches: Sequence[Dict[str, np.ndarray]],
                  auxes, episode_batches: int) -> List[Episode]:
    """Partition the step stream into consecutive windows of
    `episode_batches` batches. Order is preserved — the partition
    changes staging/pinning granularity, never step order."""
    assert episode_batches >= 1, "episode_batches must be >= 1"
    out = []
    for i, lo in enumerate(range(0, len(batches), episode_batches)):
        hi = lo + episode_batches
        out.append(Episode(i, list(batches[lo:hi]),
                           None if auxes is None else list(auxes[lo:hi])))
    return out


class EpisodicRunner:
    """Drives a fused-step runner (ops/fused.py DeviceRoutedRunner or
    FusedStepRunner) episodically. `run(batches, auxes, lr)` returns
    the per-step losses in step order, bit-identical to calling the
    runner sequentially on the same batches."""

    _COMMIT_TIMEOUT_S = 600.0

    def __init__(self, runner, episode_batches: Optional[int] = None):
        self.runner = runner
        self.server = runner.server
        srv = self.server
        eb = episode_batches or srv.opts.episode_batches
        # measured prep sizing (ISSUE 16; ops/costs.py): with an
        # attached kernel cost table and no explicit override, size the
        # window from the per-class measured gather costs — slow/wide
        # classes prep shorter episodes so host prep cannot outrun the
        # overlapped commit. An explicit episode_batches (arg or a
        # table-less server) keeps the static knob untouched.
        if episode_batches is None and getattr(srv, "costs",
                                               None) is not None:
            eb = srv.costs.suggest_episode_batches(
                eb, [st.value_length for st in srv.stores])
        self.episode_batches = int(eb)
        assert self.episode_batches >= 1
        # key staging is a DeviceRoutedRunner capability; the host-routed
        # FusedStepRunner still gets episodic pin/promote prep
        self._stage = getattr(runner, "prefetch_keys", None)
        self._staged_ok = self._stage is not None
        reg = srv.obs
        # shared=True: several runners may drive one server
        self._c_episodes = reg.counter("episode.episodes_total",
                                       shared=True)
        self._c_staged = reg.counter("episode.staged_batches_total",
                                     shared=True)
        self._c_pinned = reg.counter("episode.pinned_rows_total",
                                     shared=True)
        self._h_prep = reg.histogram("episode.prep_s", shared=True)
        self._h_commit = reg.histogram("episode.commit_s", shared=True)

    # -- prep (the `episode` stream) -----------------------------------------

    def _class_unions(self, ep: Episode) -> Dict[int, np.ndarray]:
        """Per-length-class union of the episode's keys (the episode's
        working set), via the runner's role->class map."""
        role_class = self.runner.role_class
        by_cid: Dict[int, list] = {}
        for b in ep.batches:
            for r, keys in b.items():
                k = np.asarray(keys, dtype=np.int64).ravel()
                if len(k):
                    by_cid.setdefault(role_class[r], []).append(k)
        return {cid: np.unique(np.concatenate(parts))
                for cid, parts in by_cid.items()}

    def _prep(self, ep: Episode):
        """Stage episode `ep` ahead of its commit: promote + pin its
        hot set (tiered servers) and pre-upload its key batches.
        Runs on the CALLER thread, tracked as `episode`-stream
        occupancy; takes the server lock only around the promotion
        enqueues (the lock-narrowing rule)."""
        srv = self.server
        t0 = time.perf_counter()
        with srv.exec.track("episode"):
            tier = srv.tier
            if tier is not None:
                end = tier.step_pin_end() + 1  # cover the whole window
                for cid, keys in self._class_unions(ep).items():
                    o_sh = srv.ab.owner[keys]
                    o_sl = srv.ab.slot[keys]
                    res = srv.stores[cid].res
                    m = o_sl >= 0  # process-local owners only
                    if not m.any():
                        continue
                    sh, sl = o_sh[m], o_sl[m]
                    # intent-pinned rows outrank score: promote them
                    # first so capacity bounding lands on the scored
                    # tail, not the declared-intent head (the
                    # residency.py replacement signal)
                    live = res.pin_until[sh, sl] >= \
                        tier._min_active_clock()
                    with srv._lock:
                        n = 0
                        if live.any():
                            n += tier.ensure_hot(cid, sh[live],
                                                 sl[live], pin_end=end)
                        rest = ~live
                        if rest.any():
                            order = np.argsort(
                                -res.score[sh[rest], sl[rest]],
                                kind="stable")
                            n += tier.ensure_hot(cid, sh[rest][order],
                                                 sl[rest][order],
                                                 pin_end=end)
                    if n:
                        self._c_pinned.inc(n)
            staged = None
            if self._staged_ok:
                staged = [self._stage(b) for b in ep.batches]
                self._c_staged.inc(len(staged))
        self._h_prep.observe(time.perf_counter() - t0)
        return staged

    # -- commit (the `episode_commit` stream) --------------------------------

    def _commit(self, ep: Episode, staged, lr: float, eps: float):
        """Run the episode's steps in order — exactly what a sequential
        caller would execute, staged key uploads aside."""
        t0 = time.perf_counter()
        losses = []
        for i, b in enumerate(ep.batches):
            aux = None if ep.auxes is None else ep.auxes[i]
            if staged is not None:
                losses.append(self.runner(b, aux, lr, eps,
                                          staged=staged[i]))
            else:
                losses.append(self.runner(b, aux, lr, eps))
        self._c_episodes.inc()
        self._h_commit.observe(time.perf_counter() - t0)
        return losses

    # -- the double-buffered driver ------------------------------------------

    def run(self, batches: Sequence[Dict[str, np.ndarray]], auxes=None,
            lr: float = 0.1, eps: float = 1e-10) -> list:
        """Train `batches` episodically. Returns the per-step losses
        (device scalars, step order). `auxes` is one aux pytree per
        batch or None."""
        if auxes is not None:
            assert len(auxes) == len(batches), "one aux per batch"
        episodes = plan_episodes(batches, auxes, self.episode_batches)
        if not episodes:
            return []
        srv = self.server
        ex = srv.exec
        # the r11 double-buffering precondition: a second worker must be
        # able to run the commit while this thread preps the next
        # episode; otherwise degrade to inline prep+commit (same
        # results, no overlap)
        pipelined = (not ex.single_stream and not ex.closed
                     and ex.max_workers >= 2)
        losses: list = []
        staged = self._prep(episodes[0])
        for i, ep in enumerate(episodes):
            cur = None
            if pipelined:
                cur = ex.submit("episode_commit",
                                partial(self._commit, ep, staged, lr,
                                        eps),
                                label="episode.commit")
            else:
                losses.extend(self._commit(ep, staged, lr, eps))
            # host prep of episode N+1 overlaps commit N's device work
            staged = self._prep(episodes[i + 1]) \
                if i + 1 < len(episodes) else None
            if cur is not None:
                got = cur.result(timeout=self._COMMIT_TIMEOUT_S)
                if got is None:  # cancelled by a racing executor close
                    raise RuntimeError(
                        "episodic commit cancelled: the executor closed "
                        "mid-run (server shutdown during training?)")
                losses.extend(got)
        return losses
