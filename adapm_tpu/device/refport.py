"""NumpyRefPort: a pure-NumPy DevicePort (ISSUE 16, tentpole half c).

The existence proof that the r17 DevicePort seam is honest: a complete
second backend that never imports jax — no jit, no device_put, no
sharding — yet runs the same stores, tier engine, serve plane, sync
rounds and episodic prep BIT-IDENTICALLY to `JaxDevicePort`
(`scripts/portdiff_check.py` drives a randomized multi-plane storm
against both ports and compares every read and the post-quiesce tables
bitwise). If a data-plane change leaks a jax-ism past the port surface,
this module stops compiling against it and the port-differential storm
fails loudly.

Semantics mirror device/jaxport.py program for program:

  - gathers with `mode="fill"` read 0 for any out-of-range (shard, slot)
    entry — the OOB padding sentinel is a huge positive int32, never
    negative (a negative index would WRAP, docs/MEMORY.md);
  - scatters with `mode="drop"` skip out-of-range entries; duplicate
    in-batch indices accumulate in BATCH ORDER via `np.add.at` — the
    same order the XLA scatter applies, the accumulation-order contract
    tier/coldpath.py documents (this is what makes the fused
    `gather_pool` family bit-identical across backends);
  - the compressed-sync wire math (fp16 cast, int8 symmetric grid
    through the f16 scale wire) reuses numpy's IEEE round-to-nearest-
    even casts, which match the XLA converts bit for bit — the same
    equivalence tier/quant.py's host twins already rely on;
  - "donated" pools are simply mutated in place and returned: donation
    means the caller must rebind and never reread the old reference,
    which an in-place numpy update satisfies trivially.

`compile` / `compile_collective` raise: the reference port is a data-
plane backend (stores, tier, serve, sync), not a program compiler —
fused-step runners and device collectives stay jax-only, and nothing in
the port-differential storm needs them.
"""
from __future__ import annotations

import numpy as np

# duplicated from device/jaxport.py on purpose: importing it would pull
# jax into this module, and "imports no jax" is the point (asserted by
# scripts/portdiff_check.py)
OOB = np.int32(2**31 - 2)
F16_MAX = 65504.0

from .port import DevicePort  # noqa: E402


def _valid(arr, sh, sl):
    """In-range mask for (shard, slot) index pairs against pool `arr`
    ([S, R, L]). Matches jax's fill/drop modes: ANY out-of-range
    coordinate disqualifies the entry."""
    sh = np.asarray(sh)
    sl = np.asarray(sl)
    return ((sh >= 0) & (sh < arr.shape[0])
            & (sl >= 0) & (sl < arr.shape[1]))


def _fill_gather(arr, sh, sl):
    """`arr.at[sh, sl].get(mode="fill", fill_value=0)`."""
    sh = np.asarray(sh)
    sl = np.asarray(sl)
    m = _valid(arr, sh, sl)
    out = np.zeros((len(sh), arr.shape[-1]), arr.dtype)
    if m.any():
        out[m] = arr[sh[m], sl[m]]
    return out


def _drop_add(arr, sh, sl, vals):
    """`arr.at[sh, sl].add(vals, mode="drop")` in place — duplicates
    accumulate in batch order (np.add.at)."""
    sh = np.asarray(sh)
    sl = np.asarray(sl)
    m = _valid(arr, sh, sl)
    if m.any():
        np.add.at(arr, (sh[m], sl[m]), np.asarray(vals)[m])


def _drop_set(arr, sh, sl, vals):
    """`arr.at[sh, sl].set(vals, mode="drop")` in place."""
    sh = np.asarray(sh)
    sl = np.asarray(sl)
    m = _valid(arr, sh, sl)
    if m.any():
        arr[sh[m], sl[m]] = np.asarray(vals)[m]


def _pool_rows_host(rows, seg, out, pooling):
    """The host twin of jaxport._pool_rows: batch-order segment sum
    (np.add.at), one division for mean. `out` is consumed (mutated and
    returned) — callers pass a fresh zeroed buffer per dispatch."""
    seg = np.asarray(seg)
    m = (seg >= 0) & (seg < out.shape[0])
    np.add.at(out, seg[m], np.asarray(rows)[m])
    if pooling == "sum":
        return out
    cnt = np.zeros(out.shape[0], rows.dtype)
    np.add.at(cnt, seg[m], rows.dtype.type(1))
    denom = np.where(cnt > 0, cnt, rows.dtype.type(1))[:, None]
    return np.where(cnt[:, None] > 0, out / denom, np.zeros_like(out))


class NumpyRefPort(DevicePort):
    """The pure-NumPy reference DevicePort (module docstring). Install
    with `device.set_default_port(NumpyRefPort())` BEFORE any Server is
    built; every store then runs host-side."""

    name = "numpy-ref"

    def __init__(self):
        # same lock-free liveness-counter convention as JaxDevicePort
        self.programs = 0
        self.wire_ingest_rows = 0

    def stats(self) -> dict:
        return {"backend": self.name,
                "programs_total": int(self.programs),
                "wire_ingest_rows_total": int(self.wire_ingest_rows)}

    # -- data-plane programs -------------------------------------------------

    @staticmethod
    def _gather_rows(main, cache, delta, o_shard, o_slot, c_shard,
                     c_slot, use_cache):
        m = _fill_gather(main, o_shard, o_slot)
        c = (_fill_gather(cache, c_shard, c_slot)
             + _fill_gather(delta, c_shard, c_slot))
        return np.where(np.asarray(use_cache)[:, None], c, m)

    def gather(self, main, cache, delta, o_shard, o_slot, c_shard,
               c_slot, use_cache):
        self.programs += 1
        return self._gather_rows(main, cache, delta, o_shard, o_slot,
                                 c_shard, c_slot, use_cache)

    def gather_pool(self, main, cache, delta, o_shard, o_slot, c_shard,
                    c_slot, use_cache, seg, out, pooling="sum"):
        self.programs += 1
        rows = self._gather_rows(main, cache, delta, o_shard, o_slot,
                                 c_shard, c_slot, use_cache)
        return _pool_rows_host(rows, seg, np.array(out, copy=True),
                               pooling)

    def scatter_add(self, main, delta, o_shard, o_slot, d_shard,
                    d_slot, vals):
        self.programs += 1
        _drop_add(main, o_shard, o_slot, vals)
        _drop_add(delta, d_shard, d_slot, vals)
        return main, delta

    def set_rows(self, main, cache, delta, o_shard, o_slot, vals,
                 c_shard, c_slot):
        self.programs += 1
        _drop_set(main, o_shard, o_slot, vals)
        _drop_set(cache, c_shard, c_slot, vals)
        _drop_set(delta, c_shard, c_slot, np.zeros_like(vals))
        return main, cache, delta

    def replica_create(self, main, cache, delta, o_shard, o_slot,
                       c_shard, c_slot):
        self.programs += 1
        rows = _fill_gather(main, o_shard, o_slot)
        _drop_set(cache, c_shard, c_slot, rows)
        _drop_set(delta, c_shard, c_slot, np.zeros_like(rows))
        return cache, delta

    def sync_replicas(self, main, cache, delta, r_shard, r_cslot,
                      o_shard, o_slot, threshold: float = 0.0,
                      compress: str = "off"):
        self.programs += 1
        if compress != "off":
            return self._sync_compressed(main, cache, delta, r_shard,
                                         r_cslot, o_shard, o_slot,
                                         threshold, compress)
        dvals = _fill_gather(delta, r_shard, r_cslot)
        rs, osl = np.asarray(r_cslot), np.asarray(o_slot)
        if threshold > 0.0:
            ship = np.max(np.abs(dvals), axis=1) >= \
                main.dtype.type(threshold)
            rs = np.where(ship, rs, OOB)
            osl = np.where(ship, osl, OOB)
        _drop_add(main, o_shard, osl, dvals)
        fresh = _fill_gather(main, o_shard, osl)
        _drop_set(cache, r_shard, rs, fresh)
        _drop_set(delta, r_shard, rs, np.zeros_like(fresh))
        return main, cache, delta

    def _sync_compressed(self, main, cache, delta, r_shard, r_cslot,
                         o_shard, o_slot, threshold, mode):
        # the host twin of _sync_replicas_compressed, op for op: clip
        # before any f16 cast (inf guard), park the quantization
        # remainder in the delta row (EF loop), held rows keep their
        # full delta
        dvals = _fill_gather(delta, r_shard, r_cslot)
        thr = main.dtype.type(threshold)
        ship = np.max(np.abs(dvals), axis=1) >= thr
        if mode == "fp16":
            shipped = np.clip(dvals, -F16_MAX, F16_MAX).astype(
                np.float16).astype(dvals.dtype)
        else:  # int8, symmetric per-row scale through the f16 wire
            s = np.clip(np.max(np.abs(dvals), axis=1) / 127.0,
                        0.0, F16_MAX).astype(np.float16).astype(
                            dvals.dtype)
            safe = np.where(s > 0, s, dvals.dtype.type(1.0))
            q = np.clip(np.round(dvals / safe[:, None]), -127, 127)
            shipped = q.astype(np.int8).astype(dvals.dtype) * s[:, None]
        resid = dvals - shipped
        rs = np.where(ship, np.asarray(r_cslot), OOB)
        osl = np.where(ship, np.asarray(o_slot), OOB)
        _drop_add(main, o_shard, osl, shipped)
        fresh = _fill_gather(main, o_shard, osl)
        _drop_set(cache, r_shard, rs, fresh)
        new_delta = np.where(ship[:, None], resid, dvals)
        _drop_set(delta, r_shard, r_cslot, new_delta)
        resid_norm = np.max(np.where(ship[:, None], np.abs(resid),
                                     dvals.dtype.type(0.0)))
        return main, cache, delta, resid_norm

    def read_rows_at(self, arr, sh, sl):
        self.programs += 1
        return _fill_gather(arr, sh, sl)

    def install_rows(self, cache, delta, c_shard, c_slot, vals):
        self.programs += 1
        _drop_set(cache, c_shard, c_slot, vals)
        _drop_set(delta, c_shard, c_slot, np.zeros_like(vals))
        return cache, delta

    def refresh_after_sync(self, cache, delta, c_shard, c_slot, fresh,
                           shipped):
        self.programs += 1
        _drop_set(cache, c_shard, c_slot, fresh)
        _drop_add(delta, c_shard, c_slot, -np.asarray(shipped))
        return cache, delta

    def relocate(self, main, delta, old_shard, old_slot, new_shard,
                 new_slot, rc_shard, rc_slot):
        self.programs += 1
        # all gathers before all scatters (intra-batch slot reuse)
        rows = _fill_gather(main, old_shard, old_slot)
        rows = rows + _fill_gather(delta, rc_shard, rc_slot)
        _drop_set(main, new_shard, new_slot, rows)
        _drop_set(delta, rc_shard, rc_slot, np.zeros_like(rows))
        return main, delta

    # -- tiered cold path + wire ingest --------------------------------------

    def _gather_cold_rows(self, main, cache, delta, o_shard, o_row,
                          c_shard, c_slot, use_cache, cold_vals,
                          use_cold):
        m = _fill_gather(main, o_shard, o_row)
        m = np.where(np.asarray(use_cold)[:, None],
                     np.asarray(cold_vals), m)
        c = (_fill_gather(cache, c_shard, c_slot)
             + _fill_gather(delta, c_shard, c_slot))
        return np.where(np.asarray(use_cache)[:, None], c, m)

    def gather_cold(self, main, cache, delta, o_shard, o_row, c_shard,
                    c_slot, use_cache, cold_vals, use_cold):
        self.programs += 1
        return self._gather_cold_rows(main, cache, delta, o_shard,
                                      o_row, c_shard, c_slot,
                                      use_cache, cold_vals, use_cold)

    @staticmethod
    def _dequant_wire(mode, main, cold_q, cold_scale):
        if mode == "fp16":
            return np.asarray(cold_q).astype(main.dtype)
        return (np.asarray(cold_q).astype(main.dtype)
                * np.asarray(cold_scale)[:, None])

    def gather_cold_wire(self, mode: str, main, cache, delta, o_shard,
                         o_row, c_shard, c_slot, use_cache, cold_q,
                         cold_scale, use_cold):
        self.programs += 1
        self.wire_ingest_rows += int(np.count_nonzero(
            np.asarray(use_cold)))
        deq = self._dequant_wire(mode, main, cold_q, cold_scale)
        return self._gather_cold_rows(main, cache, delta, o_shard,
                                      o_row, c_shard, c_slot,
                                      use_cache, deq, use_cold)

    def gather_pool_cold(self, main, cache, delta, o_shard, o_row,
                         c_shard, c_slot, use_cache, cold_vals,
                         use_cold, seg, out, pooling="sum"):
        self.programs += 1
        rows = self._gather_cold_rows(main, cache, delta, o_shard,
                                      o_row, c_shard, c_slot,
                                      use_cache, cold_vals, use_cold)
        return _pool_rows_host(rows, seg, np.array(out, copy=True),
                               pooling)

    def gather_pool_cold_wire(self, mode: str, main, cache, delta,
                              o_shard, o_row, c_shard, c_slot,
                              use_cache, cold_q, cold_scale, use_cold,
                              seg, out, pooling="sum"):
        self.programs += 1
        self.wire_ingest_rows += int(np.count_nonzero(
            np.asarray(use_cold)))
        deq = self._dequant_wire(mode, main, cold_q, cold_scale)
        rows = self._gather_cold_rows(main, cache, delta, o_shard,
                                      o_row, c_shard, c_slot,
                                      use_cache, deq, use_cold)
        return _pool_rows_host(rows, seg, np.array(out, copy=True),
                               pooling)

    def write_main_rows(self, main, sh, row, vals):
        self.programs += 1
        _drop_set(main, sh, row, vals)
        return main

    def write_main_rows_wire(self, mode: str, main, sh, row, qvals,
                             scales=None):
        self.programs += 1
        self.wire_ingest_rows += int(np.count_nonzero(
            np.asarray(row) != OOB))
        _drop_set(main, sh, row,
                  self._dequant_wire(mode, main, qvals, scales))
        return main

    def clear_rows(self, arr, sh, sl):
        self.programs += 1
        sh = np.asarray(sh)
        _drop_set(arr, sh, sl,
                  np.zeros((len(sh), arr.shape[-1]), arr.dtype))
        return arr

    def install_cache_rows(self, cache, delta, c_shard, c_slot, vals,
                           resid=None):
        self.programs += 1
        _drop_set(cache, c_shard, c_slot, vals)
        _drop_set(delta, c_shard, c_slot,
                  np.zeros_like(np.asarray(vals))
                  if resid is None else resid)
        return cache, delta

    # -- buffer allocation / transfer ----------------------------------------

    def alloc_pool(self, shape, dtype, sharding):
        # host pool: the sharding argument is a placement hint this
        # backend has no devices to honor
        return np.zeros(shape, dtype)

    def install_pool(self, arr, sharding):
        return np.array(arr, copy=True)

    def launder(self, x):
        self.programs += 1
        return np.array(x, copy=True)

    def put_replicated(self, arr, sharding):
        return np.asarray(arr)

    def put_single(self, arr, device):
        return np.asarray(arr)

    # -- program construction ------------------------------------------------

    def compile(self, fn, **jit_kwargs):
        raise NotImplementedError(
            "NumpyRefPort is a data-plane reference backend; fused-step "
            "program compilation is jax-only (use JaxDevicePort)")

    def compile_collective(self, fn, mesh, in_specs, out_specs):
        raise NotImplementedError(
            "NumpyRefPort has no collective backend (single-process "
            "data plane only)")
