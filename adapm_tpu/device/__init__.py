"""The episodic device plane (ISSUE 14; ROADMAP item 4).

    port.py    — the narrow DevicePort protocol: gather/scatter/
                 fused-step/collective program construction, donation-
                 aware buffer alloc, quantized wire-row ingest. ONE
                 port implementation per accelerator backend; the rest
                 of the tree never touches jax.jit/device_put directly
                 (adapm-lint APM008: device-API confinement).
    jaxport.py — JaxDevicePort, the shipping jax/XLA implementation
                 (every jitted data-plane program lives here).
    episode.py — episodic execution (GraphVite-style): partition the
                 step stream into episodes, pin an episode's hot set
                 via the tier promotion path, and double-buffer host
                 prep of episode N+1 against device compute of episode
                 N on the `episode`/`episode_commit` executor streams.

`default_port()` is the process-wide port (lazy; importing the package
never initializes the device stack).
"""
from __future__ import annotations

from .port import DevicePort, default_port, set_default_port  # noqa: F401


def _jax_symbols():
    from . import jaxport
    return jaxport


def __getattr__(name):
    # lazy re-exports: OOB/F16_MAX and the concrete port class live in
    # jaxport, which imports jax — keep `import adapm_tpu.device` cheap
    if name in ("OOB", "F16_MAX", "JaxDevicePort"):
        return getattr(_jax_symbols(), name)
    if name in ("EpisodicRunner", "plan_episodes"):
        from . import episode
        return getattr(episode, name)
    raise AttributeError(name)
