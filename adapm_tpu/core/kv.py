"""The user-facing parameter-manager API: Server + Worker.

API parity with the reference's ColoKVServer / ColoKVWorker
(include/ps/coloc_kv_server.h, include/ps/coloc_kv_worker.h): Pull / Push /
Set / PullIfLocal / Intent / PrepareSample / PullSample / Wait / WaitAll /
WaitSync / IsFinished / advanceClock / Barrier / BeginSetup / EndSetup /
Finalize, with the reference's async contract: ops return a timestamp,
`Wait(ts)` blocks, and `-1` means "answered entirely locally, nothing to wait
for" (coloc_kv_worker.h:120-186).

Design notes (see ARCHITECTURE.md):
  - Workers are logical application threads mapped onto mesh devices
    (worker w -> shard w % S), mirroring the reference's co-located
    worker/server process model.
  - Values are flat float buffers with per-key lengths (reference per-key
    `value_lengths`, coloc_kv_server.h:76); uniform-length calls may pass/get
    2-D [B, L] arrays.
  - The async contract maps onto JAX's async dispatch: an op enqueues device
    programs and returns; Wait materializes results (device->host copy for
    pulls, block_until_ready for pushes).
  - A single coarse lock serializes table+pool mutation (the reference's
    16384-mutex array is unnecessary: ops are batched programs, not per-key
    critical sections).
"""
from __future__ import annotations

import contextlib
import threading
import time as _time
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import numpy as np

from ..base import CLOCK_MAX, LOCAL, WORKER_FINISHED, MgmtTechniques
from ..config import SystemOptions
from ..exec.executor import dispatch_gate
from ..obs.spans import NULL_SPAN
from ..parallel.mesh import MeshContext, get_mesh_context
from .addressbook import Addressbook
from .store import OOB, ShardedStore
from .sync import SyncManager


class _WaitEntry:
    __slots__ = ("groups", "out", "is_write", "keys", "remote", "futures")

    def __init__(self, groups=None, out=None, is_write=False, keys=None,
                 remote=None, futures=None):
        # groups: list of (class_id, row_positions, key_lengths_slice,
        #                  device_vals, n)
        self.groups = groups or []
        self.out = out
        self.is_write = is_write  # push/set: wait = block on current pools
        self.keys = keys
        self.remote = remote      # (positions, Future) for cross-process keys
        self.futures = futures or []  # outstanding cross-process writes


class _TopoHandle:
    """Yielded by Server._topology_mutation; cancel() marks a section
    that mutated nothing (exit then skips the version bump)."""

    __slots__ = ("cancelled",)

    def __init__(self):
        self.cancelled = False

    def cancel(self):
        self.cancelled = True


class Server:
    """Owns the sharded pools, addressbook, planner, and worker registry.

    Reference ColoKVServer (coloc_kv_server.h:58-354). `value_lengths` may be
    a scalar (uniform) or a per-key array; keys are grouped into length
    classes, each with its own pooled store.
    """

    def __init__(self, num_keys: int,
                 value_lengths: Union[int, Sequence[int]],
                 opts: Optional[SystemOptions] = None,
                 ctx: Optional[MeshContext] = None,
                 num_workers: Optional[int] = None,
                 dtype=None, net_node=None):
        import jax.numpy as jnp
        self.opts = opts or SystemOptions()
        self.ctx = ctx or get_mesh_context()
        self.num_keys = int(num_keys)
        self.dtype = dtype or jnp.float32

        lens = np.asarray(value_lengths)
        if lens.ndim == 0:
            lens = np.full(self.num_keys, int(lens), dtype=np.int64)
        assert len(lens) == self.num_keys
        self.value_lengths = lens.astype(np.int64)
        self.val_offsets = np.zeros(self.num_keys + 1, dtype=np.int64)
        np.cumsum(self.value_lengths, out=self.val_offsets[1:])

        # length classes (vectorized: uniq is sorted, so searchsorted is the
        # length -> class map)
        uniq = np.unique(self.value_lengths)
        self.class_lengths = [int(u) for u in uniq]
        key_class = np.searchsorted(uniq, self.value_lengths).astype(np.int32)
        class_counts = np.bincount(key_class, minlength=len(uniq))

        # identity comes from the net node when one is injected (a
        # LoopbackNode gives each in-process "node" its own rank; the
        # default None -> DcnNode inside GlobalPM = the jax.distributed
        # control plane, byte-identical to pre-NetPort behavior)
        self._net_node = net_node
        if net_node is not None:
            self.num_procs = int(net_node.num_procs)
            self.pid = int(net_node.pid)
        else:
            from ..parallel import control
            self.num_procs = control.num_processes()
            self.pid = control.process_id()

        # unified telemetry (adapm_tpu/obs; docs/OBSERVABILITY.md): the
        # metrics registry every subsystem below reports into, the
        # optional span tracer, and crash dumps. Built FIRST so
        # SyncManager / PlanCache / PrefetchScheduler / GlobalPM can
        # register their metrics at construction.
        from ..obs import metrics as _obs_metrics
        self.obs = _obs_metrics.MetricsRegistry(enabled=self.opts.metrics)
        _obs_metrics.set_global_registry(self.obs)
        self.spans = None
        self.crash_dump_path = None
        bc_path = ring_path = None
        if self.opts.crash_dumps:
            from ..obs.crash import enable_crash_dumps
            try:
                self.crash_dump_path, bc_path, ring_path = \
                    enable_crash_dumps(self.pid, self.opts.stats_out)
            except OSError:  # unwritable dump dir must not block startup
                bc_path = ring_path = None
        if self.opts.trace_spans:
            from ..obs.spans import SpanTracer
            self.spans = SpanTracer(
                rank=self.pid, breadcrumb_path=bc_path,
                max_events=self.opts.trace_spans_max_events,
                registry=self.obs)
        # request-flight tracing (ISSUE 7 tentpole; obs/flight.py):
        # per-request causal traces across admission -> batch ->
        # executor program -> reply, exported as Perfetto flow events.
        # Default off — when None every instrumented site pays one
        # `is None` check (the r7 skip-wrapper discipline) and the
        # registry holds zero flight.* names.
        self.flight = None
        if self.opts.trace_flight:
            from ..obs.flight import FlightTracer
            self.flight = FlightTracer(
                registry=self.obs, rank=self.pid,
                freshness_bound=self.opts.flight_freshness_samples)
        # workload trace capture (ISSUE 15 tentpole; obs/wtrace.py,
        # docs/REPLAY.md): the semantic op stream recorded to a
        # versioned, checksummed .wtrace file for the offline replay
        # engine (adapm_tpu/replay). Default off — when None every
        # instrumented site pays one `is None` check (the r7 skip-
        # wrapper discipline) and the registry holds zero wtrace.*
        # names.
        self.wtrace = None
        if self.opts.trace_workload:
            from ..obs.wtrace import WorkloadTraceRecorder
            self.wtrace = WorkloadTraceRecorder(
                self, self.opts.trace_workload,
                key_budget=self.opts.trace_workload_keys)
        # decision telemetry capture (ISSUE 17 tentpole;
        # obs/decisions.py): every adaptive decision with its
        # at-decision feature vector + bounded outcome attribution,
        # recorded to a versioned, checksummed .dtrace file. Default
        # off — when None every instrumented site pays one `is None`
        # check (the r7 skip-wrapper discipline) and the registry
        # holds zero decision.* names.
        self.decisions = None
        if self.opts.trace_decisions:
            from ..obs.decisions import DecisionRecorder
            self.decisions = DecisionRecorder(
                self, self.opts.trace_decisions,
                follow_events=self.opts.trace_decisions_window)
        # learned adaptive-policy plane (ISSUE 18 tentpole;
        # adapm_tpu/policy): per-plane trained regret scorers that may
        # VETO a heuristic decision (--sys.policy.<plane> learned) or
        # shadow-score it without applying (--sys.policy.shadow).
        # Default off — when None every hook site pays one `is None`
        # check (the r7 skip-wrapper discipline) and the registry
        # holds zero policy.* names. A corrupt/incompatible artifact
        # raises the named PolicyError HERE, before any plane consults
        # it.
        self.policy = None
        if self.opts.policy_file:
            from ..policy.runtime import PolicyPlane
            self.policy = PolicyPlane(self)
        # populated by a ReplayEngine that drove this server (the
        # snapshot's always-present `replay` section; schema v11)
        self.replay_stats: Optional[Dict] = None
        # executor flight-recorder ring (rides --sys.crash_dumps): the
        # last K executor programs per stream, mirrored into a ring
        # file, so a hard abort's post-mortem says what was in flight.
        # Per PROGRAM — independent of --sys.trace.flight, never on the
        # per-op hot path.
        self.flight_recorder = None
        if self.opts.crash_dumps:
            from ..obs.flight import FlightRecorder
            self.flight_recorder = FlightRecorder(path=ring_path)
        # fault-injection plane (ISSUE 10 tentpole; adapm_tpu/fault):
        # None unless --sys.fault.spec names points — the r7 skip-
        # wrapper discipline: off costs one `is None` check per
        # instrumented site and zero fault.* registry names (pinned by
        # scripts/metrics_overhead_check.py)
        self.fault = None
        if self.opts.fault_spec:
            from ..fault.inject import FaultPlane
            self.fault = FaultPlane(self.opts.fault_spec,
                                    seed=self.opts.fault_seed,
                                    registry=self.obs)
        # executor error policy (fault/policy.py): bounded retry +
        # exponential backoff for TRANSIENT program failures. Built
        # unconditionally — the default classifier matches only
        # TransientFaultError, so with nothing raising it the policy
        # is inert and executor behavior is byte-identical to pre-PR.
        from ..fault.policy import RetryPolicy
        self._retry_policy = RetryPolicy(
            max_retries=self.opts.fault_retries,
            backoff_base_s=self.opts.fault_backoff_ms * 1e-3,
            backoff_max_s=self.opts.fault_backoff_max_ms * 1e-3)
        # degraded readiness (ISSUE 10): set while a checkpoint-chain
        # restore applies (fault/ckpt.py restore_chain) — the serve
        # plane sheds loudly with ServeDegradedError instead of
        # risking a read that mixes pre- and post-restore bits
        self._degraded_reason: Optional[str] = None
        self._last_recovery_s: Optional[float] = None
        # unified async executor (ISSUE 6 tentpole; adapm_tpu/exec,
        # docs/EXECUTOR.md): THE ordered-stream dispatch plane under
        # sync rounds, prefetch staging, tier maintenance, serve
        # batching, and fused steps. Built right after the registry so
        # every subsystem below can submit from construction; closed
        # LAST in shutdown(), after every producer is stopped.
        from ..exec import AsyncExecutor
        self.exec = AsyncExecutor(registry=self.obs,
                                  workers=self.opts.exec_workers,
                                  single_stream=self.opts.exec_single_stream,
                                  recorder=self.flight_recorder,
                                  retry_policy=self._retry_policy,
                                  fault=self.fault)

        # kv-layer metrics: per-op latency histograms live on the
        # workers (kv.pull_s/push_s/set_s, shared); registry-side extras:
        self._c_topo_bumps = self.obs.counter("kv.topology_bumps")
        self.obs.gauge("kv.topology_version",
                       fn=lambda: self.topology_version)
        self.obs.gauge("kv.workers", fn=lambda: len(self._workers))
        # collective wait-time histograms, observed by the (server-less)
        # control plane via observe_global (parallel/control.py) and by
        # Server.barrier below
        self.obs.histogram("collective.barrier_wait_s")
        self.obs.histogram("collective.allreduce_wait_s")

        self.stores: List[ShardedStore] = []
        for cid, L in enumerate(self.class_lengths):
            cache_slots = self.opts.cache_slots_per_shard
            if cache_slots == 0 and self.num_procs > 1:
                # multi-process auto default: data-parallel workloads
                # contest keys across processes, so give each shard 2x the
                # per-shard fair share (bounded by the class size). At
                # memory-bound scale tune --sys.cache_slots explicitly —
                # ensure_local raises with that hint when the pool is the
                # limit; expired replicas are dropped to make room first.
                fair = -(-int(class_counts[cid]) // self.ctx.num_shards)
                cache_slots = min(2 * fair, int(class_counts[cid]))
            self.stores.append(ShardedStore(
                int(class_counts[cid]), L, self.ctx, dtype=self.dtype,
                over_alloc=self.opts.main_over_alloc,
                cache_slots_per_shard=cache_slots,
                bucket_min=self.opts.remote_bucket_min,
                tier_hot_rows=(self.opts.tier_hot_rows
                               if self.opts.tier else 0),
                tier_cold_dtype=(self.opts.tier_cold_dtype
                                 if self.opts.tier else "fp32")))
        # device-plane accounting (ISSUE 14; schema v10): the stores
        # share one process-wide DevicePort — surface its program /
        # wire-ingest counters. shared=True: several servers in one
        # process read the same port.
        if self.obs.enabled and self.stores:
            _port = self.stores[0].port
            self.obs.gauge("device.programs_total", shared=True,
                           fn=lambda p=_port: p.programs)
            self.obs.gauge("device.wire_ingest_rows_total", shared=True,
                           fn=lambda p=_port: p.wire_ingest_rows)

        self.ab = Addressbook(
            key_class, self.ctx.num_shards,
            [s.main_slots for s in self.stores],
            [s.cache_slots for s in self.stores],
            num_procs=self.num_procs, pid=self.pid)

        # addressbook-mutation discipline (ADVICE r5 #1): every counted
        # ab mutation must happen inside _topology_mutation(), which
        # bumps topology_version as the LAST step of the critical
        # section and acknowledges the count here
        self._ab_mut_acked = self.ab.mutations

        self.num_shards = self.ctx.num_shards
        # explicit num_workers DECLARES the worker set (reference
        # Setup(num_keys, num_threads)): worker barriers then rendezvous
        # over all declared ids, so an early barrier cannot slip past
        # workers whose threads have not registered yet
        self._wb_declared = num_workers is not None
        self.max_workers = num_workers or max(self.num_shards, 1)
        self._workers: Dict[int, "Worker"] = {}
        self._clocks = np.zeros(self.max_workers, dtype=np.int64)
        self._lock = threading.RLock()
        # serializes sync ROUNDS (planner) without holding _lock across DCN
        # round-trips — see parallel/pm.py locking discipline. Reentrant:
        # run_round acquires it itself (the prefetch pipeline drives
        # rounds from a background thread, so bare run_round calls from
        # tests/benches must self-serialize), and wait_sync/quiesce wrap
        # it around multi-call sequences.
        self._round_lock = threading.RLock()
        if self.opts.lint_lockorder:
            # runtime lock-order sentinel (ISSUE 11; lint/lockorder.py,
            # docs/INVARIANTS.md): record this server's lock
            # acquisitions in the process-wide graph — a cycle or a
            # lock taken under the dispatch gate raises LockOrderError
            # at the acquire, deterministically, instead of waiting for
            # a storm to actually deadlock. Off (the default) builds
            # the plain RLocks above: zero wrapper anywhere hot.
            from ..lint import lockorder
            lockorder.enable_sentinel()
            self._lock = lockorder.SentinelLock("server", self._lock)
            self._round_lock = lockorder.SentinelLock(
                "sync_round", self._round_lock)
            self.obs._lock = lockorder.SentinelLock(
                "metrics_registry", self.obs._lock)
        self._in_setup = False
        # worker-thread barrier state (reference ColoKVWorker::Barrier is a
        # barrier over ALL workers, threads included, via the scheduler's
        # BARRIER counting — src/postoffice.cc:149-174): generation counter
        # + the set of arrived worker ids; see worker_barrier()
        self._wb_cond = threading.Condition()
        self._wb_waiting: set = set()
        self._wb_gen = 0        # generation currently accepting arrivals
        self._wb_done = 0       # generations fully completed
        self._wb_leading = False
        self._wb_errs: Dict[int, BaseException] = {}  # gen -> leader error
        # bumped whenever placement changes (replica add/drop, relocation);
        # consumers (LocalSampling) use it to invalidate local-key caches
        self.topology_version = 0

        self.sync = SyncManager(self, self.opts)
        self._sync_thread: Optional[threading.Thread] = None
        self._sync_stop = threading.Event()

        # tiered parameter storage (ISSUE 5 tentpole; adapm_tpu/tier,
        # docs/MEMORY.md): device-hot / host-cold main-row residency
        # with intent-driven promotion. None when --sys.tier is off —
        # the stores are then plain device pools, zero tier overhead.
        self.tier = None
        if self.opts.tier:
            self.opts.validate_serve()  # tier knob ranges (parse-time
            # validation is skipped for hand-built SystemOptions)
            from ..tier.residency import TierManager
            self.tier = TierManager(self, self.opts)

        # measured kernel cost table (ISSUE 16; ops/costs.py): attached
        # when --sys.costs.table names a JSON table. calibrate=1
        # measures on THIS server's live stores and persists; otherwise
        # a missing/unreadable file just means no table (the built-in
        # dispatch preferences apply — a measured table can only ever
        # refine the choice, never be required). Consulted by the serve
        # batcher's bag dispatch and the episodic prep sizing.
        self.costs = None
        if self.opts.costs_table:
            from ..ops.costs import KernelCostTable, calibrate_server
            if self.opts.costs_calibrate:
                self.costs = calibrate_server(self)
                self.costs.save(self.opts.costs_table)
            else:
                try:
                    self.costs = KernelCostTable.load(
                        self.opts.costs_table)
                except OSError:
                    self.costs = None
            if self.costs is not None:
                self.costs.bind_metrics(self.obs)

        # routing-plan cache + intent-driven prefetch pipeline (the hot
        # Pull/Push path levers; core/intent.py). Both revalidate against
        # topology_version, i.e. they depend on the _topology_mutation
        # discipline above.
        from .intent import PlanCache, PrefetchScheduler
        self._plan_cache = PlanCache(self.opts.plan_cache_entries,
                                     registry=self.obs) \
            if self.opts.plan_cache_entries > 0 else None
        self.prefetch = PrefetchScheduler(self, self.opts) \
            if self.opts.prefetch else None

        # debug: per-key additive-apply counter (ADAPM_DEBUG_APPLIES=1);
        # diagnostics only — see tests/mp_bisect.py
        import os as _os
        self._dbg_applies = np.zeros(self.num_keys) \
            if _os.environ.get("ADAPM_DEBUG_APPLIES") else None

        # cross-process layer: N launched processes form one PM
        # (parallel/pm.py; reference van/postoffice data plane)
        self.glob = None
        # outstanding remote writes (future, keys): replication of a key
        # with an in-flight remote write is deferred — the owner's base
        # snapshot might miss the write, breaking read-your-own-pushes
        # (pm.py _install_replicas)
        self._rw_pending: List = []
        # transport-plane stats surface (net/membership.py): None on
        # single-process AND dcn servers — the snapshot `net` section
        # and net.* registry names exist only when a loopback/tcp node
        # is attached (metrics_overhead_check.py pins default-off)
        self.net = None
        if self.num_procs > 1:
            from ..parallel.pm import GlobalPM
            self.glob = GlobalPM(self, node=self._net_node)
            node = self.glob.node
            if hasattr(node, "bind"):
                # loopback: attach the executor + fault plane to the
                # port and start the membership beat thread
                node.bind(self)
            self.net = node.net_plane()
            if self.opts.heartbeat_s > 0:
                node.start_heartbeat(self.opts.heartbeat_s)

        self.sampling = None  # set by enable_sampling_support
        self._shutdown_done = False  # shutdown() is idempotent
        # online serving plane (adapm_tpu/serve): attached by
        # ServePlane.__init__ so metrics_snapshot can fold readiness in
        # and shutdown can close it; None until a plane is built
        self._serve_plane = None

        # streaming plane (ISSUE 20 tentpole; adapm_tpu/stream,
        # docs/STREAMING.md): the acked-event cursor + ingest
        # accounting + the FreshnessSLO controller closing the loop on
        # event-to-servable staleness. None unless a --sys.stream.*
        # knob is set — the r7 skip-wrapper discipline: off costs one
        # `is None` check per integration site and zero stream.*
        # registry names (scripts/metrics_overhead_check.py pins it).
        # Built AFTER the sync manager (the controller's first lever)
        # and the executor (the controller tick + trainer pump run on
        # it); started here so a freshness target begins steering
        # without any further wiring.
        self.stream = None
        # cursor recovered from a checkpoint chain that carried
        # aux_stream_cursor (fault/ckpt.py restore_chain); also applied
        # to self.stream.cursor when the plane exists — kept as a
        # separate field so a restore into a plane-less server still
        # surfaces the watermark loudly instead of dropping it
        self._restored_stream_cursor: Optional[int] = None
        if self.opts.stream_batch > 0 or \
                self.opts.stream_freshness_slo_ms > 0:
            from ..stream import StreamPlane
            self.stream = StreamPlane(self)
        if self.stream is not None:
            self.stream.start()

        # native host-routing core (C++ via ctypes; None -> numpy fallback)
        from ..native import get_lib
        self._native = get_lib()

        # observability (reference PS_TRACE_KEYS / PS_LOCALITY_STATS, §5)
        from ..utils.stats import (KeyTracer, LocalityStats, ALLOC,
                                   parse_trace_spec)
        traced = parse_trace_spec(self.opts.trace_keys or "", self.num_keys)
        self.tracer = KeyTracer(traced, self.num_keys) \
            if traced is not None else None
        self.locality = LocalityStats(self.num_keys, self._native) \
            if self.opts.locality_stats else None
        # device-routed runners register a counts callback here so the
        # production path feeds locality_summary too (ops/fused.py)
        self._locality_sources: List = []
        if self.tracer is not None:
            # initial allocation events, grouped by home shard (one record
            # call per shard, not per key)
            owners = self.ab.owner[traced]
            for s in np.unique(owners):
                self.tracer.record(traced[owners == s], ALLOC, int(s))

        # periodic incremental checkpoints (ISSUE 10; fault/ckpt.py):
        # with --sys.checkpoint.every N + --sys.checkpoint.path D, a
        # self-rescheduling `ckpt`-stream executor program appends a
        # dirty-slot delta (base first) every N seconds. None when off.
        self.ckpt = None
        if self.opts.ckpt_every_s > 0:
            if not self.opts.ckpt_path:
                raise ValueError(
                    "--sys.checkpoint.every requires "
                    "--sys.checkpoint.path (chain directory)")
            from ..fault.ckpt import IncrementalCheckpointer
            self.ckpt = IncrementalCheckpointer(self, self.opts.ckpt_path)
            self.ckpt.start_periodic(self.opts.ckpt_every_s)

        # periodic metrics reporter (--sys.metrics.report N). The import
        # is INSIDE the gate on purpose: with --sys.metrics 0 the
        # reporter module must never load (tests assert this).
        self._reporter = None
        if self.opts.metrics and self.opts.metrics_report_s > 0:
            from ..obs.reporter import Reporter
            self._reporter = Reporter(self.obs,
                                      self.opts.metrics_report_s,
                                      rank=self.pid)
            self._reporter.start()

    # -- topology-mutation discipline ----------------------------------------

    def _check_topology_discipline(self) -> None:
        """Debug assertion pairing addressbook mutations with a
        topology_version bump: every counted ab mutation must have gone
        through _topology_mutation(). Cheap (one int compare), so it
        runs on every entry to the context manager and on the optimistic
        revalidation path."""
        assert self.ab.mutations == self._ab_mut_acked, (
            "addressbook mutated outside Server._topology_mutation(): "
            "optimistic routing, the plan cache and staged prefetch "
            "buffers revalidate against topology_version, so an "
            "unpaired mutation lets stale plans dispatch into freed or "
            "reassigned pool slots")

    @contextlib.contextmanager
    def _topology_mutation(self):
        """THE addressbook-mutation discipline (ADVICE r5 #1). Every site
        that mutates placement tables must run inside this context: it
        holds the server lock and bumps `topology_version` as the LAST
        mutation of its critical section on exit — the invariant that
        makes optimistic routing's plan-then-revalidate sound (a stale
        plan can never pass revalidation, because the bump is visible
        before the lock is released). The yielded handle's `cancel()`
        marks a section that turned out to mutate nothing (e.g. a
        relocation whose whole batch demoted); exit then asserts nothing
        WAS mutated, so a cancelled-but-mutated section fails loudly
        instead of leaking an unbumped mutation."""
        with self._lock:
            self._check_topology_discipline()
            before = self.ab.mutations
            h = _TopoHandle()
            try:
                yield h
            finally:
                # bump even when the section raised: a PARTIAL mutation
                # must still fail every outstanding optimistic plan
                if h.cancelled:
                    assert self.ab.mutations == before, (
                        "topology mutation section cancelled after "
                        "mutating the addressbook")
                else:
                    self.topology_version += 1
                    self._c_topo_bumps.inc()
                    self._ab_mut_acked = self.ab.mutations

    def _span(self, name: str):
        """Span context for phase `name` — the shared no-op when span
        tracing is off (one attribute check on the hot path)."""
        sp = self.spans
        return NULL_SPAN if sp is None else sp.span(name)

    # -- worker management ---------------------------------------------------

    def make_worker(self, worker_id: Optional[int] = None) -> "Worker":
        with self._lock:
            if worker_id is None:
                worker_id = len(self._workers)
            assert worker_id < self.max_workers, (
                f"worker_id {worker_id} >= num_workers {self.max_workers}")
            w = Worker(self, worker_id)
            self._workers[worker_id] = w
            return w

    def workers(self):
        return list(self._workers.values())

    def worker_clocks(self) -> np.ndarray:
        return self._clocks.copy()

    def shard_min_clocks(self) -> np.ndarray:
        """Min clock over the workers mapped to each shard (used for intent
        expiry; reference compares per-customer clocks, handle.h:542-578)."""
        out = np.full(self.num_shards, np.iinfo(np.int64).max)
        for wid, w in self._workers.items():
            out[w.shard] = min(out[w.shard], self._clocks[wid])
        out[out == np.iinfo(np.int64).max] = 0
        return out

    # -- sampling ------------------------------------------------------------

    def enable_sampling_support(self, sample_key_fn, min_key: int = 0,
                                max_key: Optional[int] = None,
                                allowed_keys=None) -> None:
        """Install a sampling scheme (reference
        ColoKVServer::enable_sampling_support, coloc_kv_server.h;
        `sample_key_fn(n, rng) -> np.ndarray[int64]` draws app-distribution
        keys, like the reference's `Key sample_key()` callback).
        `allowed_keys` bounds the Local scheme's snap population when the
        sampled keys are not a contiguous range."""
        from .sampling import make_sampling
        self.sampling = make_sampling(self, sample_key_fn, min_key,
                                      max_key if max_key is not None
                                      else self.num_keys,
                                      allowed_keys=allowed_keys)

    # -- routing helpers (host) ---------------------------------------------

    def _route(self, keys: np.ndarray, shard: int,
               write_through: bool = False, record: bool = True):
        """Resolve keys (any shape) to pool coordinates for a worker on
        `shard`, preferring a local replica over the owner row (the single
        routing policy shared by Pull/Push and the fused step, ops/fused.py).
        Returns (o_sh, o_sl, c_sh, c_sl, use_c, n_remote, local): owner
        shard+slot, replica shard+slot (OOB where none), replica mask,
        remote-key count, and the per-key locality mask (THE definition of
        "local" — dispatch-time stats reuse it instead of restating the
        policy). Locality stats are recorded here unless `record=False`
        (optimistic planning: a plan that fails topology revalidation is
        recomputed, and must not count twice); `write_through` marks ops
        that must reach the owner regardless of replicas (Set), so a
        replica doesn't count as local. Uses the native router
        (adapm_tpu/native) when available."""
        ab = self.ab
        if self._native is not None:
            from ..native import route
            flat = np.ascontiguousarray(keys.ravel(), dtype=np.int64)
            o_sh, o_sl, c_sh, c_sl, use_c, n_remote, local = route(
                self._native, flat, ab.owner, ab.slot,
                ab.cache_slot[shard], shard, int(OOB), write_through)
            if record and self.locality is not None:
                self.locality.record(flat, local)
            sh = keys.shape
            o_sh, o_sl = o_sh.reshape(sh), o_sl.reshape(sh)
            c_sh, c_sl = c_sh.reshape(sh), c_sl.reshape(sh)
            use_c = use_c.reshape(sh)
            return o_sh, o_sl, c_sh, c_sl, use_c, n_remote, \
                local.reshape(sh)
        # numpy fallback: match the native path's bounds behavior
        from ..base import check_key_range
        check_key_range(keys, self.num_keys)
        o_sh = ab.owner[keys].astype(np.int32)
        o_sl = ab.slot[keys].astype(np.int32)
        cs = ab.cache_slot[shard, keys].astype(np.int32)
        use_c = cs >= 0
        on_owner = o_sh == shard
        local = on_owner if write_through else (use_c | on_owner)
        n_remote = int((~local).sum())
        if record and self.locality is not None:
            self.locality.record(keys.ravel(), local.ravel())
        c_sh = np.full_like(o_sh, shard)
        c_sl = np.where(use_c, cs, OOB).astype(np.int32)
        return o_sh, o_sl, c_sh, c_sl, use_c, n_remote, local

    def _group_by_class(self, keys: np.ndarray):
        """Split a key batch by length class; returns [(cid, positions)]."""
        kc = self.ab.key_class[keys]
        if len(self.stores) == 1:
            return [(0, np.arange(len(keys)))]
        return [(cid, np.nonzero(kc == cid)[0])
                for cid in np.unique(kc)]

    def _flat_parts(self, keys: np.ndarray, flat: np.ndarray, positions,
                    length: int) -> np.ndarray:
        """Extract [n, L] rows for `positions` of `keys` out of a flat
        concatenated value buffer (offsets are relative to this batch).
        Vectorized via the shared ragged-buffer helpers (parallel/pm.py) —
        never a per-key loop (a full-model push at Wikidata5M scale passes
        through here)."""
        from ..parallel.pm import _offsets, _select_flat
        lens = self.value_lengths[keys]
        return _select_flat(flat, _offsets(lens), lens,
                            np.asarray(positions)).reshape(-1, length)

    # -- core ops (called by Worker; all under the server lock) --------------

    def _plan_pull(self, keys: np.ndarray, shard: int):
        """Routing plan for `_pull`: no device dispatch, no side effects.
        Safe to call WITHOUT the server lock — it reads only the fixed-size
        in-place-mutated addressbook tables, and every table mutation bumps
        `topology_version` under the lock, so callers revalidate the
        version under the lock before dispatching and re-plan on a miss
        (optimistic routing; the reference instead shards per-key locks so
        N worker threads route concurrently, handle.h:1069-1083)."""
        with self._span("kv.plan_pull"):
            return self._plan_pull_impl(keys, shard)

    def _plan_pull_impl(self, keys: np.ndarray, shard: int):
        rem = None
        loc_map = None
        if self.glob is not None:
            proc_rem = (self.ab.owner[keys] < 0) & \
                (self.ab.cache_slot[shard, keys] < 0)
            if proc_rem.any():
                rem_pos = np.nonzero(proc_rem)[0]
                rem = (rem_pos, keys[rem_pos])
                loc_map = np.nonzero(~proc_rem)[0]
                keys = keys[loc_map]
        cls = []
        if len(keys):
            for cid, pos in self._group_by_class(keys):
                ks = keys[pos]
                cls.append((cid, pos, ks,
                            self._route(ks, shard, record=False)))
        return (rem, loc_map, cls)

    def _pull(self, keys: np.ndarray, shard: int, after=(), plan=None):
        """Returns (groups, n_remote, remote): one gather per length class.
        `remote` is (positions, Future) for process-remote keys served over
        the DCN channel (multi-process only); `after` futures are this
        worker's outstanding remote writes (read-your-writes ordering).
        `plan` is an optional pre-computed `_plan_pull` result (must have
        been revalidated against `topology_version` under the lock)."""
        if plan is None:
            plan = self._plan_pull(keys, shard)
        rem, loc_map, cls = plan
        groups = []
        remote = None
        n_remote = 0
        if rem is not None:
            rem_pos, rem_keys = rem
            fut = self.glob.pull_async(rem_keys, after=after)
            remote = (rem_pos, fut)
            n_remote = len(rem_pos)
        # Multi-class batches: issue every class's gather back-to-back
        # under ONE dispatch-gate hold (ISSUE 16 satellite). Each
        # store.gather re-acquires the (reentrant) gate per program, so
        # without the outer hold a concurrent serve/step dispatcher could
        # interleave between classes and the per-class enqueues would
        # serialize behind it; holding the gate across the loop keeps the
        # enqueue train contiguous. The gate is a leaf lock, so taking it
        # while holding the server lock is in-order (APM004).
        with dispatch_gate():
            for cid, pos, ks, (o_sh, o_sl, c_sh, c_sl, use_c, nr,
                               local) in cls:
                n_remote += nr
                if self.locality is not None:
                    self.locality.record(ks.ravel(), local.ravel())
                o_sl = np.where(use_c, OOB, o_sl).astype(np.int32)
                vals = self.stores[cid].gather(o_sh, o_sl, c_sh, c_sl,
                                               use_c)
                gpos = pos if loc_map is None else loc_map[pos]
                groups.append((cid, gpos, self.value_lengths[ks], vals,
                               len(ks)))
        return groups, n_remote, remote

    def _plan_push_routes(self, keys: np.ndarray, shard: int,
                          is_set: bool = False):
        """The cacheable routing part of `_plan_push`: everything derived
        from the key batch and the tables alone — the PlanCache entry for
        the 'push'/'set' kinds. Value staging is applied per call by
        `_plan_push` (values change every step; routes only change with
        the topology)."""
        rem_pos = loc_pos = None
        kloc = keys
        if self.glob is not None:
            # Set must reach the owner; Push may land in a local replica's
            # delta row (same split as the reference's local attempt)
            if is_set:
                proc_rem = self.ab.owner[keys] < 0
            else:
                proc_rem = (self.ab.owner[keys] < 0) & \
                    (self.ab.cache_slot[shard, keys] < 0)
            if proc_rem.any():
                rem_pos = np.nonzero(proc_rem)[0]
                loc_pos = np.nonzero(~proc_rem)[0]
                kloc = keys[loc_pos]
        cls = []
        if len(kloc):
            for cid, pos in self._group_by_class(kloc):
                ks = kloc[pos]
                cls.append((cid, pos, ks,
                            self._route(ks, shard, write_through=is_set,
                                        record=False)))
        return (rem_pos, loc_pos, cls)

    def _plan_push(self, keys: np.ndarray, vals: np.ndarray, shard: int,
                   is_set: bool = False, routes=None):
        """Routing + staging plan for `_push`: no device dispatch, no side
        effects; same lock-free contract as `_plan_pull`. `routes` is an
        optional pre-computed (possibly plan-cached) `_plan_push_routes`
        result for the same (keys, shard, is_set)."""
        with self._span("kv.plan_push"):
            return self._plan_push_impl(keys, vals, shard, is_set=is_set,
                                        routes=routes)

    def _plan_push_impl(self, keys, vals, shard, is_set=False,
                        routes=None):
        if routes is None:
            routes = self._plan_push_routes(keys, shard, is_set=is_set)
        rem_pos, loc_pos, cls_r = routes
        flat = vals.ndim == 1
        rem = None
        if rem_pos is not None:
            from ..parallel.pm import _offsets, _select_flat
            rem_keys = keys[rem_pos]
            if flat:
                lens = self.value_lengths[keys]
                offs = _offsets(lens)
                rem_flat = _select_flat(vals, offs, lens, rem_pos)
                vals = _select_flat(vals, offs, lens, loc_pos)
            else:
                rem_flat = np.ascontiguousarray(vals[rem_pos]).ravel()
                vals = vals[loc_pos]
            keys = keys[loc_pos]
            rem = (rem_pos, rem_keys, rem_flat)
        cls = []
        for cid, pos, ks, route in cls_r:
            L = self.class_lengths[cid]
            rows = self._flat_parts(keys, vals, pos, L) if flat \
                else vals[pos]
            cls.append((cid, ks, rows, route))
        return (rem, cls)

    def _push(self, keys: np.ndarray, vals: np.ndarray, shard: int,
              is_set: bool = False, after=(), plan=None):
        """Returns (n_remote, futures): futures are outstanding cross-process
        writes (multi-process only; `after` = the worker's earlier write
        futures, chained to preserve per-worker write order). `plan` is an
        optional `_plan_push` result revalidated under the lock."""
        self._prefetch_note(keys)
        if plan is None:
            plan = self._plan_push(keys, vals, shard, is_set=is_set)
        rem, cls = plan
        n_remote = 0
        futures = []
        if rem is not None:
            from ..parallel.pm import _fill_flat, _offsets
            rem_pos, rem_keys, rem_flat = rem
            chain = list(after)
            if is_set:
                # Set invalidates any local replicas of these keys: a
                # kept replica's pending delta would re-add on top of
                # the overwritten value. Flush the delta (ordered
                # BEFORE the set) and drop the replica; reads route to
                # the owner afterwards.
                cs = self.ab.cache_slot[shard, rem_keys]
                has = cs >= 0
                if has.any():
                    hk = np.unique(rem_keys[has])
                    lens_h = self.value_lengths[hk]
                    offs_h = _offsets(lens_h)
                    dflat = np.zeros(offs_h[-1], np.float32)
                    for cid, pos in self._group_by_class(hk):
                        rows = self.stores[cid].read_rows(
                            "delta",
                            np.full(len(pos), shard, np.int32),
                            self.ab.cache_slot[
                                shard, hk[pos]].astype(np.int32))
                        _fill_flat(dflat, offs_h, lens_h, pos,
                                   rows.ravel())
                    self._drop_cross_replicas(hk, shard)
                    chain = chain + [self.glob.write_async(
                        hk, dflat, is_set=False, after=chain)]
            fut = self.glob.write_async(
                rem_keys, rem_flat.astype(np.float32), is_set,
                after=chain)
            if is_set and len(chain) > len(after):
                # the owner keeps serving sync for our dropped replicas
                # until we unsubscribe; do it once the set has landed
                fut = self.glob.unsub_async(hk, after=[fut])
            futures.append(fut)
            if len(self._rw_pending) > 64:
                self._prune_rw_pending()
            self._rw_pending.append((fut, rem_keys))
            n_remote += len(rem_pos)
        for cid, ks, rows, (o_sh, o_sl, c_sh, c_sl, use_c, nr,
                            local) in cls:
            n_remote += nr
            if self.locality is not None:
                self.locality.record(ks.ravel(), local.ravel())
            if is_set:
                # Set writes through to the main copy and refreshes the
                # writer's local replica (store._set_rows docstring)
                self.stores[cid].set_rows(o_sh, o_sl, rows, c_sh, c_sl)
            else:
                if self._dbg_applies is not None:
                    np.add.at(self._dbg_applies, ks, rows[:, 0])
                o_sl = np.where(use_c, OOB, o_sl).astype(np.int32)
                self.stores[cid].scatter_add(o_sh, o_sl, c_sh, c_sl, rows)
        return n_remote, futures

    # -- cross-process service endpoints (called by GlobalPM under _lock) ----

    # full-model reads switch to one whole-pool device->host copy per class
    # instead of a padded device gather: at 5M keys the gather program (and
    # its compile) costs minutes, the pool copy seconds
    _BULK_READ_MIN = 65536

    def _read_owned_flat(self, keys: np.ndarray) -> np.ndarray:
        """Current main-copy values of locally-owned keys (flat concat)."""
        if len(keys) >= self._BULK_READ_MIN:
            return self._read_owned_bulk(keys)
        groups, _ = self._pull_main_only(keys)
        return self._assemble_flat(keys, groups)

    def _read_owned_bulk(self, keys: np.ndarray) -> np.ndarray:
        """Checkpoint/eval/export-scale read: copy each class pool to host
        once, then reorder rows with a vectorized fancy index."""
        from ..parallel.pm import _fill_flat, _offsets
        lens = self.value_lengths[keys]
        offs = _offsets(lens)
        out = np.empty(offs[-1], dtype=np.float32)
        for cid, pos in self._group_by_class(keys):
            ks = keys[pos]
            st = self.stores[cid]
            if st.res is not None:
                # tiered: read only the REQUESTED rows (cold store fancy
                # index + one hot-pool-sized overlay readback) — a full
                # main_host() copy would transiently double host RAM at
                # the beyond-HBM sizes tiering exists for
                from ..tier.coldpath import read_main_rows_bulk
                rows = read_main_rows_bulk(
                    st, self.ab.owner[ks], self.ab.slot[ks])
            else:
                host = np.asarray(st.main)             # [S, slots, L]
                rows = host[self.ab.owner[ks], self.ab.slot[ks]]
            _fill_flat(out, offs, lens, pos, rows.ravel())
        return out

    def _plan_cached(self, kind: str, shard: int, keys: np.ndarray,
                     tv: int, compute):
        """The one plan-cache get-or-compute-then-put sequence (shared by
        Worker.pull/push/set and the prefetch staging path, so the
        caching contract lives in one place)."""
        cache = self._plan_cache
        plan = cache.get(kind, shard, keys, tv) \
            if cache is not None else None
        if plan is None:
            plan = compute()
            if cache is not None:
                cache.put(kind, shard, keys, tv, plan)
        return plan

    def _prefetch_note(self, keys: np.ndarray) -> None:
        """Invalidate staged prefetch buffers that intersect a value
        write (caller holds the lock; every write path must pass through
        here BEFORE a reader could miss the write — see
        PrefetchScheduler.note_writes)."""
        if self.prefetch is not None:
            self.prefetch.note_writes(keys)

    def _apply_remote_write(self, keys: np.ndarray, flat: np.ndarray,
                            is_set: bool) -> None:
        """Apply a cross-process push/set to locally-owned main rows."""
        self._prefetch_note(keys)
        flat = np.asarray(flat, dtype=np.float32)
        for cid, pos in self._group_by_class(keys):
            ks = keys[pos]
            L = self.class_lengths[cid]
            rows = self._flat_parts(keys, flat, pos, L)
            o_sh = self.ab.owner[ks].astype(np.int32)
            o_sl = self.ab.slot[ks].astype(np.int32)
            n = len(ks)
            zeros = np.zeros(n, np.int32)
            oob = np.full(n, OOB, np.int32)
            if is_set:
                self.stores[cid].set_rows(o_sh, o_sl, rows, zeros, oob)
            else:
                if self._dbg_applies is not None:
                    np.add.at(self._dbg_applies, ks, rows[:, 0])
                self.stores[cid].scatter_add(o_sh, o_sl, zeros, oob, rows)

    def ensure_local(self, keys: np.ndarray, shard: int) -> None:
        """Make process-remote `keys` locally servable (replicate or adopt
        via the owner's decision) — the fused runners' miss path: apps
        normally signal intent ahead so keys are local by step time; a
        cold miss blocks here once instead of computing on garbage rows.
        No-op in a single process."""
        if self.glob is None:
            return
        with self._lock:
            rem = keys[(self.ab.owner[keys] < 0)
                       & (self.ab.cache_slot[shard, keys] < 0)]
        if len(rem) == 0:
            return
        import time as _time
        rem = np.unique(rem)
        end = int(self._clocks.max()) + 2
        self.sync.intent_end[shard, rem] = np.maximum(
            self.sync.intent_end[shard, rem], end)
        for attempt in range(50):
            self.glob.intent_remote(rem, shard, end)
            # installs are deferred for keys with in-flight remote writes
            # (and capacity-truncated ones get unsubscribed) — retry until
            # everything is servable locally
            with self._lock:
                rem = rem[(self.ab.owner[rem] < 0)
                          & (self.ab.cache_slot[shard, rem] < 0)]
            if len(rem) == 0:
                return
            # a full cache pool frees up as expired replicas drop: drive a
            # full sync round (flush + drop) before retrying
            with self._round_lock:
                self.sync.run_round(all_channels=True)
            _time.sleep(0.005 * (attempt + 1))
        raise RuntimeError(
            f"{len(rem)} keys could not be made local on shard {shard} "
            f"(cache pool full?); first: {rem[:5].tolist()}")

    def _prune_rw_pending(self) -> None:
        """Drop completed remote-write records (caller holds the lock). A
        completed future means the write is applied at its owner, so any
        owner-side read AFTER the prune observes it."""
        self._rw_pending = [(f, k) for f, k in self._rw_pending
                            if not f.done()]

    def _rw_blocked_keys(self):
        """Keys with remote writes recorded since the last prune (caller
        holds the lock); replication installs must skip them."""
        if not self._rw_pending:
            return None
        return np.unique(np.concatenate([k for _, k in self._rw_pending]))

    def _drop_cross_replicas(self, keys: np.ndarray, shard: int) -> None:
        """Drop this shard's replicas of remotely-owned `keys` (metadata +
        channel registry only; the caller handles delta flushing and the
        owner unsubscription). Caller holds the lock."""
        keys = keys[self.ab.cache_slot[shard, keys] >= 0]
        if len(keys) == 0:
            return
        with self._topology_mutation():
            self.sync.replica_discard(keys, shard)
            for _, pos in self._group_by_class(keys):
                self.ab.drop_replicas(keys[pos], shard)
            self.sync.stats.add(replicas_dropped=len(keys))

    def _flush_drop_local_replicas(self, keys: np.ndarray) -> None:
        """Flush pending deltas of all local replicas of `keys` into their
        local main copies and drop the replicas (used before a forced
        cross-process relocation so no delta is lost)."""
        sh_idx, k_idx = np.nonzero(self.ab.cache_slot[:, keys] >= 0)
        if len(k_idx) == 0:
            return
        karr = keys[k_idx].astype(np.int64)
        sarr = sh_idx.astype(np.int32)
        self._sync_replicas(karr, sarr)
        with self._topology_mutation():
            self.sync.replica_discard(karr, sarr)
            for s in np.unique(sarr):
                sk = karr[sarr == s]
                for _, pos in self._group_by_class(sk):
                    self.ab.drop_replicas(sk[pos], int(s))
            self.sync.stats.add(replicas_dropped=len(karr))

    # -- planner ops (called by SyncManager) ---------------------------------

    def _create_replicas(self, keys: np.ndarray, shard: int) -> np.ndarray:
        """Allocate+materialize replicas on `shard`; returns created keys.
        Batched end to end (reference creates replica stubs per key under
        per-key locks, handle.h:484-532; here one allocator batch + one
        device program per length class). A full cache pool truncates the
        batch: surplus keys stay remote — slower, never wrong."""
        with self._lock:
            ab = self.ab
            mask = ~ab.is_local(keys, shard)
            todo = np.unique(keys[mask])
            # replica_create copies from LOCAL main rows; keys a DCN handler
            # relocated away concurrently must not be materialized from them
            todo = todo[ab.owner[todo] >= 0]
            if len(todo) == 0:
                return np.empty(0, dtype=np.int64)
            created = []
            with self._topology_mutation() as tm:
                for cid, pos in self._group_by_class(todo):
                    cs = ab.add_replicas(todo[pos], shard)
                    ks = todo[pos][: len(cs)]
                    if len(ks) == 0:
                        continue
                    c_sl = cs.astype(np.int32)
                    o_sh = ab.owner[ks].astype(np.int32)
                    o_sl = ab.slot[ks].astype(np.int32)
                    c_sh = np.full_like(o_sh, shard)
                    self.stores[cid].replica_create(o_sh, o_sl, c_sh, c_sl)
                    created.append(ks)
                if not created:
                    tm.cancel()  # cache pool full: nothing materialized
            if not created:
                return np.empty(0, dtype=np.int64)
            out = np.concatenate(created)
            if self.tracer is not None:
                from ..utils.stats import REPLICA_SETUP
                self.tracer.record(out, REPLICA_SETUP, shard)
            return out

    def _dirty_replica_mask(self, keys: np.ndarray,
                            shards: np.ndarray) -> np.ndarray:
        """True per (key, holder-shard) replica iff a sync would change
        any bit: an unshipped delta write or a base older than the main
        row (the store-level write epochs; store.py). Cross-process
        replicas (owner remote, no local main row) report their
        delta-dirty flag alone — epochs cannot see the remote owner's
        writes, which is why sync_channel exempts them from the filter;
        here the flag keeps the dirty_fraction gauge honest in
        multi-process runs. Pure host reads — safe without the lock (a
        racing write flips an entry to dirty and is picked up next
        round; a dropped replica reads as clean and is skipped, which
        `_sync_replicas` would do anyway)."""
        out = np.zeros(len(keys), dtype=bool)
        ab = self.ab
        for cid, pos in self._group_by_class(keys):
            ks, ss = keys[pos], shards[pos]
            cs = ab.cache_slot[ss, ks]
            o_sh = ab.owner[ks]
            o_sl = ab.slot[ks]
            st = self.stores[cid]
            d = np.zeros(len(ks), dtype=bool)
            has = np.nonzero(cs >= 0)[0]
            if len(has) == 0:
                continue
            d[has] = st.delta_dirty[ss[has], cs[has]]
            loc = has[o_sl[has] >= 0]
            if len(loc):
                d[loc] |= (st.main_epoch[o_sh[loc], o_sl[loc]]
                           != st.repl_epoch[ss[loc], cs[loc]])
            out[pos] = d
        return out

    def _sync_replicas(self, keys: np.ndarray, shards: np.ndarray,
                       threshold: float = 0.0,
                       compress: bool = False) -> None:
        """Sync replicas given parallel (key, holder-shard) arrays.
        threshold > 0 leaves small-delta replicas out of the round
        (--sys.sync.threshold); drop/quiesce paths pass 0 so no pending
        delta is ever lost. compress=True applies the
        --sys.sync.compress wire format (quantized deltas, EF residual
        parked in the delta row — store._sync_replicas_compressed);
        ONLY the periodic sync_channel rounds pass it. Drop and
        quiesce flushes keep the default: a dropped replica's delta
        row is freed, so a compressed flush there would LOSE its
        parked residual — the exact flush is what bounds the
        compression contract (docs/MEMORY.md). Under the lock this
        does only coordinate revalidation and program ENQUEUE: the
        per-class device programs are dispatched back-to-back (JAX
        dispatch is asynchronous), so device execution overlaps the
        caller's classification of the next channel instead of
        serializing behind the lock."""
        mode = self.opts.sync_compress if compress else "off"
        with self._lock:
            ab = self.ab
            karr = np.ascontiguousarray(keys, dtype=np.int64)
            sarr = np.ascontiguousarray(shards, dtype=np.int32)
            # a sync refreshes replica bases (and may advance owner rows):
            # staged pull buffers of these keys are no longer what a
            # fresh pull would return
            self._prefetch_note(karr)
            for cid, pos in self._group_by_class(karr):
                ks, ss = karr[pos], sarr[pos]
                r_cs = ab.cache_slot[ss, ks].astype(np.int32)
                o_sh = ab.owner[ks].astype(np.int32)
                o_sl = ab.slot[ks].astype(np.int32)
                # a DCN handler may have dropped a replica or relocated a
                # key away since the caller snapshotted its items; a -1
                # index would WRAP in the device gather/scatter and corrupt
                # unrelated rows, so re-validate under the lock
                ok = (r_cs >= 0) & (o_sl >= 0)
                if not ok.all():
                    ss, r_cs = ss[ok], r_cs[ok]
                    o_sh, o_sl = o_sh[ok], o_sl[ok]
                    if not ok.any():
                        continue
                self.stores[cid].sync_replicas(ss, r_cs, o_sh, o_sl,
                                               threshold=threshold,
                                               compress=mode)

    def _drop_replicas(self, keys: np.ndarray,
                       shards: np.ndarray) -> None:
        with self._lock:
            # drop only replicas still on record (a DCN handler may have
            # upgraded/dropped some since the caller snapshotted)
            karr = np.ascontiguousarray(keys, dtype=np.int64)
            sarr = np.ascontiguousarray(shards, dtype=np.int32)
            ok = self.ab.cache_slot[sarr, karr] >= 0
            if not ok.any():
                return
            karr, sarr = karr[ok], sarr[ok]
            # flush pending deltas first (base refresh is harmless), then
            # free the slots (reference readAndPotentiallyDropReplica) —
            # grouped per (shard, class), not per key
            self._sync_replicas(karr, sarr)
            with self._topology_mutation():
                for s in np.unique(sarr):
                    sk = karr[sarr == s]
                    for _, pos in self._group_by_class(sk):
                        self.ab.drop_replicas(sk[pos], int(s))
                    if self.tracer is not None:
                        from ..utils.stats import REPLICA_DROP
                        self.tracer.record(sk, REPLICA_DROP, int(s))

    def _relocate(self, moves: List[Tuple[int, int]]) -> int:
        """Move main copies given (key, dest_shard) pairs. Returns the number
        of moves actually performed; see _relocate_to."""
        if not moves:
            return 0
        karr = np.fromiter((k for k, _ in moves), np.int64, len(moves))
        sarr = np.fromiter((s for _, s in moves), np.int32, len(moves))
        return sum(self._relocate_to(karr[sarr == dest], int(dest))
                   for dest in np.unique(sarr))

    def _relocate_to(self, keys: np.ndarray, dest: int) -> int:
        """Move the main copies of `keys` to shard `dest` (the drain path's
        shape: one destination per intent entry). Batched per class: one
        allocator batch + one device program. A move whose destination main
        pool is full is demoted to a replication attempt (the planner's
        graceful-degradation policy, sync.py _register) rather than
        silently dropped."""
        pol = self.policy
        if pol is not None and len(keys) and pol.active("reloc"):
            # ISSUE 18 learned reloc law: predicted move-thrash regret
            # (the plane's `move` outcome — locality 0 at window
            # close) may HOLD the whole batch in place; the keys stay
            # owned where they are and every pull/push reaches the
            # same main row immediately — slower, never wrong.
            # Value-preservation guard: a dest replica's pending delta
            # merges in-kernel AT relocate time, so holding the move
            # is only a bitwise no-op when every dest replica in the
            # batch is verifiably clean (the exact store-epoch mask,
            # never a heuristic); otherwise the heuristic's move
            # proceeds unvetoed.
            if pol.consult("reloc",
                           {"n_moved": len(keys), "n_demoted": 0},
                           len(keys)):
                rk = keys[self.ab.cache_slot[dest, keys] >= 0]
                if len(rk) == 0 or not self._dirty_replica_mask(
                        rk, np.full(len(rk), dest, np.int32)).any():
                    pol.applied("reloc")
                    return 0
                pol.guard_blocked("reloc")
        demoted = np.empty(0, dtype=np.int64)
        n_moved = 0
        with self._lock:
            ab = self.ab
            # dedup: a duplicate key would double-free its old main slot in
            # relocate_batch (the drain path dedups in Worker.intent, but
            # direct callers may not). Keys a DCN handler relocated to
            # another PROCESS since the caller's classification are skipped
            # (owner < 0): the planner re-requests them cross-process on a
            # later intent drain.
            keys = np.unique(keys)
            keys = keys[(ab.owner[keys] != dest) & (ab.owner[keys] >= 0)]
            if len(keys) == 0:
                return 0
            with self._topology_mutation() as tm:
                for cid, pos in self._group_by_class(keys):
                    ks = keys[pos]
                    moved, old_sh, old_sl, new_sl = \
                        ab.relocate_batch(ks, dest)
                    if len(moved) < len(ks):  # pool full: demote the rest
                        demoted = np.concatenate((demoted, ks[len(moved):]))
                    if len(moved) == 0:
                        continue
                    # a replica at the destination upgrades to owner: its
                    # pending delta merges in-kernel (rc coords), and its
                    # cache slot is freed
                    cs = ab.cache_slot[dest, moved]
                    has_rep = cs >= 0
                    rc_sh = np.where(has_rep, dest, 0).astype(np.int32)
                    rc_sl = np.where(has_rep, cs, OOB).astype(np.int32)
                    rep_keys = moved[has_rep]
                    if len(rep_keys):
                        self.sync.replica_discard(rep_keys, dest)
                        ab.drop_replicas(rep_keys, dest)
                    self.stores[cid].relocate_rows(
                        old_sh.astype(np.int32), old_sl.astype(np.int32),
                        np.full(len(moved), dest, np.int32),
                        new_sl.astype(np.int32), rc_sh, rc_sl)
                    n_moved += len(moved)
                    if self.tracer is not None:
                        from ..utils.stats import RELOCATE
                        self.tracer.record(moved, RELOCATE, dest)
                if n_moved == 0:
                    tm.cancel()  # whole batch demoted: nothing moved
        if len(demoted):
            created = self._create_replicas(demoted, dest)
            with self._lock:
                self.sync.replica_add(created, dest)
            self.sync.stats.add(replicas_created=len(created))
        wt = self.wtrace
        if wt is not None and (n_moved or len(demoted)):
            # relocation decision as it landed (ISSUE 15): moves plus
            # the pool-full demotions-to-replication — observational,
            # replay lets the candidate policy re-decide
            wt.record_decision("reloc", n_moved, dest=int(dest),
                               demoted=int(len(demoted)))
        dc = self.decisions
        if dc is not None and (n_moved or len(demoted)):
            # ISSUE 17: the same landed move, with features + a
            # post-move-locality outcome window over the keys that
            # actually moved (the deduped batch minus the demotions)
            moved_keys = np.setdiff1d(keys, demoted) if len(demoted) \
                else keys
            dc.record_move(int(dest), n_moved, int(len(demoted)),
                           moved_keys)
        return n_moved

    # -- lifecycle -----------------------------------------------------------

    def start_sync_thread(self) -> None:
        """Run sync rounds in the background (reference SyncManager threads,
        coloc_kv_server.h:100-105). Optional: tests drive rounds manually.

        PR 6: the dedicated thread is subsumed by the executor — rounds
        run as a self-rescheduling program on the `sync` stream (one
        round per program, FIFO, resubmitted until stopped), so
        background sync shares the executor's worker pool and shows up
        in its queue/overlap accounting. `_sync_thread` remains the
        started/stopped token the old API exposed (None = stopped)."""
        if self._sync_thread is not None:
            return
        self._sync_stop.clear()
        state = {"last_report": _time.monotonic(), "last_rounds": 0,
                 "fail_streak": 0}
        token = object()
        self._sync_thread = token

        def tick():
            from ..utils import alog
            if self._sync_stop.is_set() or self._sync_thread is not token:
                return
            delay = 0.0
            try:
                if self.fault is not None:
                    # ISSUE 10 injection point: fires BEFORE the round
                    # does any work, so a retried tick re-runs cleanly
                    self.fault.fire("sync.round")
                with self._round_lock:
                    self.sync.run_round()
                state["fail_streak"] = 0
                # periodic report (reference SyncManager 10-second
                # reports, sync_manager.h:482-497)
                rs = self.opts.sync_report_s
                now = _time.monotonic()
                if rs > 0 and now - state["last_report"] >= rs:
                    dr = self.sync.stats.rounds - state["last_rounds"]
                    alog(f"[sync] "
                         f"{dr / (now - state['last_report']):.1f} "
                         f"rounds/s | " + self.sync.report())
                    state["last_report"] = now
                    state["last_rounds"] = self.sync.stats.rounds
            except Exception as e:  # noqa: BLE001 — the loop is
                # IMMORTAL (ISSUE 10): a failed round — injected or
                # real — reschedules with its own capped exponential
                # backoff instead of dying with an error nobody waits
                # on (the pre-PR failure mode: one transient tick
                # failure silently killed background sync forever).
                # Caught here rather than left to the executor's
                # retry policy: the policy's budget is bounded, and a
                # streak one longer than the budget must still not
                # kill the loop — the tier maintenance pass and the
                # periodic checkpointer follow the same pattern.
                state["fail_streak"] += 1
                delay = min(2.0, self.opts.fault_backoff_ms * 1e-3 *
                            (2.0 ** min(state["fail_streak"], 10)))
                if self.fault is not None:
                    self.fault.c_loop_retries.inc()
                alog(f"[sync] background round failed "
                     f"(streak {state['fail_streak']}): "
                     f"{type(e).__name__}: {e} — retrying in "
                     f"{delay * 1e3:.0f} ms")
            if not self._sync_stop.is_set() and \
                    self._sync_thread is token:
                self.exec.submit("sync", tick, label="sync.round",
                                 coalesce_key="sync.round", delay=delay)

        self.exec.submit("sync", tick, label="sync.round",
                         coalesce_key="sync.round")

    def stop_sync_thread(self) -> None:
        if self._sync_thread is None:
            return
        self._sync_stop.set()
        # drain, not join: at most one more queued round observes the
        # stop flag and returns immediately. A round that does NOT
        # drain is wedged (e.g. blocked on a dead remote peer) and
        # still reads through the pools — proceeding into executor
        # close and pool teardown would be a use-after-teardown, so
        # fail-stop loudly instead (the serve-dispatcher discipline,
        # docs/failure_handling.md)
        if not self.exec.drain("sync", timeout=60):
            from ..utils import alog
            alog("[sync] background round failed to drain within 60s "
                 "of stop — wedged mid-round (dead remote peer?)")
            raise RuntimeError(
                "sync round wedged: did not drain within 60s of stop; "
                "refusing to proceed into pool teardown under a live "
                "reader")
        self._sync_thread = None

    def _wb_active_ids(self) -> set:
        """Worker ids that participate in worker barriers: the declared set
        when the Server was built with an explicit num_workers (reference
        Setup(num_keys, num_threads) declares the thread count), else the
        workers registered so far; finalized workers (clock ==
        WORKER_FINISHED) are excluded either way."""
        ids = range(self.max_workers) if self._wb_declared \
            else list(self._workers)  # copy: registration mutates the dict
        return {wid for wid in ids
                if self._clocks[wid] != WORKER_FINISHED}

    def worker_barrier(self, worker_id: int) -> None:
        """Barrier across ALL active worker threads of all processes
        (reference ColoKVWorker::Barrier -> Postoffice::Barrier over the
        worker group): local threads rendezvous first, then one leader per
        process runs the cross-process barrier. A worker that finalizes
        while others wait is excluded (finalize() re-notifies).

        Cross-process contract (same as control.barrier): every process
        must run the same sequence of barrier generations — finalize
        exclusion is process-local, so an app whose ranks retire ALL their
        workers at different times while other ranks still barrier is
        misusing the API (it would equally hang the reference's
        scheduler-counted barriers)."""
        import time as _time

        from ..utils import alog
        with self._wb_cond:
            gen = self._wb_gen  # the generation this arrival joins: while
            # a leader is mid-flight the counter has already advanced, so
            # late arrivals rendezvous in the NEXT generation instead of
            # being absorbed into one they never synchronized with
            self._wb_waiting.add(worker_id)
            next_warn = _time.monotonic() + 30.0
            while True:
                if self._wb_done > gen:
                    err = self._wb_errs.get(gen)
                    if err is not None:  # leader's cross-process failure
                        raise RuntimeError(
                            f"worker barrier generation {gen} failed at "
                            f"the leader") from err
                    return
                if (not self._wb_leading and self._wb_gen == gen
                        and self._wb_waiting >= self._wb_active_ids()):
                    # freeze this generation's membership and open the next
                    self._wb_leading = True
                    self._wb_gen += 1
                    self._wb_waiting = set()
                    break  # this thread leads the global phase
                self._wb_cond.wait(timeout=5.0)
                # stall diagnostic: with declared num_workers, a declared-
                # but-never-created worker hangs the barrier silently —
                # name the absentees (one thread logs per window)
                if (_time.monotonic() >= next_warn
                        and self._wb_gen == gen
                        and worker_id == min(self._wb_waiting, default=-1)):
                    missing = sorted(
                        self._wb_active_ids() - self._wb_waiting)
                    if missing:
                        alog(f"[barrier] worker barrier gen {gen} stalled "
                             f">30s: waiting for worker ids {missing} "
                             f"(declared num_workers counts workers that "
                             f"must barrier or finalize)")
                    next_warn = _time.monotonic() + 30.0
        err = None
        try:
            self.barrier()
        except BaseException as e:  # noqa: BLE001 — followers must see it
            err = e
        with self._wb_cond:
            self._wb_leading = False
            self._wb_done = gen + 1
            if err is not None:
                self._wb_errs[gen] = err
                # prune: followers read their gen's error promptly; only a
                # bounded window is kept
                for g in [g for g in self._wb_errs if g < gen - 8]:
                    del self._wb_errs[g]
            self._wb_cond.notify_all()
        if err is not None:
            raise err

    def barrier(self) -> None:
        """Process barrier. Single-controller: flush dispatch. Multi-host:
        control-plane barrier (parallel/control.py replaces the reference's
        scheduler BARRIER protocol, src/postoffice.cc:149-174)."""
        from ..parallel import control
        # Pause the background sync thread across the cross-host barrier:
        # its rounds dispatch device programs, and the barrier collective
        # must not interleave with them. (Today each process owns its own
        # pools, so sync programs are process-local and the barrier is the
        # only cross-host collective; once pools span hosts, sync rounds
        # themselves must be driven at globally agreed points.)
        was_running = self._sync_thread is not None
        if was_running:
            self.stop_sync_thread()
        with self._span("collective.barrier"):
            self.block()
            if self.glob is not None:
                self.glob.node.barrier()
            else:
                control.barrier()
        if was_running:
            self.start_sync_thread()

    def block(self) -> None:
        # under the server lock: pool buffers are donated+replaced by ops
        # running in other threads, and blocking on a donated buffer raises
        with self._lock:
            for s in self.stores:
                # apm-lint: disable=APM002 quiesce point BY DESIGN: the
                # lock must be held across the device wait here, or a
                # racing op donates the very buffer being blocked on
                s.block()

    def dead_nodes(self, max_age_s: float = 10.0) -> list:
        """Peer processes whose heartbeat has gone stale (reference
        Postoffice::GetDeadNodes; requires --sys.heartbeat > 0). With a
        net node attached, its membership plane is the authority."""
        if self.glob is not None:
            return self.glob.node.dead_peers(max_age_s)
        from ..parallel import control
        return control.dead_processes(max_age_s)

    # -- degraded readiness (ISSUE 10; fault/ckpt.py restore_chain) ----------

    def begin_degraded(self, reason: str) -> None:
        """Flip the server into DEGRADED state: the serve plane sheds
        every lookup loudly with ServeDegradedError (session submit AND
        dispatcher batch-serve both check), and readiness reports the
        reason. Set by restore_chain around the chain apply; available
        to operators for any maintenance window where reads must not
        race a state mutation. A plain write — readers are lock-free:
        a lookup that read None just before the flag flips linearizes
        before the guarded mutation begins (nothing has changed yet),
        which is a valid pre-window read."""
        self._degraded_reason = str(reason)

    def end_degraded(self) -> None:
        self._degraded_reason = None

    @property
    def degraded(self) -> bool:
        return self._degraded_reason is not None

    @property
    def degraded_reason(self) -> Optional[str]:
        return self._degraded_reason

    def drive_rounds(self, n: int = 1) -> None:
        """One training step's planner-drive slot (the apps' per-step
        `sync.run_round` loop): inline when no prefetch pipeline, else
        delegated to the pipeline's background thread so planner work —
        relocations, replica churn, and the device-table re-uploads they
        trigger — overlaps the in-flight device step instead of
        serializing after it."""
        if self.prefetch is not None:
            self.prefetch.pump(n)
        else:
            for _ in range(n):
                self.sync.run_round()

    def shutdown(self) -> None:
        """Deterministic teardown (ISSUE 5 satellite). Order matters —
        every closed plane reads through the pools the later steps block
        on, so readers go down strictly before their substrate:

          1. serve plane (stop admitting lookups; dispatcher drains),
             then the stream plane (ingest pump drains; freshness
             controller stops walking sync/replica state)
          2. metrics reporter
          3. prefetch pipeline (staged gathers + delegated rounds)
          4. tier maintenance worker (demotion readbacks)
          5. periodic checkpointer (an in-flight `ckpt` save reads
             through the pools: its stream drains BEFORE teardown —
             ISSUE 10 satellite)
          6. background sync rounds
          7. the unified executor (every producer above is stopped, so
             a well-ordered close cancels nothing; queued stragglers
             finish cancelled rather than dispatching into teardown)
          8. pool quiesce (block) + sync channel executor
          9. stats / trace / span export, registry unhook
         10. cross-process layer

        Idempotent: a second shutdown() is a no-op (each subordinate
        close is idempotent too, so a test that closed a plane manually
        and then shuts the server down stays clean)."""
        if getattr(self, "_shutdown_done", False):
            return
        self._shutdown_done = True
        if self._serve_plane is not None:
            # stop admitting lookups first: the serve dispatcher reads
            # through the same pools the teardown below blocks on
            self._serve_plane.close()
        if self.stream is not None:
            # stream plane next: the ingest pump pushes through the
            # live pools (its `stream` stream drains inside close) and
            # the freshness tick walks sync/replica state
            self.stream.close()
        if self._reporter is not None:
            self._reporter.stop()
            self._reporter = None
        if self.prefetch is not None:
            self.prefetch.close()
        if self.tier is not None:
            self.tier.close()
        if self.ckpt is not None:
            self.ckpt.close()
        self.stop_sync_thread()
        self.exec.close()
        self.block()
        self.sync.close()
        self.write_stats()
        self.write_trace()
        self.write_flight_trace()
        if self.wtrace is not None:
            # final flush + seal AFTER every producer is stopped: the
            # .wtrace on disk is the complete recorded stream
            self.wtrace.close()
        if self.decisions is not None:
            # same ordering rule, and additionally BEFORE store/pool
            # teardown below: close() force-resolves the open outcome
            # windows, whose probes read residency/addressbook state
            self.decisions.close()
        if self.spans is not None:
            self.spans.close()
        if self.flight_recorder is not None:
            self.flight_recorder.close()
        from ..obs import metrics as _obs_metrics
        _obs_metrics.clear_global_registry(self.obs)
        if self.glob is not None:
            self.glob.node.stop_heartbeat()
            self.glob.shutdown()

    def locality_summary(self) -> Dict[str, float]:
        """Aggregate worker op/param locality ratios (reference shutdown
        summary, coloc_kv_server.h:147-157). Device-routed runners count
        inside the step program; their fused gather+scatter contributes to
        both the pull and push aggregates."""
        agg: Dict[str, int] = {}
        for w in self._workers.values():
            for k, v in w.stats.items():
                agg[k] = agg.get(k, 0) + v
        for src in self._locality_sources:
            c = src()
            for kind in ("pull", "push"):
                for unit in ("ops", "params"):
                    agg[f"{kind}_{unit}"] = \
                        agg.get(f"{kind}_{unit}", 0) + c[unit]
                    agg[f"{kind}_{unit}_local"] = \
                        agg.get(f"{kind}_{unit}_local", 0) + \
                        c[f"{unit}_local"]
        out = {}
        for kind in ("pull", "push"):
            for unit in ("ops", "params"):
                tot = agg.get(f"{kind}_{unit}", 0)
                loc = agg.get(f"{kind}_{unit}_local", 0)
                out[f"{kind}_{unit}_local_frac"] = \
                    loc / tot if tot else float("nan")
        return out

    def write_stats(self) -> List[str]:
        """Dump trace/locality files into --sys.stats.out and log the final
        locality + sync summary."""
        from ..utils import alog, verbose_level
        enabled = bool(self.opts.stats_out or self.tracer is not None
                       or self.locality is not None or verbose_level() > 0)
        if enabled:
            summ = self.locality_summary()
            if any(v == v for v in summ.values()):  # any non-nan
                alog("[stats] " + " ".join(f"{k}={v:.3f}" for k, v in
                                           summ.items() if v == v))
            alog("[stats]", self.sync.report())
            if self.prefetch is not None:
                alog("[stats] prefetch: " + " ".join(
                    f"{k}={v}" for k, v in self.prefetch.report().items()))
            if self._plan_cache is not None:
                alog("[stats] plan_cache: " + " ".join(
                    f"{k}={v}" for k, v in self._plan_cache.stats().items()))
            if self.tier is not None:
                alog("[stats] tier: " + " ".join(
                    f"{k}={v}" for k, v in self.tier.report().items()))
        if not self.opts.stats_out:
            return []
        from ..parallel import control
        from ..utils.stats import write_stats
        written = write_stats(self.opts.stats_out, control.process_id(),
                              self.tracer, self.locality)
        if self.obs.enabled:
            # the full telemetry snapshot rides along (apps pass
            # --sys.stats.out; bench embeds the same dict in its JSON)
            import json
            import os
            p = os.path.join(self.opts.stats_out,
                             f"metrics.{control.process_id()}.json")
            with open(p, "w") as f:
                json.dump(self.metrics_snapshot(), f, indent=1,
                          default=float)
            written.append(p)
        return written

    # snapshot sections guaranteed present (possibly empty) in every
    # metrics_snapshot() — the schema-stability contract tests pin
    _SNAPSHOT_SECTIONS = ("kv", "prefetch", "plan_cache", "staging",
                          "sync", "pm", "collective", "fused", "spans",
                          "serve", "tier", "exec", "flight", "slo",
                          "fault", "ckpt", "device", "episode",
                          "wtrace", "replay", "decision", "policy",
                          "net", "stream")

    def metrics_snapshot(self, drain_device: bool = True) -> Dict:
        """One structured, JSON-serializable telemetry dict for this
        process (docs/OBSERVABILITY.md has the metric catalog). Schema:
        `schema_version`, `metrics_enabled`, and the fixed sections in
        `_SNAPSHOT_SECTIONS` — always present, `{}`-valued where the
        subsystem is off or `--sys.metrics 0`. This is the single source
        of truth the pre-existing ad-hoc surfaces (prefetch stats, plan
        cache stats, fused locality counts) are folded into; their old
        accessors remain as views.

        `drain_device=False` skips the fused-runner locality drain (a
        device readback, ~60 ms on a relay-attached backend) — for
        periodic callers; end-of-run callers keep the default.

        schema_version 2 (PR 3): `sync.keys_synced` now counts SHIPPED
        keys (post-dirty-filter; `sync.keys_shipped` is an alias), the
        new `sync.keys_considered` counts examined replicas, and the
        sync section gains `replicas_live`/`dirty_fraction` gauges
        (total + per channel).

        schema_version 3 (PR 4): new `serve` section — the online
        serving plane's qps/latency/queue/shed metrics plus the
        liveness/readiness surface (`serve.ready`, `serve.dead_peers`,
        and the embedded `readiness` detail dict when a ServePlane is
        attached); `{}` when no plane was ever built.

        schema_version 4 (PR 5): new `tier` section — the tiered-
        storage plane's hot-hit rate, promotions/demotions, hot-pool
        occupancy gauges, and the cold-serve latency histogram
        (`tier.cold_serve_s`); `{}` when --sys.tier is off.

        schema_version 5 (PR 6): new always-present `exec` section —
        the unified executor's per-stream queue-depth gauges
        (`exec.queue_depth.<stream>`), the enqueue->dispatch latency
        histogram (`exec.dispatch_wait_s`), program counters, and the
        `exec.overlap_fraction` gauge (fraction of busy executor wall
        time where >= 2 streams ran simultaneously — the
        transfer/compute-overlap measure).

        schema_version 6 (PR 7): new always-present `flight` and `slo`
        sections. `flight` — request-flight tracing (obs/flight.py):
        the per-request breakdown histograms (`queue_s` /
        `batch_wait_s` / `dispatch_s` / `device_s`), the freshness
        probe (`freshness_s`), trace/program counters, the tracer's
        minted/complete/dropped stats, and the executor
        flight-recorder summary (`recorder`, present whenever
        `--sys.crash_dumps` is on). `{}` when `--sys.trace.flight` is
        off and crash dumps are off too. `slo` — the closed-loop
        tail-latency controller (obs/slo.py, `--sys.serve.slo_ms`):
        target/effective-window/P99 gauges, tick/adjustment counters,
        and the bounded recent-adjustment log; `{}` when no SLO target
        is set.

        schema_version 7 (PR 8): the compression plane's gauges
        (ISSUE 8) — `sync.bytes_per_round` (wire bytes the most recent
        round shipped in the --sys.sync.compress format),
        `sync.bytes_shipped` / `sync.bytes_full_equiv` (cumulative
        wire vs full-width-f32-equivalent bytes — their ratio IS the
        compression factor), `sync.ef_residual_norm` (max-abs error-
        feedback residual parked by the last compressed round), and in
        the tier section `tier.cold_bytes_per_row` (actual host bytes
        per cold row: dense store + scale column + parked residuals)
        plus the `tier.ef_resid_rows` / `tier.ef_evicted` residual-map
        health pair.

        schema_version 8 (PR 9): the serve fast-path/tenancy surface
        (ISSUE 9) — `serve.replica_hit_rate` (fraction of coalesced
        batches served lock-free from the read-only replica snapshot),
        `serve.replica_hits_total` / `serve.replica_refreshes_total` /
        `serve.replica_stale_fallbacks_total` /  `serve.replica_rows`,
        per-dispatcher `serve.lane_depth.<i>` gauges, and — once
        tenants are configured — the per-tenant
        `serve.tenant.<name>.{served,shed,rejected}_total` counters.
        The readiness dict gains `dispatchers` /
        `wedged_dispatchers`. All present-but-inert at the default
        knobs (`--sys.serve.dispatchers 1`, no replica, no tenants).

        schema_version 9 (PR 10): always-present `fault` and `ckpt`
        sections (ISSUE 10). `fault` — the injection plane's seed,
        fired-injection totals and per-point eval/fire counts, plus
        the executor error policy's retries / cumulative backoff
        seconds and the watchdog's wedge-flip count; `{}` unless
        `--sys.fault.spec` names points. `ckpt` — the incremental
        checkpoint chain's save/base/delta counters, last link bytes
        and dirty-slot count, cumulative bytes, and — once a
        restore_chain ran on this server — `recovery_s`; `{}` unless a
        periodic checkpointer is attached or a restore ran. The
        readiness dict gains `degraded` (the restore-window shed
        reason, None when healthy) and `wedged_streams`.

        schema_version 10 (PR 12): always-present `device` and
        `episode` sections (ISSUE 14). `device` — the DevicePort's
        accounting: backend name, dispatched-program and quantized
        wire-ingest-row totals (adapm_tpu/device). `episode` —
        episodic-execution counters and prep/commit wall histograms
        (device/episode.py EpisodicRunner); `{}` until a runner is
        constructed.

        schema_version 11 (PR 13): always-present `wtrace` and
        `replay` sections (ISSUE 15). `wtrace` — workload trace
        capture (obs/wtrace.py, `--sys.trace.workload`): event /
        dropped / sampled-batch counters, bytes written, the trace
        path and buffered-event count; `{}` when capture is off (no
        recorder object, zero wtrace.* names). `replay` — populated
        on a server DRIVEN by the offline replay engine
        (adapm_tpu/replay): events replayed/skipped, the replay seed
        and logical speed, and the reads digest the determinism
        contract pins; `{}` everywhere else.

        schema_version 12 (PR 16): the serve section gains the fused
        bag-read counters (ISSUE 16; serve/bags.py) —
        `serve.bag_lookups_total` / `serve.bag_pooled_total` and the
        per-batch dispatch split `serve.bag_fused_total` /
        `serve.bag_hostpool_total` / `serve.bag_replica_hits_total` —
        and the device section gains the measured kernel-cost-table
        accounting (ops/costs.py, `--sys.costs.table`):
        `device.costs_consults_total` / `device.costs_overrides_total`
        / `device.costs_calibrations_total` and the
        `device.costs_entries` gauge, absent until a table is
        attached.

        schema_version 13 (PR 17): always-present `decision` section
        (ISSUE 17; obs/decisions.py, `--sys.trace.decisions`) — the
        decision telemetry plane's event/dropped counters, the
        per-plane regret counters and rates
        (`decision.promoted_never_hit`,
        `decision.replicated_never_read`, `decision.shipped_clean`,
        `decision.regret_rate.<plane>`), and the recorder's
        window-attribution stats (opened/resolved/forced + per-plane
        decided/resolved/regretted tallies); `{}` when capture is off
        (no recorder object, zero decision.* names). The spans section
        gains `spans.dropped` (registered while a SpanTracer exists):
        span-buffer overflow drops, counted loudly instead of silently
        capping at the old hardcoded 1M bound (now
        `--sys.trace.spans.max_events`).

        schema_version 14 (PR 18): always-present `policy` section
        (ISSUE 18; adapm_tpu/policy, `--sys.policy.*`) — the learned
        adaptive-policy plane's consult/veto counters
        (`policy.consults_total`, `policy.applied_total`,
        `policy.guard_vetoes_total`), the shadow A/B tallies
        (`policy.shadow_agree` / `policy.shadow_disagree`), and the
        plane's stats dict (per-plane mode/consults/vetoes/applied/
        guard-blocked/agree/disagree, the loaded artifact path, and
        the serve batch-window close-reason tallies); `{}` when no
        `--sys.policy.file` is set (no PolicyPlane object, zero
        policy.* names).

        schema_version 15 (PR 19): always-present `net` section
        (ISSUE 19; adapm_tpu/net) — the NetPort transport plane's
        frame accounting (`msgs_out/in`, `bytes_out/in`, per-family
        message counts, `retransmits`, `dup_suppressed`,
        `decode_errors`, `dropped_frames`) and the membership plane's
        peer states (`peers_live/dead/left/total`), beat/join/leave
        tallies, and failover record (`failovers`, `failover_s`,
        `promoted_keys`, `lost_keys`); `{}` on single-process and
        legacy-DCN servers (no plane object, zero net.* names —
        metrics_overhead_check.py pins default-off).

        schema_version 16 (PR 20): always-present `stream` section
        (ISSUE 20; adapm_tpu/stream) — the streaming plane's ingest
        accounting (acked-event `cursor`, `events_total` /
        `batches_total` / `acked_events_total` /
        `replayed_events_total`), the trainer's resume/batch/rate
        stats, and — with `--sys.stream.freshness_slo_ms` — the
        FreshnessSLO controller report (effective target, lever
        positions vs their static knobs, adjustment log); `{}` when no
        `--sys.stream.*` knob is set (no plane object, zero stream.*
        names — metrics_overhead_check.py pins default-off)."""
        out: Dict = {"schema_version": 16,
                     "metrics_enabled": bool(self.obs.enabled)}
        for s in self._SNAPSHOT_SECTIONS:
            out[s] = {}
        if not self.obs.enabled:
            return out
        serve_ready = None
        if self._serve_plane is not None:
            # probe readiness ONCE, BEFORE the registry snapshot: the
            # serve.ready/dead_peers gauges then read this result's
            # cache instead of each paying their own dead-peer probe
            # (multi-process, a probe is one coordinator KV read per
            # peer), and the gauges agree with the embedded dict below
            serve_ready = self._serve_plane.health.readiness()
        for sec, vals in self.obs.snapshot().items():
            out.setdefault(sec, {}).update(vals)
        # kv: worker-aggregated op/param counters + the ts=-1 rate
        agg: Dict[str, int] = {}
        with self._lock:
            workers = list(self._workers.values())
        for w in workers:
            for k, v in w.stats.items():
                agg[k] = agg.get(k, 0) + int(v)
        out["kv"].update(agg)
        po = agg.get("pull_ops", 0)
        out["kv"]["local_answer_frac"] = \
            (agg.get("pull_ops_local", 0) / po) if po else None
        if drain_device:
            out["kv"]["locality"] = self.locality_summary()
        if self.prefetch is not None:
            out["prefetch"].update(
                {k: int(v) for k, v in self.prefetch.report().items()})
        if self._plan_cache is not None:
            out["plan_cache"].update(self._plan_cache.stats())
        if self.glob is not None:
            with self.glob._stats_lock:
                out["pm"].update({k: int(v)
                                  for k, v in self.glob.stats.items()})
                out["pm"]["hops"] = [int(h) for h in self.glob.hops]
            if self.glob.coll is not None:
                out["collective"].update(
                    {f"bsp_{k}": int(v)
                     for k, v in self.glob.coll.stats.items()})
        if self.net is not None:
            out["net"].update(self.net.stats())
        if self.stream is not None:
            out["stream"].update(self.stream.stats())
        if self.spans is not None:
            out["spans"].update(self.spans.stats())
        # executor occupancy/overlap summary rides with the registry's
        # exec.* gauges (same numbers, one locked read)
        out["exec"].update(self.exec.stats())
        if self.stores:
            # device-plane accounting (ISSUE 14): the port's own stats
            # dict (incl. the backend name the gauges cannot carry)
            out["device"].update(self.stores[0].port.stats())
        if self.flight is not None:
            out["flight"].update(self.flight.stats())
        if self.flight_recorder is not None:
            out["flight"]["recorder"] = self.flight_recorder.summary()
        if self.wtrace is not None:
            out["wtrace"].update(self.wtrace.stats())
        if self.decisions is not None:
            out["decision"].update(self.decisions.stats())
        if self.policy is not None:
            out["policy"].update(self.policy.stats())
        if self.replay_stats is not None:
            out["replay"].update(self.replay_stats)
        if self._serve_plane is not None and \
                self._serve_plane.slo is not None:
            out["slo"].update(self._serve_plane.slo.report())
        # fault/ckpt (schema v9): populated only while the respective
        # plane exists — the sections stay {} (never absent) otherwise
        if self.fault is not None:
            out["fault"].update(self.fault.stats())
            out["fault"].update(self.exec.fault_stats())
        if self.ckpt is not None:
            out["ckpt"].update(self.ckpt.stats())
        if self._last_recovery_s is not None:
            out["ckpt"]["recovery_s"] = self._last_recovery_s
        if serve_ready is not None:
            # readiness detail rides with the serve.* gauges: dead peers
            # (Server.dead_nodes — detection-only), queue depth/bound,
            # and the human-readable not-ready reasons
            out["serve"]["readiness"] = serve_ready
        return out

    def write_trace(self) -> Optional[str]:
        """Export the span trace (Chrome trace-event JSON, Perfetto-
        loadable) when --sys.trace.spans is on; returns the path. Called
        by shutdown; callable earlier for a mid-run trace."""
        if self.spans is None:
            return None
        import os
        path = self.opts.trace_spans_out or os.path.join(
            self.opts.stats_out or ".",
            f"spans.{self.pid}.trace.json")
        return self.spans.export(path)

    def write_flight_trace(self) -> Optional[str]:
        """Export the request-flight trace (Perfetto flow-event JSON;
        docs/OBSERVABILITY.md "Follow one request") when
        --sys.trace.flight is on; returns the path. Called by shutdown;
        callable earlier for a mid-run export."""
        if self.flight is None:
            return None
        import os
        path = self.opts.trace_flight_out or os.path.join(
            self.opts.stats_out or ".",
            f"flight.{self.pid}.trace.json")
        return self.flight.export(path)

    def wait_sync(self) -> None:
        """Act on all signalled intents and complete a full sync round
        (reference WaitSync, coloc_kv_worker.h:517). Multi-process: the
        round ships cross-process deltas and intent requests; the full
        quiesce protocol is WaitSync -> Barrier -> WaitSync on every
        process (reference test_many_key_operations.cc:375-385)."""
        with self._round_lock:
            self.sync.run_round(force_intents=True, all_channels=True)
        self.block()

    def quiesce(self) -> None:
        wt = self.wtrace
        if wt is not None:
            # recorded at entry so replay re-drives the quiesce at the
            # same point in the op stream (docs/REPLAY.md)
            wt.record_quiesce()
        with self._round_lock:
            self.sync.quiesce()

    def collective_pull(self, keys) -> np.ndarray:
        """BSP pull through the device-collective exchange — EVERY process
        must call this together (parallel/pm.py collective_pull;
        --sys.collective_sync). Returns owner values, flat."""
        assert self.glob is not None, "single process: use Worker.pull"
        return self.glob.collective_pull(keys)

    def collective_push(self, keys, vals) -> None:
        """BSP additive push through the device-collective exchange — same
        collective contract as collective_pull."""
        assert self.glob is not None, "single process: use Worker.push"
        self.glob.collective_push(keys, vals)

    def read_main(self, keys) -> np.ndarray:
        """Debug/test/checkpoint: read current authoritative main-copy
        values (flat concat). Multi-process: remotely-owned keys are read
        from their owner over the DCN channel."""
        keys = np.asarray(keys, dtype=np.int64)
        if self.glob is None:
            with self._lock:
                if len(keys) >= self._BULK_READ_MIN:
                    return self._read_owned_bulk(keys)
                groups, _ = self._pull_main_only(keys)
            return self._assemble_flat(keys, groups)
        from ..parallel.pm import _fill_flat, _offsets
        lens = self.value_lengths[keys]
        offs = _offsets(lens)
        out = np.empty(offs[-1], dtype=np.float32)
        with self._lock:
            owned = self.ab.owner[keys] >= 0
            pos = np.nonzero(owned)[0]
            if len(pos):
                _fill_flat(out, offs, lens, pos,
                           self._read_owned_flat(keys[pos]))
        rem = np.nonzero(~owned)[0]
        if len(rem):
            flat_r, _ = self.glob.request_pull(keys[rem])
            _fill_flat(out, offs, lens, rem, flat_r)
        return out

    def _pull_main_only(self, keys: np.ndarray):
        ab = self.ab
        groups = []
        for cid, pos in self._group_by_class(keys):
            ks = keys[pos]
            o_sh = ab.owner[ks].astype(np.int32)
            o_sl = ab.slot[ks].astype(np.int32)
            n = len(ks)
            vals = self.stores[cid].gather(
                o_sh, o_sl, np.zeros(n, np.int32),
                np.full(n, OOB, np.int32), np.zeros(n, bool))
            groups.append((cid, pos, self.value_lengths[ks], vals, n))
        return groups, 0

    def _assemble_flat(self, keys: np.ndarray, groups,
                       remote=None) -> np.ndarray:
        from ..parallel.pm import _fill_flat, _offsets
        lens = self.value_lengths[keys]
        offs = _offsets(lens)
        out = np.empty(offs[-1], dtype=np.float32)
        for cid, pos, klens, vals, n in groups:
            # one strided/fancy-indexed write per class, never per key
            _fill_flat(out, offs, lens, np.asarray(pos),
                       np.asarray(vals)[:n].ravel())
        if remote is not None:
            rem_pos, fut = remote
            _fill_flat(out, offs, lens, rem_pos, fut.result())
        return out


class Worker:
    """Reference ColoKVWorker (coloc_kv_worker.h). One per logical worker;
    mapped to mesh shard `worker_id % num_shards` (co-location)."""

    def __init__(self, server: Server, worker_id: int):
        self.server = server
        self.worker_id = worker_id
        self.shard = worker_id % server.num_shards
        # seed from the server's clock table so a worker registered after a
        # checkpoint restore resumes at the restored clock instead of
        # regressing it to 0 on its first advance
        self._clock = int(server._clocks[worker_id])
        self._ts = 0
        self._pending: Dict[int, _WaitEntry] = {}
        from .intent import IntentQueue
        self._intent_queue = IntentQueue()
        # outstanding cross-process write futures (read-your-writes: remote
        # pulls are ordered after them, see Server._pull's `after`)
        self._write_futs: List = []
        # locality stats (reference coloc_kv_server.h:147-157)
        self.stats = {"pull_ops": 0, "pull_ops_local": 0,
                      "pull_params": 0, "pull_params_local": 0,
                      "push_ops": 0, "push_ops_local": 0,
                      "push_params": 0, "push_params_local": 0}
        # kv op latency histograms (shared across workers; obs/metrics).
        # None with --sys.metrics 0 so the hot path skips even the
        # perf_counter bracketing.
        if server.obs.enabled:
            self._h_pull = server.obs.histogram("kv.pull_s", shared=True)
            self._h_push = server.obs.histogram("kv.push_s", shared=True)
            self._h_set = server.obs.histogram("kv.set_s", shared=True)
        else:
            self._h_pull = self._h_push = self._h_set = None

    # -- value plumbing ------------------------------------------------------

    def _keys(self, keys) -> np.ndarray:
        return np.ascontiguousarray(np.asarray(keys, dtype=np.int64).ravel())

    def _new_ts(self, entry: _WaitEntry) -> int:
        self._ts += 1
        self._pending[self._ts] = entry
        return self._ts

    # -- API: Pull / Push / Set ----------------------------------------------

    def _live_write_futs(self):
        self._write_futs = [f for f in self._write_futs if not f.done()]
        return list(self._write_futs)

    def _instrumented(self, name: str, h, impl, *args):
        """Latency-histogram + span + flight bracket for a worker op;
        degrades to a plain call when metrics, spans, and flight
        tracing are all off (the skip-wrapper discipline: each disabled
        layer costs one `is None` check here)."""
        sp = self.server.spans
        fl = self.server.flight
        if h is None and sp is None and fl is None:
            return impl(*args)
        t0 = _time.perf_counter()
        tok = sp.begin(name) if sp is not None else None
        try:
            return impl(*args)
        finally:
            if h is not None:
                h.observe(_time.perf_counter() - t0)
            if tok is not None:
                sp.end(name, tok)
            if fl is not None:
                # a plain Worker op is a single-segment flight: one
                # minted id, one slice on the caller's thread
                fl.record_op(name, t0)

    def _cached_push_routes(self, keys: np.ndarray, tv: int, is_set: bool):
        """Route skeleton for push/set through the plan cache (values are
        applied per call; routes only change with the topology)."""
        srv = self.server
        return srv._plan_cached(
            "set" if is_set else "push", self.shard, keys, tv,
            lambda: srv._plan_push_routes(keys, self.shard, is_set=is_set))

    def pull(self, keys, out: Optional[np.ndarray] = None) -> int:
        """Async pull. Returns ts (use wait) or LOCAL=-1 if every key was
        served from this worker's shard (owned or replicated) — in that case
        `out` is already filled when provided.

        Fast path: a batch this worker declared intent for may have been
        pre-gathered by the prefetch pipeline (core/intent.py); the pull
        then consumes the staged device buffers directly — no planning,
        no server lock, no dispatch. Validity (topology unchanged since
        the gather, no intersecting write) was enforced by the pipeline,
        so a staged hit is bit-identical to the pull it replaced."""
        return self._instrumented("kv.pull", self._h_pull,
                                  self._pull_op, keys, out)

    def _pull_op(self, keys, out: Optional[np.ndarray]) -> int:
        keys = self._keys(keys)
        srv = self.server
        wt = srv.wtrace  # bind-once, test-once (APM003 skip-wrapper)
        if wt is not None:
            wt.record_kv("pull", self.worker_id, self._clock, keys)
        if srv.prefetch is not None:
            st = srv.prefetch.take_staged(self, keys)
            if st is not None:
                self.stats["pull_ops"] += 1
                self.stats["pull_params"] += len(keys)
                self.stats["pull_params_local"] += len(keys) - st.n_remote
                entry = _WaitEntry(groups=st.groups, out=out, keys=keys)
                if st.n_remote == 0:
                    self.stats["pull_ops_local"] += 1
                    self._finish_pull(keys, entry)
                    return LOCAL
                return self._new_ts(entry)
        after = self._live_write_futs() if srv.glob is not None else ()
        plan, tv = None, -1
        if srv.opts.optimistic_routing:
            # route + stage outside the lock; revalidate the topology
            # below (reference: per-key lock array lets N worker threads
            # route concurrently, handle.h:1069-1083). Identical batches
            # skip planning entirely via the plan cache.
            tv = srv.topology_version
            plan = srv._plan_cached(
                "pull", self.shard, keys, tv,
                lambda: srv._plan_pull(keys, self.shard))
        with srv._lock:
            if plan is not None and srv.topology_version != tv:
                plan = None  # topology moved underneath us: re-plan
            groups, n_remote, remote = srv._pull(keys, self.shard,
                                                 after=after, plan=plan)
        self.stats["pull_ops"] += 1
        self.stats["pull_params"] += len(keys)
        self.stats["pull_params_local"] += len(keys) - n_remote
        entry = _WaitEntry(groups=groups, out=out, keys=keys, remote=remote)
        if n_remote == 0:
            self.stats["pull_ops_local"] += 1
            self._finish_pull(keys, entry)
            return LOCAL
        return self._new_ts(entry)

    def pull_sync(self, keys) -> np.ndarray:
        """Pull and materialize; returns flat values (or [B, L] when the
        batch is single-class and `reshape` fits)."""
        keys = self._keys(keys)
        ts = self.pull(keys)
        if ts == LOCAL:
            flat = self._last_result
        else:
            flat = self.wait(ts)
        lens = self.server.value_lengths[keys]
        if len(np.unique(lens)) == 1:
            return flat.reshape(len(keys), int(lens[0]))
        return flat

    def _finish_pull(self, keys, entry: _WaitEntry) -> np.ndarray:
        flat = self.server._assemble_flat(keys, entry.groups,
                                          remote=entry.remote)
        if entry.out is not None:
            np.copyto(entry.out.reshape(-1)[: len(flat)], flat)
        self._last_result = flat
        return flat

    def pull_if_local(self, keys, out: Optional[np.ndarray] = None):
        """Pull only if all keys are local (reference PullIfLocal,
        coloc_kv_worker.h:352). Returns (success, values|None)."""
        keys = self._keys(keys)
        srv = self.server
        with srv._lock:
            if not bool(srv.ab.is_local(keys, self.shard).all()):
                return False, None
            groups, _, _ = srv._pull(keys, self.shard)
        entry = _WaitEntry(groups=groups, out=out)
        return True, self._finish_pull(keys, entry)

    def push(self, keys, vals, asynchronous: bool = True) -> int:
        """Additive push (reference Push, coloc_kv_worker.h:120). vals is a
        flat buffer or [B, L]. Returns ts or LOCAL."""
        return self._instrumented("kv.push", self._h_push,
                                  self._push_op, keys, vals)

    def _push_op(self, keys, vals) -> int:
        keys = self._keys(keys)
        vals = np.asarray(vals, dtype=np.float32)
        srv = self.server
        wt = srv.wtrace
        if wt is not None:
            wt.record_kv("push", self.worker_id, self._clock, keys)
        probe = None
        fl = srv.flight  # bind-once, test-once (APM003 skip-wrapper)
        if fl is not None:
            # event-to-servable freshness probe (sampled): push wall
            # time -> first serve read of the key (obs/flight.py);
            # marked visible under the lock once the scatter enqueues
            probe = fl.freshness.note_push(keys)
        after = self._live_write_futs() if srv.glob is not None else ()
        plan, tv = None, -1
        if srv.opts.optimistic_routing:
            tv = srv.topology_version
            plan = srv._plan_push(
                keys, vals, self.shard, is_set=False,
                routes=self._cached_push_routes(keys, tv, is_set=False))
        with srv._lock:
            if plan is not None and srv.topology_version != tv:
                plan = None
            n_remote, futs = srv._push(keys, vals, self.shard,
                                       is_set=False, after=after,
                                       plan=plan)
            if probe is not None:
                fl.freshness.push_visible(probe)
        self.stats["push_ops"] += 1
        self.stats["push_params"] += len(keys)
        self.stats["push_params_local"] += len(keys) - n_remote
        self._write_futs.extend(futs)
        if n_remote == 0:
            self.stats["push_ops_local"] += 1
            return LOCAL
        return self._new_ts(_WaitEntry(is_write=True, futures=futs))

    def staggered_push(self, keys, vals, group_size: int = 100_000) -> int:
        """Push a large key set in groups (reference StaggeredPush,
        coloc_kv_worker.h:556-580: bounds per-request buffering when
        pushing e.g. a whole initial model). Returns the last group's ts."""
        keys = self._keys(keys)
        vals = np.asarray(vals, dtype=np.float32)
        flat = vals.ndim == 1
        if flat:
            cum = np.zeros(len(keys) + 1, dtype=np.int64)
            np.cumsum(self.server.value_lengths[keys], out=cum[1:])
        ts = LOCAL
        for lo in range(0, len(keys), group_size):
            hi = min(lo + group_size, len(keys))
            part = vals[cum[lo]:cum[hi]] if flat else vals[lo:hi]
            ts = self.push(keys[lo:hi], part)
        return ts

    def set(self, keys, vals) -> int:
        """Overwrite values (reference Set: non-additive write)."""
        return self._instrumented("kv.set", self._h_set,
                                  self._set_op, keys, vals)

    def _set_op(self, keys, vals) -> int:
        import contextlib
        keys = self._keys(keys)
        vals = np.asarray(vals, dtype=np.float32)
        srv = self.server
        wt = srv.wtrace
        if wt is not None:
            wt.record_kv("set", self.worker_id, self._clock, keys)
        after = self._live_write_futs() if srv.glob is not None else ()
        # Set may invalidate (consume the delta of) cross-process replicas;
        # that must not interleave with an in-flight sync round's extracted
        # delta (pm.py delta_window; taken BEFORE the server lock)
        dm = srv.glob.delta_window_for(keys) if srv.glob is not None \
            else contextlib.nullcontext()
        plan, tv = None, -1
        if srv.opts.optimistic_routing:
            tv = srv.topology_version
            plan = srv._plan_push(
                keys, vals, self.shard, is_set=True,
                routes=self._cached_push_routes(keys, tv, is_set=True))
        with dm:
            with srv._lock:
                if plan is not None and srv.topology_version != tv:
                    plan = None
                n_remote, futs = srv._push(keys, vals, self.shard,
                                           is_set=True, after=after,
                                           plan=plan)
        self._write_futs.extend(futs)
        if n_remote == 0:
            return LOCAL
        return self._new_ts(_WaitEntry(is_write=True, futures=futs))

    # -- API: waiting ---------------------------------------------------------

    def wait(self, ts: int):
        """Block until op `ts` is complete; for pulls returns/fills values."""
        if ts == LOCAL:
            return getattr(self, "_last_result", None)
        entry = self._pending.pop(ts, None)
        if entry is None:
            return None
        if entry.groups or entry.remote is not None:
            return self._finish_pull(entry.keys, entry)
        # write op: dispatch order serializes programs on the pool buffers,
        # so blocking on the current pools covers this op; cross-process
        # writes complete when their futures resolve
        for f in entry.futures:
            f.result()
        self.server.block()
        return None

    def wait_all(self) -> None:
        for ts in sorted(self._pending.keys()):
            self.wait(ts)

    def is_finished(self, ts: int) -> bool:
        """Non-blocking completion check (reference IsFinished)."""
        if ts == LOCAL or ts not in self._pending:
            return True
        entry = self._pending[ts]
        if not all(f.done() for f in entry.futures):
            return False
        if entry.remote is not None and not entry.remote[1].done():
            return False
        if entry.is_write:
            with self.server._lock:
                return all(s.main.is_ready() and s.delta.is_ready()
                           for s in self.server.stores)
        return all(g[3].is_ready() for g in entry.groups)

    def wait_sync(self) -> None:
        self.server.wait_sync()

    # -- API: intent + clock --------------------------------------------------

    def intent(self, keys, start: int, end: Optional[int] = None) -> None:
        """Declare future access to `keys` in clock window [start, end]
        (reference Intent, coloc_kv_worker.h:380-408; end defaults to
        start). With the prefetch pipeline on, the declaration also
        queues background staging: a later `pull` of exactly this
        (unique, sorted) key batch inside the window can be served from
        a pre-gathered staged buffer."""
        keys = np.unique(self._keys(keys))
        end = start if end is None else end
        srv = self.server
        wt = srv.wtrace
        if wt is not None:
            wt.record_intent(self.worker_id, self._clock, keys,
                             int(start), int(end))
        self._intent_queue.push(keys, int(start), int(end))
        if srv.prefetch is not None:
            srv.prefetch.on_intent(self, keys, int(start), int(end))

    def advance_clock(self) -> int:
        self._clock += 1
        self.server._clocks[self.worker_id] = self._clock
        wt = self.server.wtrace
        if wt is not None:
            wt.record_clock(self.worker_id, self._clock)
        return self._clock

    @property
    def current_clock(self) -> int:
        return self._clock

    # -- API: sampling --------------------------------------------------------

    def prepare_sample(self, n: int, start: Optional[int] = None,
                       end: Optional[int] = None) -> int:
        """Reference PrepareSample (coloc_kv_worker.h:418): announce that this
        worker will sample `n` keys around clock [start, end]."""
        start = self._clock if start is None else start
        end = start if end is None else end
        h = self.server.sampling.prepare(self, n, int(start), int(end))
        wt = self.server.wtrace
        if wt is not None:
            wt.record_sample("prep_sample", self.worker_id, self._clock,
                             h, n, int(start), int(end))
        return h

    def pull_sample(self, handle: int, n: Optional[int] = None):
        """Draw n keys (default: all prepared) from sampling handle; returns
        (keys, values[B, L])."""
        wt = self.server.wtrace
        if wt is not None:
            wt.record_sample("pull_sample", self.worker_id, self._clock,
                             handle, n)
        return self.server.sampling.pull(self, handle, n)

    def pull_sample_keys(self, handle: int, n: Optional[int] = None):
        """Draw n keys without fetching values (for fused steps that gather
        values themselves); locality behavior matches pull_sample."""
        return self.server.sampling.pull_keys(self, handle, n)

    def finish_sample(self, handle: int) -> None:
        wt = self.server.wtrace
        if wt is not None:
            wt.record_sample("finish_sample", self.worker_id,
                             self._clock, handle, None)
        self.server.sampling.finish(self, handle)

    # -- API: lifecycle -------------------------------------------------------

    def barrier(self) -> None:
        """Barrier with every other active worker (all threads, all
        processes) — reference ColoKVWorker::Barrier.

        Note this is an ALL-WORKER rendezvous, not a per-process barrier
        (changed from the pre-r3 semantics): with a declared num_workers,
        every declared worker must eventually barrier or finalize, or the
        barrier stalls (a periodic warning names the absent ids)."""
        self.server.worker_barrier(self.worker_id)

    def begin_setup(self) -> None:
        """Bracket initialization (reference BeginSetup/EndSetup): sync is
        paused so bulk Set/Push of initial values runs at full speed."""
        self.server._in_setup = True

    def end_setup(self) -> None:
        self.server._in_setup = False
        self.server.barrier()

    def finalize(self) -> None:
        """Mark worker finished (reference Finalize): clock to infinity so
        its intents expire and replicas can be dropped."""
        self.wait_all()
        self._clock = WORKER_FINISHED
        self.server._clocks[self.worker_id] = WORKER_FINISHED
        # workers blocked in a barrier must re-evaluate the participant set
        with self.server._wb_cond:
            self.server._wb_cond.notify_all()
