"""Host-side ownership metadata: the reference's Addressbook reborn.

Per key the reference tracks (addressbook.h):
  - manager (home) shard = key % S              (addressbook.h:110-112)
  - current owner (dense vector at the manager)  (addressbook.h:151)
  - relocation counters to reject stale updates  (addressbook.h:92-102)
  - optional location cache                      (addressbook.h:114-133)

In the single-controller TPU design the addressbook is a set of host numpy
tables shared by the planner and every local worker (one authoritative copy
per controller process, so the manager/owner/location-cache distinction
collapses locally; across hosts the control plane keeps them consistent). It
additionally owns slot allocation: every key maps to a (shard, slot) row in
its length class's device pool, and replicas map to (shard, cache slot).

Keys may have different value lengths (reference `get_len`,
coloc_kv_server_handle.h:996-999); keys are grouped into *length classes*,
each backed by its own pooled store, so `slot` is a row index within the
key's class pool.

Everything here is O(1) or vectorized per *batch*, never per key in Python —
the reference's addressbook is O(1)/key in C++ (addressbook.h:110-151), and a
5M-key Wikidata5M-scale table must construct in seconds, not minutes.
"""
from __future__ import annotations

from typing import List, Sequence

import numpy as np

from ..base import NO_SLOT, REMOTE


class SlotAllocator:
    """Per-shard allocator over pool slots.

    A fresh-slot watermark plus a LIFO free list of returned slots: O(1)
    construction (no materialized range lists — at 5M slots per shard those
    alone would cost hundreds of MB) and O(batch) alloc/free.
    """

    def __init__(self, num_shards: int, slots_per_shard: int):
        self.num_shards = num_shards
        self.slots_per_shard = slots_per_shard
        # slots [watermark, slots_per_shard) have never been handed out
        self._watermark = np.zeros(num_shards, dtype=np.int64)
        self._returned: List[List[int]] = [[] for _ in range(num_shards)]

    def set_watermark(self, counts: np.ndarray) -> None:
        """Mark the first counts[s] slots of each shard as allocated (bulk
        initial allocation; callers assign those slots contiguously)."""
        assert (counts <= self.slots_per_shard).all()
        self._watermark[:] = counts

    def alloc(self, shard: int) -> int:
        ret = self._returned[shard]
        if ret:
            return ret.pop()
        w = int(self._watermark[shard])
        if w >= self.slots_per_shard:
            raise RuntimeError(
                f"shard {shard} out of pool slots ({self.slots_per_shard}); "
                "increase the pool over-allocation factor")
        self._watermark[shard] = w + 1
        return w

    def alloc_batch(self, shard: int, n: int) -> np.ndarray:
        """Allocate up to n slots (returns fewer when the pool runs out)."""
        n = min(n, self.num_free(shard))
        ret = self._returned[shard]
        take = min(n, len(ret))
        out = np.empty(n, dtype=np.int64)
        if take:
            out[:take] = ret[len(ret) - take:]
            del ret[len(ret) - take:]
        fresh = n - take
        if fresh:
            w = int(self._watermark[shard])
            out[take:] = np.arange(w, w + fresh)
            self._watermark[shard] = w + fresh
        return out

    def free(self, shard: int, slot: int) -> None:
        self._returned[shard].append(int(slot))

    def free_batch(self, shard: int, slots: np.ndarray) -> None:
        self._returned[shard].extend(np.asarray(slots).tolist())

    def num_free(self, shard: int) -> int:
        return (self.slots_per_shard - int(self._watermark[shard])
                + len(self._returned[shard]))

    def set_used(self, shard: int, used: np.ndarray) -> None:
        """Reset one shard so exactly `used` slots are allocated (checkpoint
        restore): watermark just past the highest used slot, gaps below it
        on the returned list."""
        used = np.asarray(used, dtype=np.int64)
        if len(used) == 0:
            self._watermark[shard] = 0
            self._returned[shard] = []
            return
        w = int(used.max()) + 1
        assert w <= self.slots_per_shard, \
            f"used slot {w - 1} outside pool of {self.slots_per_shard}"
        gap = np.ones(w, dtype=bool)
        gap[used] = False
        self._watermark[shard] = w
        self._returned[shard] = np.nonzero(gap)[0].tolist()


class Addressbook:
    """Global key → location tables over all length classes.

    Multi-process (num_procs > 1): the key space is partitioned over
    `num_procs * num_shards` *global* shards; this process's tables cover
    only the keys whose global home shard lands here. Keys owned by another
    process carry `owner == REMOTE` (and no slot) — the cross-process layer
    (parallel/pm.py GlobalPM) routes those, mirroring the reference split
    between the per-node store and the manager/owner metadata
    (addressbook.h:110-151)."""

    def __init__(self, key_class: np.ndarray, num_shards: int,
                 main_slots: Sequence[int], cache_slots: Sequence[int],
                 num_procs: int = 1, pid: int = 0):
        num_keys = len(key_class)
        self.num_keys = num_keys
        self.num_shards = num_shards
        self.num_procs = num_procs
        self.pid = pid
        self.key_class = key_class.astype(np.int32)
        # main copy location: owner shard + slot within the class pool;
        # REMOTE = owned by another process
        self.owner = np.full(num_keys, REMOTE, dtype=np.int32)
        self.slot = np.full(num_keys, NO_SLOT, dtype=np.int32)
        # replica locations: cache_slot[shard, key] = class-pool cache slot
        self.cache_slot = np.full((num_shards, num_keys), NO_SLOT,
                                  dtype=np.int32)
        self.replica_count = np.zeros(num_keys, dtype=np.int32)
        # bumped on every ownership move; rejects stale location info in the
        # multi-host control plane (reference addressbook.h:92-102)
        self.relocation_counter = np.zeros(num_keys, dtype=np.int32)
        # counted placement mutations (replica add/drop, relocation,
        # adopt/abandon) — paired with topology_version bumps by
        # Server._topology_mutation's discipline assertion; the initial
        # allocation below is construction, not a mutation
        self.mutations = 0

        self.main_alloc = [SlotAllocator(num_shards, m) for m in main_slots]
        self.cache_alloc = [SlotAllocator(num_shards, c) for c in cache_slots]

        # initial allocation, vectorized: global home shard = key % (S*P)
        # (reference manager = key % num_servers, addressbook.h:110-112);
        # within (class, local shard) keys take consecutive slots in key order
        gs = num_shards * num_procs
        single_class = len(self.main_alloc) == 1
        for cid, alloc in enumerate(self.main_alloc):
            if single_class:
                # fast path (uniform value lengths, the common case): keys
                # with the same global home shard are k ≡ g (mod S*P), so
                # the rank within the group is k // (S*P)
                g = np.arange(num_keys) % gs
                owned = (g // num_shards) == pid
                lsh = (g % num_shards).astype(np.int32)
                self.owner[:] = np.where(owned, lsh, REMOTE)
                self.slot[:] = np.where(owned, np.arange(num_keys) // gs,
                                        NO_SLOT)
                alloc.set_watermark(
                    np.bincount(lsh[owned], minlength=num_shards))
                continue
            keys_c = np.nonzero(self.key_class == cid)[0]
            g = keys_c % gs
            keys_c = keys_c[(g // num_shards) == pid]
            if len(keys_c) == 0:
                alloc.set_watermark(np.zeros(num_shards, dtype=np.int64))
                continue
            home = ((keys_c % gs) % num_shards).astype(np.int32)
            counts = np.zeros(num_shards, dtype=np.int64)
            for h in range(num_shards):  # S masked passes beat an argsort
                grp = keys_c[home == h]
                counts[h] = len(grp)
                self.owner[grp] = h
                self.slot[grp] = np.arange(len(grp))
            alloc.set_watermark(counts)

    # -- queries ------------------------------------------------------------
    def home(self, key: int) -> int:
        return int(key) % self.num_shards

    def is_local(self, keys: np.ndarray, shard: int) -> np.ndarray:
        """True per key if shard holds the main copy or a replica."""
        return (self.owner[keys] == shard) | (
            self.cache_slot[shard, keys] != NO_SLOT)

    def has_replica(self, keys: np.ndarray, shard: int) -> np.ndarray:
        return self.cache_slot[shard, keys] != NO_SLOT

    def replica_shards(self, key: int) -> np.ndarray:
        return np.nonzero(self.cache_slot[:, key] != NO_SLOT)[0]

    # -- replica bookkeeping -------------------------------------------------
    def add_replica(self, key: int, shard: int) -> int:
        cs = self.add_replicas(np.asarray([key], dtype=np.int64), shard)
        if len(cs) == 0:
            cls = int(self.key_class[key])
            raise RuntimeError(
                f"shard {shard} out of cache pool slots "
                f"({self.cache_alloc[cls].slots_per_shard}); increase "
                "cache_slots_per_shard")
        return int(cs[0])

    def add_replicas(self, keys: np.ndarray, shard: int) -> np.ndarray:
        """Allocate cache slots for `keys` (all same class, none already
        replicated on `shard`); returns the slots. Capacity-bounded: only
        the first num_free keys get slots; the returned array may be
        shorter than `keys` (callers truncate their batch accordingly)."""
        assert (self.cache_slot[shard, keys] == NO_SLOT).all()
        cls = self.key_class[keys]
        assert len(keys) == 0 or (cls == cls[0]).all(), \
            "add_replicas batch must be single-class"
        if len(keys) == 0:
            return np.empty(0, dtype=np.int64)
        alloc = self.cache_alloc[int(cls[0])]
        cs = alloc.alloc_batch(shard, len(keys))
        taken = keys[: len(cs)]
        if len(taken):
            self.mutations += 1
        self.cache_slot[shard, taken] = cs
        self.replica_count[taken] += 1
        return cs

    def drop_replica(self, key: int, shard: int) -> int:
        cs = int(self.cache_slot[shard, key])
        assert cs != NO_SLOT
        self.drop_replicas(np.asarray([key], dtype=np.int64), shard)
        return cs

    def drop_replicas(self, keys: np.ndarray, shard: int) -> None:
        """Free the cache slots of `keys` on `shard` (single class)."""
        if len(keys) == 0:
            return
        cs = self.cache_slot[shard, keys]
        assert (cs != NO_SLOT).all()
        cls = self.key_class[keys]
        assert (cls == cls[0]).all(), \
            "drop_replicas batch must be single-class"
        self.mutations += 1
        self.cache_slot[shard, keys] = NO_SLOT
        self.replica_count[keys] -= 1
        self.cache_alloc[int(cls[0])].free_batch(shard, cs)

    # -- relocation ----------------------------------------------------------
    def relocate(self, key: int, new_shard: int) -> tuple[int, int, int]:
        """Move ownership of `key` to `new_shard`. Returns
        (old_shard, old_slot, new_slot); the device row move is the caller's
        job (Server.relocate). Host metadata only."""
        old_shard = int(self.owner[key])
        old_slot = int(self.slot[key])
        assert old_shard != new_shard
        alloc = self.main_alloc[self.key_class[key]]
        new_slot = alloc.alloc(new_shard)
        self.mutations += 1
        self.owner[key] = new_shard
        self.slot[key] = new_slot
        alloc.free(old_shard, old_slot)
        self.relocation_counter[key] += 1
        return old_shard, old_slot, new_slot

    def adopt_batch(self, keys: np.ndarray, shard: int):
        """Cross-process relocation, requester side: this process takes
        ownership of `keys` (currently REMOTE, single class), preferring
        local `shard` and SPILLING OVER to sibling shards when its pool
        is full (reads reach sibling shards through the cross-shard
        gather, so spillover trades some intra-process locality, never
        correctness). Returns (shards, slots). Raises only if the whole
        process is out of pool — impossible by construction: per-shard
        pools are over-allocated so their sum exceeds the class size."""
        if len(keys) == 0:
            e = np.empty(0, dtype=np.int64)
            return e, e
        assert (self.owner[keys] == REMOTE).all(), \
            "adopt_batch keys must be remotely owned"
        cls = self.key_class[keys]
        assert (cls == cls[0]).all(), "adopt_batch must be single-class"
        alloc = self.main_alloc[int(cls[0])]
        sh_out = np.empty(len(keys), dtype=np.int64)
        sl_out = np.empty(len(keys), dtype=np.int64)
        order = [shard] + sorted(
            (s for s in range(self.num_shards) if s != shard),
            key=alloc.num_free, reverse=True)
        pos = 0
        for s in order:
            if pos >= len(keys):
                break
            slots = alloc.alloc_batch(s, len(keys) - pos)
            sh_out[pos:pos + len(slots)] = s
            sl_out[pos:pos + len(slots)] = slots
            pos += len(slots)
        if pos < len(keys):
            raise RuntimeError(
                f"process out of main pool slots while adopting "
                f"{len(keys) - pos} relocated keys; increase over_alloc")
        self.mutations += 1
        self.owner[keys] = sh_out
        self.slot[keys] = sl_out
        self.relocation_counter[keys] += 1
        return sh_out, sl_out

    def abandon_batch(self, keys: np.ndarray) -> None:
        """Cross-process relocation, owner side: release ownership of
        locally-owned `keys` (single class) — their main copies move to
        another process. Frees the main slots; owner becomes REMOTE."""
        if len(keys) == 0:
            return
        cls = self.key_class[keys]
        assert (cls == cls[0]).all(), "abandon_batch must be single-class"
        sh = self.owner[keys]
        sl = self.slot[keys]
        assert (sh >= 0).all(), "abandon_batch keys must be locally owned"
        alloc = self.main_alloc[int(cls[0])]
        self.mutations += 1
        for s in np.unique(sh):
            alloc.free_batch(int(s), sl[sh == s])
        self.owner[keys] = REMOTE
        self.slot[keys] = NO_SLOT
        self.relocation_counter[keys] += 1

    def relocate_batch(self, keys: np.ndarray, new_shard: int) -> tuple:
        """Move ownership of `keys` (single class, none already owned by
        `new_shard`) to `new_shard`. Capacity-bounded like add_replicas:
        only the first num_free keys move. Returns
        (moved_keys, old_shards, old_slots, new_slots)."""
        if len(keys) == 0:
            e = np.empty(0, dtype=np.int64)
            return e, e, e, e
        cls = self.key_class[keys]
        assert (cls == cls[0]).all(), "relocate_batch must be single-class"
        alloc = self.main_alloc[int(cls[0])]
        new_slots = alloc.alloc_batch(new_shard, len(keys))
        moved = keys[: len(new_slots)]
        if len(moved):
            self.mutations += 1
        old_shards = self.owner[moved].astype(np.int64)
        old_slots = self.slot[moved].astype(np.int64)
        assert (old_shards != new_shard).all()
        self.owner[moved] = new_shard
        self.slot[moved] = new_slots
        self.relocation_counter[moved] += 1
        # free per old shard (grouped, not per key)
        for s in np.unique(old_shards):
            alloc.free_batch(int(s), old_slots[old_shards == s])
        return moved, old_shards, old_slots, new_slots
