"""Host-side ownership metadata: the reference's Addressbook reborn.

Per key the reference tracks (addressbook.h):
  - manager (home) shard = key % S              (addressbook.h:110-112)
  - current owner (dense vector at the manager)  (addressbook.h:151)
  - relocation counters to reject stale updates  (addressbook.h:92-102)
  - optional location cache                      (addressbook.h:114-133)

In the single-controller TPU design the addressbook is a set of host numpy
tables shared by the planner and every local worker (one authoritative copy
per controller process, so the manager/owner/location-cache distinction
collapses locally; across hosts the control plane keeps them consistent). It
additionally owns slot allocation: every key maps to a (shard, slot) row in
its length class's device pool, and replicas map to (shard, cache slot).

Keys may have different value lengths (reference `get_len`,
coloc_kv_server_handle.h:996-999); keys are grouped into *length classes*,
each backed by its own pooled store, so `slot` is a row index within the
key's class pool.
"""
from __future__ import annotations

from typing import List, Sequence

import numpy as np

from ..base import NO_SLOT


class SlotAllocator:
    """Per-shard free-list over pool slots (LIFO for allocation locality)."""

    def __init__(self, num_shards: int, slots_per_shard: int):
        self.num_shards = num_shards
        self.slots_per_shard = slots_per_shard
        self._free: List[List[int]] = [
            list(range(slots_per_shard - 1, -1, -1)) for _ in range(num_shards)
        ]

    def alloc(self, shard: int) -> int:
        free = self._free[shard]
        if not free:
            raise RuntimeError(
                f"shard {shard} out of pool slots ({self.slots_per_shard}); "
                "increase the pool over-allocation factor")
        return free.pop()

    def free(self, shard: int, slot: int) -> None:
        self._free[shard].append(slot)

    def num_free(self, shard: int) -> int:
        return len(self._free[shard])


class Addressbook:
    """Global key → location tables over all length classes."""

    def __init__(self, key_class: np.ndarray, num_shards: int,
                 main_slots: Sequence[int], cache_slots: Sequence[int]):
        num_keys = len(key_class)
        num_classes = len(main_slots)
        self.num_keys = num_keys
        self.num_shards = num_shards
        self.key_class = key_class.astype(np.int32)
        # main copy location: owner shard + slot within the class pool
        self.owner = np.empty(num_keys, dtype=np.int32)
        self.slot = np.full(num_keys, NO_SLOT, dtype=np.int32)
        # replica locations: cache_slot[shard, key] = class-pool cache slot
        self.cache_slot = np.full((num_shards, num_keys), NO_SLOT,
                                  dtype=np.int32)
        self.replica_count = np.zeros(num_keys, dtype=np.int32)
        # bumped on every ownership move; rejects stale location info in the
        # multi-host control plane (reference addressbook.h:92-102)
        self.relocation_counter = np.zeros(num_keys, dtype=np.int64)

        self.main_alloc = [SlotAllocator(num_shards, m) for m in main_slots]
        self.cache_alloc = [SlotAllocator(num_shards, c) for c in cache_slots]

        # initial allocation: home shard = key % S (addressbook.h:110-112)
        for k in range(num_keys):
            h = k % num_shards
            self.owner[k] = h
            self.slot[k] = self.main_alloc[self.key_class[k]].alloc(h)

    # -- queries ------------------------------------------------------------
    def home(self, key: int) -> int:
        return int(key) % self.num_shards

    def is_local(self, keys: np.ndarray, shard: int) -> np.ndarray:
        """True per key if shard holds the main copy or a replica."""
        return (self.owner[keys] == shard) | (
            self.cache_slot[shard, keys] != NO_SLOT)

    def has_replica(self, keys: np.ndarray, shard: int) -> np.ndarray:
        return self.cache_slot[shard, keys] != NO_SLOT

    def replica_shards(self, key: int) -> np.ndarray:
        return np.nonzero(self.cache_slot[:, key] != NO_SLOT)[0]

    # -- replica bookkeeping -------------------------------------------------
    def add_replica(self, key: int, shard: int) -> int:
        assert self.cache_slot[shard, key] == NO_SLOT
        cs = self.cache_alloc[self.key_class[key]].alloc(shard)
        self.cache_slot[shard, key] = cs
        self.replica_count[key] += 1
        return cs

    def drop_replica(self, key: int, shard: int) -> int:
        cs = int(self.cache_slot[shard, key])
        assert cs != NO_SLOT
        self.cache_slot[shard, key] = NO_SLOT
        self.replica_count[key] -= 1
        self.cache_alloc[self.key_class[key]].free(shard, cs)
        return cs

    # -- relocation ----------------------------------------------------------
    def relocate(self, key: int, new_shard: int) -> tuple[int, int, int]:
        """Move ownership of `key` to `new_shard`. Returns
        (old_shard, old_slot, new_slot); the device row move is the caller's
        job (Server.relocate). Host metadata only."""
        old_shard = int(self.owner[key])
        old_slot = int(self.slot[key])
        assert old_shard != new_shard
        alloc = self.main_alloc[self.key_class[key]]
        new_slot = alloc.alloc(new_shard)
        self.owner[key] = new_shard
        self.slot[key] = new_slot
        alloc.free(old_shard, old_slot)
        self.relocation_counter[key] += 1
        return old_shard, old_slot, new_slot
