"""Sampling access management: PrepareSample / PullSample / FinishSample.

Reference include/ps/sampling.h — the PM manages negative-sampling access so
it can exploit locality (NuPS heritage). Four schemes (sampling.h:180-525):

  naive   draw keys at prepare time, plain Pull at pull time
  preloc  naive + Intent on the drawn keys at prepare time
  pool    shared pool of samples, refreshed with a reuse factor
  local   (default) draw from the app distribution, then snap to a key that
          is *locally available* — trades exact distribution for locality
          (documented distortion, sampling.h:361-365)

On TPU the Local scheme gets cheaper than the reference's linear key probe
(sampling.h:476-505): we keep a sorted array of locally-resident keys per
shard and snap with np.searchsorted (binary search), refreshed lazily when
the placement topology changes.

The app supplies `sample_key_fn(n, rng) -> keys` (reference `Key
sample_key()`), e.g. unigram^0.75 for word2vec.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from ..base import NO_SLOT


class _Handle:
    __slots__ = ("n", "start", "end", "keys", "pos", "seen")

    def __init__(self, n: int, start: int, end: int):
        self.n = n
        self.start = start
        self.end = end
        self.keys: Optional[np.ndarray] = None  # pre-drawn (naive/preloc)
        self.pos = 0
        self.seen: set = set()                  # without-replacement dedup


class SamplingBase:
    def __init__(self, server, sample_key_fn, min_key: int, max_key: int,
                 allowed_keys: Optional[np.ndarray] = None, seed: int = 42):
        self.server = server
        self.sample_key_fn = sample_key_fn
        self.min_key = min_key
        self.max_key = max_key
        # Local scheme: the population a drawn key may snap to. The reference
        # expresses this as the contiguous [min_key, max_key) sampling range
        # (sampling.h:476-505); with enforce_random_keys the eligible keys
        # (e.g. entities, syn1 rows) are scattered, so an explicit key set is
        # needed to keep snapping inside the sampled population.
        self.allowed_keys = None if allowed_keys is None else \
            np.unique(np.asarray(allowed_keys, dtype=np.int64))
        self.opts = server.opts
        self._rngs: Dict[int, np.random.Generator] = {}
        self._handles: Dict[Tuple[int, int], _Handle] = {}
        self._next_id: Dict[int, int] = {}
        self._seed = seed
        # RNG batching (--sampling.batch_size, reference sampling.h:394-405):
        # per-worker buffer of pre-drawn keys so small draws (WOR probes draw
        # one key at a time) amortize the app sample_key_fn call
        self._draw_buf: Dict[int, Tuple[np.ndarray, int]] = {}
        # per-scheme access stats (reference sampling.h:85-97)
        self.stats = {"prepared": 0, "pulled": 0, "pulled_local": 0}

    def _rng(self, worker) -> np.random.Generator:
        wid = worker.worker_id
        if wid not in self._rngs:
            self._rngs[wid] = np.random.default_rng(self._seed + wid)
        return self._rngs[wid]

    def _draw(self, n: int, worker) -> np.ndarray:
        bs = self.opts.sampling_batch_size
        if bs <= 1 or n >= bs:
            return np.asarray(self.sample_key_fn(n, self._rng(worker)),
                              dtype=np.int64)
        wid = worker.worker_id
        buf, pos = self._draw_buf.get(wid, (None, 0))
        if buf is None or pos + n > len(buf):
            buf = np.asarray(self.sample_key_fn(bs, self._rng(worker)),
                             dtype=np.int64)
            pos = 0
        out = buf[pos:pos + n]
        self._draw_buf[wid] = (buf, pos + n)
        return out

    def _draw_wor(self, n: int, worker, seen: set) -> np.ndarray:
        """Draw without replacement against `seen` (rejection sampling,
        reference draw_samples WOR, sampling.h:142-160). Batched: each
        round draws all still-needed keys at once and filters collisions
        vectorized (np.isin + first-occurrence), instead of the per-key
        Python probe the reference's C++ can afford."""
        out = np.empty(n, dtype=np.int64)
        got = 0
        seen_arr = np.fromiter(seen, np.int64, len(seen)) if seen else \
            np.empty(0, dtype=np.int64)
        for _ in range(200):
            if got >= n:
                break
            cand = self._draw(n - got, worker)
            # accept first occurrences not in seen (vectorized)
            _, first = np.unique(cand, return_index=True)
            ok = np.zeros(len(cand), dtype=bool)
            ok[first] = True
            ok &= ~np.isin(cand, seen_arr)
            acc = cand[ok]
            out[got:got + len(acc)] = acc
            got += len(acc)
            seen_arr = np.concatenate([seen_arr, acc])
        if got < n:
            raise RuntimeError("WOR sampling could not find enough keys")
        seen.update(out.tolist())
        return out

    # -- public (called via Worker) -----------------------------------------

    def prepare(self, worker, n: int, start: int, end: int) -> int:
        wid = worker.worker_id
        hid = self._next_id.get(wid, 0)
        self._next_id[wid] = hid + 1
        h = _Handle(n, start, end)
        self._handles[(wid, hid)] = h
        self._prepare(worker, h)
        self.stats["prepared"] += n
        return hid

    def pull(self, worker, hid: int, n: Optional[int] = None):
        keys = self.pull_keys(worker, hid, n)
        return keys, worker.pull_sync(keys)

    def pull_keys(self, worker, hid: int, n: Optional[int] = None):
        """Like pull() but returns only the sampled keys, skipping the value
        fetch — for callers that gather values themselves inside a fused step
        (ops/fused.py). Locality behavior per scheme is identical."""
        h = self._handles[(worker.worker_id, hid)]
        n = h.n - h.pos if n is None else n
        assert h.pos + n <= h.n, "pulling more samples than prepared"
        keys = self._pull_keys(worker, h, n)
        h.pos += n
        self.stats["pulled"] += n
        if self.server.locality is not None:
            self.server.locality.record_sampling(keys)
        return keys

    def finish(self, worker, hid: int) -> None:
        self._handles.pop((worker.worker_id, hid), None)

    # -- scheme hooks --------------------------------------------------------

    def _prepare(self, worker, h: _Handle) -> None:
        pass

    def _pull_keys(self, worker, h: _Handle, n: int) -> np.ndarray:
        raise NotImplementedError


class NaiveSampling(SamplingBase):
    """Draw at prepare, plain Pull at pull time (sampling.h:180-241)."""

    def _prepare(self, worker, h: _Handle) -> None:
        if self.opts.sampling_with_replacement:
            h.keys = self._draw(h.n, worker)
        else:
            h.keys = self._draw_wor(h.n, worker, h.seen)

    def _pull_keys(self, worker, h: _Handle, n: int) -> np.ndarray:
        return h.keys[h.pos:h.pos + n]


class PrelocSampling(NaiveSampling):
    """Naive + Intent on the drawn keys (sampling.h:248-280), so by pull time
    the planner has replicated/relocated them."""

    def _prepare(self, worker, h: _Handle) -> None:
        super()._prepare(worker, h)
        worker.intent(h.keys, h.start, h.end)


class PoolSampling(SamplingBase):
    """Shared pool of samples with bounded reuse (sampling.h:288-357): the
    pool is filled from the app distribution, every entry is used at most
    `reuse` times before being redrawn, and pool entries carry intent so the
    planner keeps them local."""

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        size = self.opts.sampling_pool_size or 4096
        self.pool = np.zeros(size, dtype=np.int64)
        self.uses = np.full(size, 2**31 - 1, dtype=np.int64)  # force refill
        self.reuse = max(1, self.opts.sampling_reuse_factor)
        self._cursor = 0

    def _refill(self, worker, idx: np.ndarray) -> None:
        fresh = self._draw(len(idx), worker)
        self.pool[idx] = fresh
        self.uses[idx] = 0
        clock = worker.current_clock
        worker.intent(fresh, clock, clock + self.reuse)

    def _pull_keys(self, worker, h: _Handle, n: int) -> np.ndarray:
        size = len(self.pool)
        idx = (self._cursor + np.arange(n)) % size
        self._cursor = int((self._cursor + n) % size)
        stale = idx[self.uses[idx] >= self.reuse]
        if len(stale):
            self._refill(worker, stale)
        self.uses[idx] += 1
        keys = self.pool[idx].copy()
        if not self.opts.sampling_with_replacement:
            # dedup within the handle: accept first occurrences not yet
            # seen (one vectorized pass), redraw the collisions in one
            # batched WOR call
            seen_arr = np.fromiter(h.seen, np.int64, len(h.seen)) \
                if h.seen else np.empty(0, dtype=np.int64)
            _, first = np.unique(keys, return_index=True)
            ok = np.zeros(len(keys), dtype=bool)
            ok[first] = True
            ok &= ~np.isin(keys, seen_arr)
            h.seen.update(keys[ok].tolist())
            bad = np.nonzero(~ok)[0]
            if len(bad):
                keys[bad] = self._draw_wor(len(bad), worker, h.seen)
        return keys


class LocalSampling(SamplingBase):
    """Default scheme (sampling.h:366-525): snap each drawn key to one that
    is locally available on the worker's shard, so sampled pulls never leave
    the device. Uses a sorted local-key index + binary search instead of the
    reference's linear probe."""

    def __init__(self, *args, **kw):
        super().__init__(*args, **kw)
        self._local_keys: Dict[int, np.ndarray] = {}
        self._topo_version = -1

    def _local_index(self, shard: int) -> np.ndarray:
        srv = self.server
        v = srv.topology_version
        if v != self._topo_version:
            self._local_keys.clear()
            self._topo_version = v
        if shard not in self._local_keys:
            ab = srv.ab
            rng = self.allowed_keys if self.allowed_keys is not None else \
                np.arange(self.min_key, self.max_key, dtype=np.int64)
            local = (ab.owner[rng] == shard) | (
                ab.cache_slot[shard, rng] != NO_SLOT)
            self._local_keys[shard] = rng[local]
        return self._local_keys[shard]

    def _snap(self, keys: np.ndarray, shard: int) -> np.ndarray:
        local = self._local_index(shard)
        if len(local) == 0:
            return keys  # nothing local; fall back to the raw draw
        pos = np.searchsorted(local, keys)
        pos = np.where(pos >= len(local), 0, pos)  # wrap (sampling.h:494)
        return local[pos]

    def _pull_keys(self, worker, h: _Handle, n: int) -> np.ndarray:
        if self.opts.sampling_with_replacement:
            keys = self._snap(self._draw(n, worker), worker.shard)
            self.stats["pulled_local"] += n
            return keys
        # WOR: batched draw+snap, then collisions probe FORWARD through
        # the local index — all rounds vectorized (the reference probes
        # per sample in C++, sampling.h:437-460; a Python per-sample loop
        # is exactly what kills w2v-at-scale prepare/pull)
        local = self._local_index(worker.shard)
        out = np.empty(n, dtype=np.int64)
        got = 0
        if len(local):
            seen_arr = np.fromiter(h.seen, np.int64, len(h.seen)) \
                if h.seen else np.empty(0, dtype=np.int64)
            # position of each pending sample's probe in the local index
            probe = np.searchsorted(local, self._snap(
                self._draw(n, worker), worker.shard))
            probe = np.where(probe >= len(local), 0, probe)
            for _ in range(len(local) + 1):
                if got >= n:
                    break
                cand = local[probe]
                _, first = np.unique(cand, return_index=True)
                ok = np.zeros(len(cand), dtype=bool)
                ok[first] = True
                ok &= ~np.isin(cand, seen_arr)
                acc = cand[ok]
                out[got:got + len(acc)] = acc
                got += len(acc)
                seen_arr = np.concatenate([seen_arr, acc])
                probe = (probe[~ok] + 1) % len(local)
            h.seen.update(out[:got].tolist())
        if got < n:
            # local population exhausted: global WOR draws (keys may be
            # remote — slower, never wrong)
            out[got:] = self._draw_wor(n - got, worker, h.seen)
        self.stats["pulled_local"] += n
        return out


def make_sampling(server, sample_key_fn, min_key: int, max_key: int,
                  allowed_keys=None):
    scheme = server.opts.sampling_scheme
    cls = {"naive": NaiveSampling, "preloc": PrelocSampling,
           "pool": PoolSampling, "local": LocalSampling}[scheme]
    return cls(server, sample_key_fn, min_key, max_key,
               allowed_keys=allowed_keys)
