"""The adaptive management planner: SyncManager reborn.

Reference: one SyncManager thread per channel (sync_manager.h:452-520) drains
worker intent queues, materializes replicas, extracts/ships deltas, and — on
the owner side — decides per key whether to *relocate* the main copy to the
requesting node or *replicate* it there (sync_manager.h:553-739, decision at
:624-644: relocate iff no other node and no local worker has intent).

Here the planner is a host-side loop (optionally a background thread) driving
the jitted sync/relocate/replica-create programs of the ShardedStores. The
owner/requester message exchange collapses: the single controller holds the
authoritative tables, so a "sync round" for a channel is ONE fused device
program per length class (delta psum -> owner merge -> fresh-value refresh)
instead of per-destination ZeroMQ messages. Channels partition keys by the
same Knuth multiplicative hash (reference handle.h:1016-1029) and bound the
per-round payload.
"""
from __future__ import annotations

import time
from typing import List, Set, Tuple

import numpy as np

from ..base import CLOCK_MAX, NO_SLOT, MgmtTechniques
from .intent import ActionTimer

KNUTH = np.uint64(2654435761)


def key_channel(keys: np.ndarray, num_channels: int) -> np.ndarray:
    """Key -> channel via Knuth multiplicative hash (handle.h:1016-1029).

    The HIGH half of the 32-bit product picks the channel: KNUTH is odd,
    so the product's low bits are just a permutation of the key's low
    bits — `h % 2^m` would degenerate to `key % 2^m`, perfectly
    correlated with the home-process layout (key % (S*P)), and one
    process's keys would all share a channel (observed in dcn_bench:
    chan_rounds == 1 at P = 4)."""
    h = (keys.astype(np.uint64) * KNUTH) & np.uint64(0xFFFFFFFF)
    return ((h >> np.uint64(16)) % np.uint64(num_channels)).astype(
        np.int32)


class SyncStats:
    def __init__(self):
        # concurrent per-channel rounds (_sync_all_channels) bump these
        # from several threads; int += is not atomic
        import threading
        self.lock = threading.Lock()
        self.rounds = 0
        self.replicas_created = 0
        self.replicas_dropped = 0
        self.relocations = 0
        # replicas *considered* by sync rounds; with sync_threshold > 0 the
        # ship/hold decision is made on device, so held-back small-delta
        # replicas are still counted here (an exact shipped count would cost
        # a device readback per round)
        self.keys_synced = 0
        self.intents_processed = 0


class SyncManager:
    """Plans and executes replication/relocation/sync for one Server."""

    def __init__(self, server, opts):
        self.server = server
        self.opts = opts
        self.num_channels = opts.channels
        S = server.num_shards
        K = server.num_keys
        # per-shard registered intent horizon: max end clock of any active
        # intent by a worker on that shard (reference: Parameter.local_intents
        # per customer, handle.h:122-152, aggregated to the node level).
        # int32: clocks are bounded by CLOCK_MAX = 2^31-1 (base.py), and at
        # Wikidata5M scale this table is S x 5M — int64 would double its
        # footprint for no range benefit
        self.intent_end = np.full((S, K), -1, dtype=np.int32)
        # live replicas, partitioned by channel: channel -> set[(key, shard)]
        self.replicas: List[Set[Tuple[int, int]]] = [
            set() for _ in range(self.num_channels)]
        self.timer = ActionTimer(
            server.max_workers, alpha=opts.timing_alpha,
            quantile=opts.timing_quantile,
            rounds_lookahead=opts.timing_rounds_lookahead,
            enabled=opts.time_intent_actions)
        self.stats = SyncStats()
        # obs wiring (docs/OBSERVABILITY.md): round latency, replica
        # staleness in clocks, and SyncStats mirrored as callable gauges
        # so metrics_snapshot()'s sync section is complete without
        # touching the counters the rest of this file maintains
        reg = server.obs
        self._h_round = reg.histogram("sync.round_s")
        # staleness = worker clocks elapsed since the channel's previous
        # sync round, observed once per round that refreshed replicas
        # (i.e. how stale those replicas had been allowed to grow)
        self._h_staleness = reg.histogram(
            "sync.replica_staleness_clocks", unit="clocks",
            bounds=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024))
        if reg.enabled:
            for name in ("rounds", "replicas_created", "replicas_dropped",
                         "relocations", "keys_synced",
                         "intents_processed"):
                reg.gauge(f"sync.{name}",
                          fn=lambda n=name: getattr(self.stats, n))
        # per-channel min-active-clock at the channel's last sync round
        # (-1 = never synced yet); feeds _h_staleness
        self._chan_last_clock = np.full(self.num_channels, -1,
                                        dtype=np.int64)
        self._next_channel = 0
        self._last_round_t = 0.0
        # collective cadence state (--sys.collective_cadence K): local
        # joins of the BSP exchange must be serialized (two local threads
        # entering the all-to-all concurrently would corrupt the global
        # exchange sequence); _cad_joined counts the clock boundaries
        # already serviced since the last global sync point
        import threading
        self._coll_lock = threading.Lock()
        self._cad_joined = 0
        self._chan_exec = None  # lazy: concurrent all-channel rounds (mp)

    # ------------------------------------------------------------------
    # intent registration + replicate-vs-relocate decision
    # ------------------------------------------------------------------

    def drain_intents(self, force: bool = False) -> None:
        """Drain worker intent queues for intents starting within the
        ActionTimer window (reference registerNewIntents,
        sync_manager.h:257-286); force=True drains everything (WaitSync)."""
        with self.server._span("sync.drain_intents"):
            self._drain_intents_impl(force)

    def _drain_intents_impl(self, force: bool) -> None:
        clocks = self.server.worker_clocks()
        self.timer.observe(clocks)
        window = self.timer.window()
        for w in self.server.workers():
            max_start = CLOCK_MAX if force else int(
                clocks[w.worker_id] + window[w.worker_id])
            for keys, start, end in w._intent_queue.pop_relevant(max_start):
                # actions are applied per intent entry: a later intent in the
                # same drain must observe placement changes made by earlier
                # ones, or locality decisions go stale
                relocate_keys, replicate_keys, remote_keys = self._register(
                    w.shard, keys, end)
                self.stats.intents_processed += len(keys)
                if len(remote_keys):
                    # keys owned by another process: the OWNER decides
                    # relocate-vs-replicate (reference owner branch,
                    # sync_manager.h:553-739) — ask it over the channel
                    self.server.glob.intent_remote(remote_keys, w.shard, end)
                if len(relocate_keys):
                    self.stats.relocations += self.server._relocate_to(
                        relocate_keys, w.shard)
                if len(replicate_keys):
                    created = self.server._create_replicas(
                        replicate_keys, w.shard)
                    chans = key_channel(created, self.num_channels)
                    with self.server._lock:
                        for k, c in zip(created.tolist(), chans.tolist()):
                            self.replicas[c].add((k, w.shard))
                    self.stats.replicas_created += len(created)

    def _register(self, shard: int, keys: np.ndarray,
                  end: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Register an intent batch; returns (keys to relocate to `shard`,
        keys to replicate onto `shard`, remotely-owned keys to hand to the
        cross-process layer). Fully vectorized — no per-key Python (the
        reference is O(1)/key in C++, addressbook.h:110-151). Capacity
        degradation (full pools) is handled downstream: _relocate demotes
        to replication, _create_replicas truncates — slower for the surplus
        keys, never wrong."""
        ie = self.intent_end
        # validate up front so the native and numpy paths leave identical
        # intent_end state when the batch contains a bad key (the C helper
        # applies in-range updates before reporting the bad count)
        from ..base import check_key_range
        check_key_range(keys, self.server.num_keys, "intent key")
        if self.server._native is not None:
            self.server._native.adapm_intent_max(
                np.ascontiguousarray(keys, np.int64), len(keys),
                self.server.num_keys, int(end), ie[shard])
        else:
            np.maximum.at(ie[shard], keys, np.int32(min(end, 2**31 - 1)))
        if self.server.tracer is not None:
            from ..utils.stats import INTENT_START
            self.server.tracer.record(keys, INTENT_START, shard)
        # keys that are not yet available on `shard`
        cand = keys[~self.server.ab.is_local(keys, shard)]
        e = np.empty(0, dtype=np.int64)
        if len(cand) == 0:
            return e, e, e
        remote = e
        if self.server.glob is not None:
            rm = self.server.ab.owner[cand] < 0
            remote, cand = cand[rm], cand[~rm]
            if len(cand) == 0:
                return e, e, remote
        relocate = self._decide_batch(cand, shard)
        return cand[relocate], cand[~relocate], remote

    def _decide_batch(self, keys: np.ndarray, shard: int) -> np.ndarray:
        """Relocate vs replicate (reference sync_manager.h:624-644): relocate
        iff no *other* shard currently has interest in any of the keys (an
        active intent or a replica) — otherwise replicate. Returns a bool
        mask (True = relocate)."""
        t = self.opts.techniques
        if t == MgmtTechniques.REPLICATION_ONLY:
            return np.zeros(len(keys), dtype=bool)
        if t == MgmtTechniques.RELOCATION_ONLY:
            return np.ones(len(keys), dtype=bool)
        ab = self.server.ab
        clocks = self.server.shard_min_clocks()
        other_interest = np.zeros(len(keys), dtype=bool)
        for s in range(self.server.num_shards):
            if s == shard:
                continue
            # any other shard's active intent or replica blocks relocation;
            # the reference distinguishes owner-local and remote node intent
            # but blocks relocation on either (:624-644)
            other_interest |= (ab.cache_slot[s, keys] != NO_SLOT) | \
                (self.intent_end[s, keys] >= clocks[s])
        return ~other_interest

    # ------------------------------------------------------------------
    # sync rounds
    # ------------------------------------------------------------------

    def sync_channel(self, channel: int) -> None:
        """Refresh replicas with active intent; flush+drop expired ones
        (reference readAndPotentiallyDropReplica, handle.h:601-662).
        Replicas of remotely-owned keys sync/drop over the DCN channel."""
        reps = self.replicas[channel]
        srv = self.server
        # staleness-in-clocks: replicas refreshed this round had gone
        # unrefreshed since the channel's previous round — observe the
        # min-active-clock delta across that gap
        mc = self._min_active_clock()
        if mc is not None:
            last = int(self._chan_last_clock[channel])
            self._chan_last_clock[channel] = mc
            # mc can REGRESS below last when a new worker registers at
            # clock 0 mid-run; that re-bases the channel (line above)
            # and must not feed a negative staleness into the histogram
            if 0 <= last <= mc and reps:
                self._h_staleness.observe(float(mc - last))
        with srv._lock:  # cross-process handlers mutate replica sets too
            if not reps:
                return
            items = list(reps)
            cross_mask = (srv.ab.owner[np.fromiter(
                (k for k, _ in items), np.int64, len(items))] < 0) \
                if srv.glob is not None else None
        min_clocks = srv.shard_min_clocks()
        if srv._native is not None:
            karr = np.fromiter((k for k, _ in items), np.int64, len(items))
            sarr = np.fromiter((s for _, s in items), np.int32, len(items))
            keep_mask = np.empty(len(items), np.uint8)
            srv._native.adapm_replica_scan(
                karr, sarr, len(items), self.intent_end.ravel(),
                np.ascontiguousarray(min_clocks, np.int64),
                srv.num_keys, keep_mask)
        else:
            keep_mask = np.fromiter(
                (self.intent_end[s, k] >= min_clocks[s] for k, s in items),
                np.uint8, len(items))
        if cross_mask is None:
            keep = [it for it, m in zip(items, keep_mask) if m]
            drop = [it for it, m in zip(items, keep_mask) if not m]
            keep_x = drop_x = []
        else:
            keep, drop, keep_x, drop_x = [], [], [], []
            for it, m, x in zip(items, keep_mask, cross_mask):
                (keep_x if x else keep).append(it) if m else \
                    (drop_x if x else drop).append(it)
        if keep:
            srv._sync_replicas(keep, threshold=self.opts.sync_threshold)
            with self.stats.lock:
                self.stats.keys_synced += len(keep)
        if keep_x and not self.opts.collective_sync:
            # collective mode: cross-process deltas accumulate and ship in
            # the BSP exchange at the next WaitSync/quiesce point
            srv.glob.sync_replicas(keep_x)
            with self.stats.lock:
                self.stats.keys_synced += len(keep_x)
        if drop or drop_x:
            if srv.tracer is not None:
                from ..utils.stats import INTENT_STOP
                for k, s in drop + drop_x:
                    srv.tracer.record(k, INTENT_STOP, s)
        if drop:
            srv._drop_replicas(drop)
            with srv._lock:
                for item in drop:
                    reps.discard(item)
            with self.stats.lock:
                self.stats.replicas_dropped += len(drop)
        if drop_x:
            srv.glob.drop_replicas(drop_x)  # discards from the channel set
            with self.stats.lock:
                self.stats.replicas_dropped += len(drop_x)

    def run_round(self, force_intents: bool = False,
                  all_channels: bool = False) -> None:
        # self-serializing (the round lock is reentrant): rounds may now
        # be driven concurrently by the training thread, the background
        # sync thread, AND the prefetch pipeline — drain_intents pops
        # worker heaps and sync_channel walks replica sets, neither of
        # which tolerates interleaved rounds
        with self.server._round_lock:
            self._throttle()
            if self.server._in_setup and not force_intents:
                # BeginSetup/EndSetup bracket (reference
                # coloc_kv_worker.h): management is paused so bulk
                # Set/Push of initial values runs at full speed;
                # EndSetup's barrier resumes it. An explicit WaitSync
                # (force) still acts.
                return
            # round latency measured AFTER the throttle (sleep is policy,
            # not work) — sync.round_s + the "sync.round" span
            from ..obs.metrics import timed
            with timed(self._h_round), self.server._span("sync.round"):
                self.drain_intents(force=force_intents)
                if all_channels:
                    self._sync_all_channels()
                else:
                    self.sync_channel(self._next_channel)
                    self._next_channel = \
                        (self._next_channel + 1) % self.num_channels
                if force_intents and all_channels:
                    # the WaitSync shape: in collective mode this is the
                    # agreed point where every process joins the BSP delta
                    # exchange
                    self._collective_point()
                else:
                    self._maybe_cadence()
                self.stats.rounds += 1

    def _sync_all_channels(self) -> None:
        """All channels' rounds. Multi-process, >1 channel: issued
        CONCURRENTLY — channels partition keys (per-channel delta locks,
        pm.delta_window), local device work serializes briefly under the
        server lock, and the expensive part (per-channel DCN round-trips
        to owners) overlaps instead of stacking RTTs (VERDICT r4 item 9;
        reference: C parallel SyncManager threads,
        coloc_kv_server.h:100-105). Single-process: serial — there is no
        network latency to hide, only thread overhead to pay."""
        srv = self.server
        if srv.glob is None or self.num_channels == 1:
            for c in range(self.num_channels):
                self.sync_channel(c)
            return
        if self._chan_exec is None:
            from concurrent.futures import ThreadPoolExecutor
            self._chan_exec = ThreadPoolExecutor(
                max_workers=self.num_channels,
                thread_name_prefix="adapm-chan")
        futs = [self._chan_exec.submit(self.sync_channel, c)
                for c in range(self.num_channels)]
        errs = []
        for f in futs:
            try:
                f.result()
            except Exception as e:
                errs.append(e)
        if errs:
            # surface every channel's failure: log the others before
            # raising the first, so concurrent-round diagnostics are not
            # reduced to whichever channel happened to be joined first
            from ..utils.log import alog
            for e in errs[1:]:
                alog(f"[sync] concurrent channel round also failed: "
                     f"{type(e).__name__}: {e}")
            raise errs[0]

    def close(self) -> None:
        if self._chan_exec is not None:
            self._chan_exec.shutdown(wait=True)
            self._chan_exec = None

    def _collective_active(self) -> bool:
        srv = self.server
        return srv.glob is not None and self.opts.collective_sync

    def _collective_exchange(self, quiescing: bool) -> bool:
        """One BSP exchange of every cross-process replica delta (caller
        holds _coll_lock). Returns True iff all processes entered it
        quiescing."""
        srv = self.server
        with srv._lock:
            items = [it for c in range(self.num_channels)
                     for it in self.replicas[c]
                     if srv.ab.owner[it[0]] < 0]
        all_q = srv.glob.collective_sync(items, quiescing=quiescing)
        self.stats.keys_synced += len(items)
        return all_q

    def _min_active_clock(self):
        """Min clock over this process's registered, unfinished workers;
        None when no worker is active (cadence then never triggers)."""
        from ..base import WORKER_FINISHED
        srv = self.server
        clocks = [int(srv._clocks[wid]) for wid in list(srv._workers)
                  if srv._clocks[wid] != WORKER_FINISHED]
        return min(clocks) if clocks else None

    def _maybe_cadence(self) -> None:
        """--sys.collective_cadence K: join one BSP exchange per K-clock
        boundary this process's workers have crossed. Every process runs
        the same check in its run_round, so exchanges pair up globally in
        boundary order; a process that crosses fewer boundaries before
        its next WaitSync/quiesce is absorbed there by the flag loop
        (_collective_point). Bounded staleness: a replica observes any
        remote push within K clocks of the slowest process (plus one
        run_round), vs unbounded between wait points with cadence off."""
        K = self.opts.collective_cadence
        if K <= 0 or not self._collective_active():
            return
        while True:
            mc = self._min_active_clock()
            if mc is None or mc < (self._cad_joined + 1) * K:
                return
            with self._coll_lock:
                # re-check: another local thread may have serviced it (or
                # the last worker may have finalized mid-check)
                mc = self._min_active_clock()
                if mc is None or mc < (self._cad_joined + 1) * K:
                    continue
                self._cad_joined += 1
                self._collective_exchange(quiescing=False)

    def _collective_point(self) -> None:
        """Ship all cross-process replica deltas through the collective
        exchange (parallel/collective.py). Must be reached by every
        process together; runs (with possibly zero items) whenever
        collective mode is on. With a cadence configured this is a FLAG
        LOOP: the process keeps joining exchanges (quiescing=True) until
        every peer is also at its wait point — absorbing peers that cross
        more cadence boundaries than we did (skewed batch counts)."""
        if not self._collective_active():
            return
        with self._coll_lock:
            while True:
                all_q = self._collective_exchange(quiescing=True)
                if all_q or self.opts.collective_cadence <= 0:
                    break
            # quiesce is a global sync point: re-base the cadence so all
            # processes agree that past boundaries need no exchange
            K = self.opts.collective_cadence
            if K > 0:
                mc = self._min_active_clock()
                self._cad_joined = 0 if mc is None else mc // K

    def _throttle(self) -> None:
        """Bound sync frequency (reference sync_manager.h:384-411, 805-814:
        --sys.sync.max_per_sec / --sys.sync.pause)."""
        if self.opts.sync_pause_ms > 0:
            time.sleep(self.opts.sync_pause_ms / 1e3)
            return
        if self.opts.sync_max_per_sec <= 0:
            return
        min_gap = 1.0 / self.opts.sync_max_per_sec
        now = time.monotonic()
        wait = self._last_round_t + min_gap - now
        if wait > 0:
            time.sleep(wait)
        self._last_round_t = time.monotonic()

    # ------------------------------------------------------------------

    def quiesce(self) -> None:
        """Force-process all intents and flush every pending delta; after
        this — and in multi-process, after every process quiesces and a
        barrier (WaitSync -> Barrier -> WaitSync) — all reads observe
        identical values (reference test_many_key_operations.cc:375-385)."""
        srv = self.server
        # same self-serialization as run_round (reentrant under the
        # Server.quiesce wrapper)
        with srv._round_lock:
            self._quiesce_locked()

    def _quiesce_locked(self) -> None:
        srv = self.server
        self.drain_intents(force=True)
        for c in range(self.num_channels):
            with srv._lock:
                reps = list(self.replicas[c])
            if not reps:
                continue
            if srv.glob is not None:
                karr = np.fromiter((k for k, _ in reps), np.int64, len(reps))
                with srv._lock:
                    cross = srv.ab.owner[karr] < 0
                local = [it for it, x in zip(reps, cross) if not x]
                remote = [it for it, x in zip(reps, cross) if x]
            else:
                local, remote = reps, []
            if local:
                srv._sync_replicas(local)
                self.stats.keys_synced += len(local)
            if remote and not self.opts.collective_sync:
                srv.glob.sync_replicas(remote)
                self.stats.keys_synced += len(remote)
        # collective mode: one BSP exchange covers every cross replica
        # (joined by all processes, items or not)
        self._collective_point()
        srv.block()

    def report(self) -> str:
        s = self.stats
        out = (f"sync: rounds={s.rounds} intents={s.intents_processed} "
               f"replicas+={s.replicas_created} -={s.replicas_dropped} "
               f"relocations={s.relocations} keys_synced={s.keys_synced}")
        if self.server.glob is not None:
            out += " | " + self.server.glob.report()
        return out
