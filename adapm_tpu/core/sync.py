"""The adaptive management planner: SyncManager reborn.

Reference: one SyncManager thread per channel (sync_manager.h:452-520) drains
worker intent queues, materializes replicas, extracts/ships deltas, and — on
the owner side — decides per key whether to *relocate* the main copy to the
requesting node or *replicate* it there (sync_manager.h:553-739, decision at
:624-644: relocate iff no other node and no local worker has intent).

Here the planner is a host-side loop (optionally a background thread) driving
the jitted sync/relocate/replica-create programs of the ShardedStores. The
owner/requester message exchange collapses: the single controller holds the
authoritative tables, so a "sync round" for a channel is ONE fused device
program per length class (delta psum -> owner merge -> fresh-value refresh)
instead of per-destination ZeroMQ messages. Channels partition keys by the
same Knuth multiplicative hash (reference handle.h:1016-1029) and bound the
per-round payload.
"""
from __future__ import annotations

import time
from typing import List, Optional, Tuple

import numpy as np

from ..base import CLOCK_MAX, NO_SLOT, MgmtTechniques
from .intent import ActionTimer

KNUTH = np.uint64(2654435761)


def key_channel(keys: np.ndarray, num_channels: int) -> np.ndarray:
    """Key -> channel via Knuth multiplicative hash (handle.h:1016-1029).

    The HIGH half of the 32-bit product picks the channel: KNUTH is odd,
    so the product's low bits are just a permutation of the key's low
    bits — `h % 2^m` would degenerate to `key % 2^m`, perfectly
    correlated with the home-process layout (key % (S*P)), and one
    process's keys would all share a channel (observed in dcn_bench:
    chan_rounds == 1 at P = 4)."""
    h = (keys.astype(np.uint64) * KNUTH) & np.uint64(0xFFFFFFFF)
    return ((h >> np.uint64(16)) % np.uint64(num_channels)).astype(
        np.int32)


class ReplicaTable:
    """One channel's live-replica set as a numpy structure-of-arrays.

    Replaces the `set[(key, shard)]` the planner used to walk with
    per-key Python: parallel `keys` (int64) / `shards` (int32) columns,
    a `live` mask, and a LIFO free-list of dead rows — every operation
    (add / remove / contains / snapshot) is O(batch) vectorized.

    Membership is one fancy-indexed read of a `(num_shards, num_keys)`
    int32 row-lookup table. The lookup may be SHARED across the channel
    tables of one SyncManager: a (key, shard) pair lives in exactly one
    channel (channel = hash(key)), so one table serves all channels
    without collisions — and int32 at S x K matches the `intent_end`
    footprint decision above. Lookup entries are validated against the
    stored key/shard columns on every read, so a stale or foreign row
    id degrades to "absent", never to a wrong entry.

    Not internally locked: callers mutate under the server lock (the
    same discipline the replica sets had).
    """

    GROW_MIN = 1024

    def __init__(self, num_shards: int, num_keys: int,
                 row_lookup: Optional[np.ndarray] = None):
        self.num_shards = num_shards
        self.num_keys = num_keys
        self._row = row_lookup if row_lookup is not None else \
            np.full((num_shards, num_keys), -1, dtype=np.int32)
        cap = self.GROW_MIN
        self.keys = np.zeros(cap, dtype=np.int64)
        self.shards = np.zeros(cap, dtype=np.int32)
        self.live = np.zeros(cap, dtype=bool)
        self._free = np.empty(cap, dtype=np.int32)
        self._n_free = 0
        self._top = 0       # rows [0, _top) have been handed out
        self._size = 0

    def __len__(self) -> int:
        return self._size

    @staticmethod
    def _as_pair(keys, shards) -> Tuple[np.ndarray, np.ndarray]:
        keys = np.ascontiguousarray(keys, dtype=np.int64).ravel()
        if np.ndim(shards) == 0:
            shards = np.full(len(keys), int(shards), dtype=np.int32)
        else:
            shards = np.ascontiguousarray(shards, dtype=np.int32).ravel()
        return keys, shards

    def _valid_rows(self, rows: np.ndarray, keys: np.ndarray,
                    shards: np.ndarray) -> np.ndarray:
        """True where the lookup row really is (key, shard) in THIS
        table (bounds + column match — see class docstring)."""
        out = np.zeros(len(rows), dtype=bool)
        idx = np.nonzero((rows >= 0) & (rows < self._top))[0]
        if len(idx):
            r = rows[idx]
            out[idx] = (self.live[r] & (self.keys[r] == keys[idx])
                        & (self.shards[r] == shards[idx]))
        return out

    def _grow_cols(self, need: int) -> None:
        cap = len(self.keys)
        while cap < need:
            cap *= 2
        if cap == len(self.keys):
            return
        for name in ("keys", "shards", "live"):
            old = getattr(self, name)
            new = np.zeros(cap, dtype=old.dtype)
            new[: len(old)] = old
            setattr(self, name, new)

    def add(self, keys, shards) -> int:
        """Insert (key, shard) pairs; already-present and intra-batch
        duplicate pairs are ignored. Returns the number inserted."""
        keys, shards = self._as_pair(keys, shards)
        if len(keys) == 0:
            return 0
        fresh = ~self._valid_rows(self._row[shards, keys], keys, shards)
        k, s = keys[fresh], shards[fresh]
        if len(k) == 0:
            return 0
        # intra-batch dedup (first occurrence wins)
        _, first = np.unique(k * np.int64(self.num_shards) + s,
                             return_index=True)
        k, s = k[first], s[first]
        n = len(k)
        rows = np.empty(n, dtype=np.int64)
        take = min(n, self._n_free)
        if take:
            rows[:take] = self._free[self._n_free - take: self._n_free]
            self._n_free -= take
        if n - take:
            self._grow_cols(self._top + (n - take))
            rows[take:] = np.arange(self._top, self._top + (n - take))
            self._top += n - take
        self.keys[rows] = k
        self.shards[rows] = s
        self.live[rows] = True
        self._row[s, k] = rows
        self._size += n
        return n

    def remove(self, keys, shards) -> int:
        """Remove (key, shard) pairs; absent pairs are ignored. Returns
        the number removed."""
        keys, shards = self._as_pair(keys, shards)
        if len(keys) == 0 or self._size == 0:
            return 0
        rows = self._row[shards, keys]
        rows = np.unique(rows[self._valid_rows(rows, keys, shards)])
        n = len(rows)
        if n == 0:
            return 0
        self.live[rows] = False
        self._row[self.shards[rows], self.keys[rows]] = -1
        if self._n_free + n > len(self._free):
            cap = len(self._free)
            while cap < self._n_free + n:
                cap *= 2
            new = np.empty(cap, dtype=np.int32)
            new[: self._n_free] = self._free[: self._n_free]
            self._free = new
        self._free[self._n_free: self._n_free + n] = rows
        self._n_free += n
        self._size -= n
        return n

    def contains(self, keys, shards) -> np.ndarray:
        keys, shards = self._as_pair(keys, shards)
        return self._valid_rows(self._row[shards, keys], keys, shards)

    def snapshot(self) -> Tuple[np.ndarray, np.ndarray]:
        """Copies of the live (keys, shards) columns (safe to use after
        the caller releases whatever lock guarded the mutation)."""
        rows = np.nonzero(self.live[: self._top])[0]
        return self.keys[rows], self.shards[rows]


class SyncStats:
    """Planner counters. EVERY bump goes through the locked `add()`
    helper: rounds run concurrently (per-channel threads, the prefetch
    pipeline, DCN handlers) and `int +=` is not atomic — the pre-PR 3
    code locked some sites and not others."""

    FIELDS = ("rounds", "replicas_created", "replicas_dropped",
              "relocations", "keys_synced", "keys_considered",
              "intents_processed")

    def __init__(self):
        import threading
        self.lock = threading.Lock()
        # keys_considered: replicas examined by sync rounds (intent-live,
        # keep-partition); keys_synced: replicas actually SHIPPED to a
        # sync program after the dirty-delta filter. With sync_threshold
        # > 0 the final ship/hold decision is on device, so held-back
        # small-delta replicas still count as synced here (an exact
        # on-device count would cost a readback per round).
        for f in self.FIELDS:
            setattr(self, f, 0)

    def add(self, **deltas) -> None:
        with self.lock:
            for name, n in deltas.items():
                setattr(self, name, getattr(self, name) + n)


class SyncManager:
    """Plans and executes replication/relocation/sync for one Server."""

    def __init__(self, server, opts):
        self.server = server
        self.opts = opts
        # the EFFECTIVE sync-rate bound _throttle honors (ISSUE 20):
        # initialized from the static --sys.sync.max_per_sec knob and —
        # only when a FreshnessSLO controller is live — walked ABOVE it
        # so sync rounds run more often than the static throttle
        # allows, then relaxed back toward it. With no controller
        # nothing ever writes this, so throttling is byte-identical to
        # the static-knob path. <= 0 keeps meaning unthrottled.
        self.effective_max_per_sec = float(opts.sync_max_per_sec)
        self.num_channels = opts.channels
        S = server.num_shards
        K = server.num_keys
        # per-shard registered intent horizon: max end clock of any active
        # intent by a worker on that shard (reference: Parameter.local_intents
        # per customer, handle.h:122-152, aggregated to the node level).
        # int32: clocks are bounded by CLOCK_MAX = 2^31-1 (base.py), and at
        # Wikidata5M scale this table is S x 5M — int64 would double its
        # footprint for no range benefit
        self.intent_end = np.full((S, K), -1, dtype=np.int32)
        # live replicas, partitioned by channel: one array-native
        # ReplicaTable per channel, sharing a single (S, K) row-lookup
        # (a key belongs to exactly one channel, so rows never collide;
        # same S x K int32 footprint call as intent_end above). Mutated
        # under the server lock via replica_add/replica_discard.
        self._replica_row = np.full((S, K), -1, dtype=np.int32)
        self.replicas: List[ReplicaTable] = [
            ReplicaTable(S, K, row_lookup=self._replica_row)
            for _ in range(self.num_channels)]
        self.timer = ActionTimer(
            server.max_workers, alpha=opts.timing_alpha,
            quantile=opts.timing_quantile,
            rounds_lookahead=opts.timing_rounds_lookahead,
            enabled=opts.time_intent_actions)
        self.stats = SyncStats()
        # obs wiring (docs/OBSERVABILITY.md): round latency, replica
        # staleness in clocks, and SyncStats mirrored as callable gauges
        # so metrics_snapshot()'s sync section is complete without
        # touching the counters the rest of this file maintains
        reg = server.obs
        self._h_round = reg.histogram("sync.round_s")
        # staleness = worker clocks elapsed since the channel's previous
        # sync round, observed once per round that refreshed replicas
        # (i.e. how stale those replicas had been allowed to grow)
        self._h_staleness = reg.histogram(
            "sync.replica_staleness_clocks", unit="clocks",
            bounds=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024))
        if reg.enabled:
            for name in SyncStats.FIELDS:
                reg.gauge(f"sync.{name}",
                          fn=lambda n=name: getattr(self.stats, n))
            # keys_shipped: the post-dirty-filter name for keys_synced
            # (docs/OBSERVABILITY.md); both gauges read the same counter
            reg.gauge("sync.keys_shipped",
                      fn=lambda: self.stats.keys_synced)
            # compression plane (ISSUE 8; schema v7): wire bytes the
            # most recent round shipped (--sys.sync.compress format),
            # cumulative shipped vs full-width-f32-equivalent bytes,
            # and the max-abs EF residual parked by the last
            # compressed round (0 until a compressed round runs; the
            # device scalar converts lazily here, at snapshot time)
            reg.gauge("sync.bytes_per_round",
                      fn=lambda: self._last_round_bytes)
            reg.gauge("sync.bytes_shipped",
                      fn=lambda: sum(st.sync_bytes_shipped
                                     for st in server.stores))
            reg.gauge("sync.bytes_full_equiv",
                      fn=lambda: sum(st.sync_bytes_full
                                     for st in server.stores))
            reg.gauge("sync.ef_residual_norm",
                      fn=lambda: max((st.ef_residual_norm()
                                      for st in server.stores),
                                     default=0.0))
            # table occupancy + dirty fraction, per channel and total —
            # host arrays only, no device readback. Best-effort reads
            # (evaluated without the server lock at snapshot time).
            reg.gauge("sync.replicas_live",
                      fn=lambda: sum(len(t) for t in self.replicas))
            reg.gauge("sync.dirty_fraction",
                      fn=lambda: self._dirty_fraction(None))
            for c in range(self.num_channels):
                reg.gauge(f"sync.replicas_live.c{c}",
                          fn=lambda c=c: len(self.replicas[c]))
                reg.gauge(f"sync.dirty_fraction.c{c}",
                          fn=lambda c=c: self._dirty_fraction(c))
        # per-channel min-active-clock at the channel's last sync round
        # (-1 = never synced yet); feeds _h_staleness
        self._chan_last_clock = np.full(self.num_channels, -1,
                                        dtype=np.int64)
        self._next_channel = 0
        self._last_round_t = 0.0
        # wire bytes shipped by the most recent sync_channel round
        # (sync.bytes_per_round gauge; ISSUE 8)
        self._last_round_bytes = 0
        # per-channel (monotonic, dirty, live) memo for the dirty_fraction
        # gauges — see _dirty_counts
        self._df_cache: dict = {}
        # collective cadence state (--sys.collective_cadence K): local
        # joins of the BSP exchange must be serialized (two local threads
        # entering the all-to-all concurrently would corrupt the global
        # exchange sequence); _cad_joined counts the clock boundaries
        # already serviced since the last global sync point
        import threading
        self._coll_lock = threading.Lock()
        self._cad_joined = 0
        self._chan_exec = None  # lazy: concurrent all-channel rounds (mp)

    # ------------------------------------------------------------------
    # intent registration + replicate-vs-relocate decision
    # ------------------------------------------------------------------

    def drain_intents(self, force: bool = False) -> None:
        """Drain worker intent queues for intents starting within the
        ActionTimer window (reference registerNewIntents,
        sync_manager.h:257-286); force=True drains everything (WaitSync)."""
        with self.server._span("sync.drain_intents"):
            self._drain_intents_impl(force)

    def _drain_intents_impl(self, force: bool) -> None:
        clocks = self.server.worker_clocks()
        self.timer.observe(clocks)
        window = self.timer.window()
        for w in self.server.workers():
            max_start = CLOCK_MAX if force else int(
                clocks[w.worker_id] + window[w.worker_id])
            for keys, start, end in w._intent_queue.pop_relevant(max_start):
                # actions are applied per intent entry: a later intent in the
                # same drain must observe placement changes made by earlier
                # ones, or locality decisions go stale
                relocate_keys, replicate_keys, remote_keys = self._register(
                    w.shard, keys, end)
                self.stats.add(intents_processed=len(keys))
                if len(remote_keys):
                    # keys owned by another process: the OWNER decides
                    # relocate-vs-replicate (reference owner branch,
                    # sync_manager.h:553-739) — ask it over the channel
                    self.server.glob.intent_remote(remote_keys, w.shard, end)
                if len(relocate_keys):
                    self.stats.add(relocations=self.server._relocate_to(
                        relocate_keys, w.shard))
                if len(replicate_keys):
                    created = self.server._create_replicas(
                        replicate_keys, w.shard)
                    with self.server._lock:
                        self.replica_add(created, w.shard)
                    self.stats.add(replicas_created=len(created))
                if self.server.tier is not None:
                    # tiered storage (adapm_tpu/tier): pin the intent
                    # batch's owner rows hot for the window and queue
                    # their promotion — the same just-in-time hook the
                    # prefetch pipeline rides, and AFTER the relocate/
                    # replicate actions above so the pins land on the
                    # keys' final placement
                    self.server.tier.note_intent(keys, end)

    # ------------------------------------------------------------------
    # replica registry (the channel tables; callers hold the server lock)
    # ------------------------------------------------------------------

    def _replica_op(self, keys: np.ndarray, shards, op: str) -> None:
        """One vectorized channel grouping (no per-key Python) applying
        ReplicaTable.`op` per channel; `shards` is a scalar or a per-key
        array. Caller holds the server lock."""
        if len(keys) == 0:
            return
        keys = np.ascontiguousarray(keys, dtype=np.int64).ravel()
        chans = key_channel(keys, self.num_channels)
        sarr = None if np.ndim(shards) == 0 else \
            np.asarray(shards, dtype=np.int32).ravel()
        for c in np.unique(chans):
            m = chans == c
            getattr(self.replicas[c], op)(
                keys[m], shards if sarr is None else sarr[m])

    def replica_add(self, keys: np.ndarray, shards) -> None:
        """Register live replicas into their channels' tables. Caller
        holds the server lock."""
        self._replica_op(keys, shards, "add")

    def replica_discard(self, keys: np.ndarray, shards) -> None:
        """Unregister replicas (absent pairs are ignored, matching the
        sets' discard semantics). Caller holds the server lock."""
        self._replica_op(keys, shards, "remove")

    def replica_clear(self) -> None:
        """Drop every registration (checkpoint restore rebuilds from the
        addressbook). Caller holds the server lock."""
        S, K = self.intent_end.shape
        self._replica_row.fill(-1)
        self.replicas = [ReplicaTable(S, K, row_lookup=self._replica_row)
                         for _ in range(self.num_channels)]

    def _dirty_counts(self, channel: int) -> Tuple[int, int]:
        """(dirty, live) for one channel, memoized briefly: one
        metrics_snapshot() evaluates the total gauge AND every
        per-channel gauge, and without the memo each full-table pass
        would run twice per snapshot (matters at ~1e5 live replicas)."""
        now = time.monotonic()
        ent = self._df_cache.get(channel)
        if ent is not None and now - ent[0] < 0.25:
            return ent[1], ent[2]
        t = self.replicas[channel]
        dirty = total = 0
        if len(t):
            keys, shards = t.snapshot()
            total = len(keys)
            if total:
                dirty = int(self.server._dirty_replica_mask(
                    keys, shards).sum())
        self._df_cache[channel] = (now, dirty, total)
        return dirty, total

    def _dirty_fraction(self, channel: Optional[int]) -> float:
        """Fraction of live replicas with unshipped writes (channel, or
        all channels for None). Best-effort lock-free gauge read."""
        chans = range(self.num_channels) if channel is None else (channel,)
        counts = [self._dirty_counts(c) for c in chans]
        total = sum(t for _, t in counts)
        return sum(d for d, _ in counts) / total if total else 0.0

    def _register(self, shard: int, keys: np.ndarray,
                  end: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Register an intent batch; returns (keys to relocate to `shard`,
        keys to replicate onto `shard`, remotely-owned keys to hand to the
        cross-process layer). Fully vectorized — no per-key Python (the
        reference is O(1)/key in C++, addressbook.h:110-151). Capacity
        degradation (full pools) is handled downstream: _relocate demotes
        to replication, _create_replicas truncates — slower for the surplus
        keys, never wrong."""
        ie = self.intent_end
        # validate up front so the native and numpy paths leave identical
        # intent_end state when the batch contains a bad key (the C helper
        # applies in-range updates before reporting the bad count)
        from ..base import check_key_range
        check_key_range(keys, self.server.num_keys, "intent key")
        if self.server._native is not None:
            self.server._native.adapm_intent_max(
                np.ascontiguousarray(keys, np.int64), len(keys),
                self.server.num_keys, int(end), ie[shard])
        else:
            np.maximum.at(ie[shard], keys, np.int32(min(end, 2**31 - 1)))
        if self.server.tracer is not None:
            from ..utils.stats import INTENT_START
            self.server.tracer.record(keys, INTENT_START, shard)
        # keys that are not yet available on `shard`
        cand = keys[~self.server.ab.is_local(keys, shard)]
        e = np.empty(0, dtype=np.int64)
        if len(cand) == 0:
            return e, e, e
        remote = e
        if self.server.glob is not None:
            rm = self.server.ab.owner[cand] < 0
            remote, cand = cand[rm], cand[~rm]
            if len(cand) == 0:
                return e, e, remote
        relocate = self._decide_batch(cand, shard)
        dc = self.server.decisions
        if dc is not None:
            # ISSUE 17: the relocate-vs-replicate split with its
            # feature vector; replications open an outcome window
            # probing whether the replicas were ever worth creating
            rep = cand[~relocate]
            dc.record_classify(int(shard), int(relocate.sum()),
                               len(rep), len(remote), rep)
        return cand[relocate], cand[~relocate], remote

    def _decide_batch(self, keys: np.ndarray, shard: int) -> np.ndarray:
        """Relocate vs replicate (reference sync_manager.h:624-644): relocate
        iff no *other* shard currently has interest in any of the keys (an
        active intent or a replica) — otherwise replicate. Returns a bool
        mask (True = relocate)."""
        t = self.opts.techniques
        if t == MgmtTechniques.REPLICATION_ONLY:
            return np.zeros(len(keys), dtype=bool)
        if t == MgmtTechniques.RELOCATION_ONLY:
            return np.ones(len(keys), dtype=bool)
        ab = self.server.ab
        clocks = self.server.shard_min_clocks()
        other_interest = np.zeros(len(keys), dtype=bool)
        for s in range(self.server.num_shards):
            if s == shard:
                continue
            # any other shard's active intent or replica blocks relocation;
            # the reference distinguishes owner-local and remote node intent
            # but blocks relocation on either (:624-644)
            other_interest |= (ab.cache_slot[s, keys] != NO_SLOT) | \
                (self.intent_end[s, keys] >= clocks[s])
        return ~other_interest

    # ------------------------------------------------------------------
    # sync rounds
    # ------------------------------------------------------------------

    def sync_channel(self, channel: int) -> None:
        """Refresh replicas with active intent; flush+drop expired ones
        (reference readAndPotentiallyDropReplica, handle.h:601-662).
        Replicas of remotely-owned keys sync/drop over the DCN channel.

        Lock discipline (PR 3 tentpole): the server lock brackets only
        the table snapshot here and the coordinate-revalidation +
        program-enqueue inside `_sync_replicas`/`_drop_replicas` — the
        keep/drop/cross partition, the dirty-delta filter, and the
        device execution itself all run outside it, so worker dispatch
        and the next channel's classification overlap this channel's
        device work instead of queueing behind the round."""
        srv = self.server
        table = self.replicas[channel]
        # staleness-in-clocks: replicas refreshed this round had gone
        # unrefreshed since the channel's previous round — observe the
        # min-active-clock delta across that gap
        mc = self._min_active_clock()
        if mc is not None:
            last = int(self._chan_last_clock[channel])
            self._chan_last_clock[channel] = mc
            # mc can REGRESS below last when a new worker registers at
            # clock 0 mid-run; that re-bases the channel (line above)
            # and must not feed a negative staleness into the histogram
            if 0 <= last <= mc and len(table):
                self._h_staleness.observe(float(mc - last))
        with srv._lock:  # snapshot only (DCN handlers mutate tables too)
            if len(table) == 0:
                return
            keys, shards = table.snapshot()
            cross = (srv.ab.owner[keys] < 0).astype(np.uint8) \
                if srv.glob is not None else None
        min_clocks = srv.shard_min_clocks()
        keep_l, keep_x, drop_l, drop_x = self._scan_partition(
            keys, shards, cross, min_clocks)
        self.stats.add(keys_considered=len(keep_l) + len(keep_x))
        if len(keep_l):
            kk, ks = keys[keep_l], shards[keep_l]
            n_considered, n_dirty = len(kk), -1
            if self.opts.sync_dirty_only:
                # dirty-delta filter: gather-and-ship only replicas with
                # an unshipped write or a stale base (store.py write
                # epochs). Exact, not heuristic — a clean replica's sync
                # program is a bit-for-bit no-op (delta == 0 and cache
                # == main), so skipping it cannot change any read.
                dirty = srv._dirty_replica_mask(kk, ks)
                n_dirty = int(dirty.sum())
                if dirty.any() and not dirty.all():
                    # sibling propagation: a dirty replica's merge
                    # advances the shared main row DURING this round, so
                    # its key's other replicas must ride the same fused
                    # program to pick up the post-merge value (a full
                    # round refreshes them in one program; judging them
                    # against the PRE-merge main would leave them one
                    # round stale). All replicas of a key hash to this
                    # channel, so the batch is self-contained.
                    dirty |= np.isin(kk, kk[dirty])
                kk, ks = kk[dirty], ks[dirty]
            else:
                pol = srv.policy
                if pol is not None and pol.active("sync"):
                    # ISSUE 18 learned sync law: with the static dirty
                    # filter OFF the heuristic ships every kept
                    # replica; a predicted wasted-wire verdict applies
                    # the EXACT per-batch dirty mask instead — the
                    # same value-preservation guard the filter-on
                    # branch above is built on (a clean replica's sync
                    # program is a bit-for-bit no-op, so holding it
                    # cannot change any read; sibling ride-alongs keep
                    # the post-merge refresh rule). A wrong prediction
                    # costs one mask pass — it never ships less than
                    # the dirty set.
                    if pol.consult("sync", {"n_dirty": -1},
                                   n_considered):
                        pol.applied("sync")
                        dirty = srv._dirty_replica_mask(kk, ks)
                        n_dirty = int(dirty.sum())
                        if dirty.any() and not dirty.all():
                            dirty |= np.isin(kk, kk[dirty])
                        kk, ks = kk[dirty], ks[dirty]
            dc = srv.decisions
            if dc is not None:
                # ISSUE 17: the ship/hold verdict for this channel's
                # batch — clean sibling ride-alongs (or a fully-clean
                # ship with the dirty filter off) fold into
                # decision.shipped_clean
                dc.record_sync(channel, n_considered, n_dirty, len(kk))
            if len(kk):
                # periodic rounds ship in the --sys.sync.compress wire
                # format (the EF residual parks in the delta row);
                # drop/quiesce flushes stay EXACT — kv.py _sync_replicas
                srv._sync_replicas(kk, ks,
                                   threshold=self.opts.sync_threshold,
                                   compress=True)
                self.stats.add(keys_synced=len(kk))
        if len(keep_x) and not self.opts.collective_sync:
            # collective mode: cross-process deltas accumulate and ship in
            # the BSP exchange at the next WaitSync/quiesce point. Cross
            # replicas are exempt from the dirty filter: their owner's
            # writes are invisible to local epochs, and the DCN round is
            # also how they OBSERVE remote pushes.
            srv.glob.sync_replicas(keys[keep_x], shards[keep_x])
            self.stats.add(keys_synced=len(keep_x))
        if (len(drop_l) or len(drop_x)) and srv.tracer is not None:
            from ..utils.stats import INTENT_STOP
            dk = np.concatenate([keys[drop_l], keys[drop_x]])
            ds = np.concatenate([shards[drop_l], shards[drop_x]])
            for s in np.unique(ds):
                srv.tracer.record(dk[ds == s], INTENT_STOP, int(s))
        if len(drop_l):
            dk, ds = keys[drop_l], shards[drop_l]
            srv._drop_replicas(dk, ds)
            with srv._lock:
                self.replica_discard(dk, ds)
            self.stats.add(replicas_dropped=len(dk))
        if len(drop_x):
            # discards from the channel tables itself
            srv.glob.drop_replicas(keys[drop_x], shards[drop_x])
            self.stats.add(replicas_dropped=len(drop_x))

    def _scan_partition(self, keys: np.ndarray, shards: np.ndarray,
                        cross: Optional[np.ndarray],
                        min_clocks: np.ndarray):
        """Partition one channel snapshot into (keep_local, keep_cross,
        drop_local, drop_cross) index arrays: keep iff the holder
        shard's intent horizon is still active. One native pass
        (adapm_replica_scan2) or its vectorized numpy equivalent —
        never per-key Python."""
        srv = self.server
        if srv._native is not None:
            from ..native import replica_scan_partition
            return replica_scan_partition(
                srv._native, keys, shards, self.intent_end,
                np.ascontiguousarray(min_clocks, np.int64),
                srv.num_keys, cross)
        keep = self.intent_end[shards, keys] >= min_clocks[shards]
        x = np.zeros(len(keys), dtype=bool) if cross is None \
            else cross.astype(bool)
        return (np.nonzero(keep & ~x)[0], np.nonzero(keep & x)[0],
                np.nonzero(~keep & ~x)[0], np.nonzero(~keep & x)[0])

    def run_round(self, force_intents: bool = False,
                  all_channels: bool = False) -> None:
        # self-serializing (the round lock is reentrant): rounds may now
        # be driven concurrently by the training thread, the background
        # sync thread, AND the prefetch pipeline — drain_intents pops
        # worker heaps and sync_channel walks replica sets, neither of
        # which tolerates interleaved rounds
        with self.server._round_lock:
            self._throttle()
            if self.server._in_setup and not force_intents:
                # BeginSetup/EndSetup bracket (reference
                # coloc_kv_worker.h): management is paused so bulk
                # Set/Push of initial values runs at full speed;
                # EndSetup's barrier resumes it. An explicit WaitSync
                # (force) still acts.
                return
            # round latency measured AFTER the throttle (sleep is policy,
            # not work) — sync.round_s + the "sync.round" span
            from ..obs.metrics import timed
            # wire bytes this ROUND ships (keep syncs in the
            # --sys.sync.compress format + drop flushes, which go
            # exact) — sync.bytes_per_round. Measured here, under the
            # round lock, across ALL of the round's channels: a
            # per-channel diff of the shared cumulative counter would
            # report only the last channel and cross-contaminate when
            # multi-process rounds issue channels concurrently.
            bytes_before = sum(st.sync_bytes_shipped
                               for st in self.server.stores)
            with timed(self._h_round), self.server._span("sync.round"):
                self.drain_intents(force=force_intents)
                if all_channels:
                    self._sync_all_channels()
                else:
                    self.sync_channel(self._next_channel)
                    self._next_channel = \
                        (self._next_channel + 1) % self.num_channels
                if force_intents and all_channels:
                    # the WaitSync shape: in collective mode this is the
                    # agreed point where every process joins the BSP delta
                    # exchange
                    self._collective_point()
                else:
                    self._maybe_cadence()
                self.stats.add(rounds=1)
            self._last_round_bytes = \
                sum(st.sync_bytes_shipped
                    for st in self.server.stores) - bytes_before
            wt = self.server.wtrace
            if wt is not None:
                # the round as it LANDED (ISSUE 15): replay re-drives
                # these events instead of running a timer-driven
                # background loop — rounds happen where the workload
                # put them, not where a wall clock did
                wt.record_sync(forced=force_intents,
                               all_channels=all_channels,
                               bytes_shipped=self._last_round_bytes)

    def _sync_all_channels(self) -> None:
        """All channels' rounds. Multi-process, >1 channel: issued
        CONCURRENTLY — channels partition keys (per-channel delta locks,
        pm.delta_window), local device work serializes briefly under the
        server lock, and the expensive part (per-channel DCN round-trips
        to owners) overlaps instead of stacking RTTs (VERDICT r4 item 9;
        reference: C parallel SyncManager threads,
        coloc_kv_server.h:100-105). Single-process: serial — there is no
        network latency to hide, only thread overhead to pay."""
        srv = self.server
        if srv.glob is None or self.num_channels == 1:
            for c in range(self.num_channels):
                self.sync_channel(c)
            return
        if self._chan_exec is None:
            from concurrent.futures import ThreadPoolExecutor
            self._chan_exec = ThreadPoolExecutor(
                max_workers=self.num_channels,
                thread_name_prefix="adapm-chan")
        futs = [self._chan_exec.submit(self.sync_channel, c)
                for c in range(self.num_channels)]
        errs = []
        for f in futs:
            try:
                f.result()
            except Exception as e:
                errs.append(e)
        if errs:
            # surface every channel's failure: log the others before
            # raising the first, so concurrent-round diagnostics are not
            # reduced to whichever channel happened to be joined first
            from ..utils.log import alog
            for e in errs[1:]:
                alog(f"[sync] concurrent channel round also failed: "
                     f"{type(e).__name__}: {e}")
            raise errs[0]

    def close(self) -> None:
        if self._chan_exec is not None:
            self._chan_exec.shutdown(wait=True)
            self._chan_exec = None

    def _collective_active(self) -> bool:
        srv = self.server
        return srv.glob is not None and self.opts.collective_sync

    def _collective_exchange(self, quiescing: bool) -> bool:
        """One BSP exchange of every cross-process replica delta (caller
        holds _coll_lock). Returns True iff all processes entered it
        quiescing."""
        srv = self.server
        with srv._lock:
            parts = [t.snapshot() for t in self.replicas]
            karr = np.concatenate([k for k, _ in parts])
            sarr = np.concatenate([s for _, s in parts])
            m = srv.ab.owner[karr] < 0
            karr, sarr = karr[m], sarr[m]
        all_q = srv.glob.collective_sync(karr, sarr, quiescing=quiescing)
        self.stats.add(keys_synced=len(karr), keys_considered=len(karr))
        return all_q

    def _min_active_clock(self):
        """Min clock over this process's registered, unfinished workers;
        None when no worker is active (cadence then never triggers)."""
        from ..base import WORKER_FINISHED
        srv = self.server
        clocks = [int(srv._clocks[wid]) for wid in list(srv._workers)
                  if srv._clocks[wid] != WORKER_FINISHED]
        return min(clocks) if clocks else None

    def _maybe_cadence(self) -> None:
        """--sys.collective_cadence K: join one BSP exchange per K-clock
        boundary this process's workers have crossed. Every process runs
        the same check in its run_round, so exchanges pair up globally in
        boundary order; a process that crosses fewer boundaries before
        its next WaitSync/quiesce is absorbed there by the flag loop
        (_collective_point). Bounded staleness: a replica observes any
        remote push within K clocks of the slowest process (plus one
        run_round), vs unbounded between wait points with cadence off."""
        K = self.opts.collective_cadence
        if K <= 0 or not self._collective_active():
            return
        while True:
            mc = self._min_active_clock()
            if mc is None or mc < (self._cad_joined + 1) * K:
                return
            with self._coll_lock:
                # re-check: another local thread may have serviced it (or
                # the last worker may have finalized mid-check)
                mc = self._min_active_clock()
                if mc is None or mc < (self._cad_joined + 1) * K:
                    continue
                self._cad_joined += 1
                self._collective_exchange(quiescing=False)

    def _collective_point(self) -> None:
        """Ship all cross-process replica deltas through the collective
        exchange (parallel/collective.py). Must be reached by every
        process together; runs (with possibly zero items) whenever
        collective mode is on. With a cadence configured this is a FLAG
        LOOP: the process keeps joining exchanges (quiescing=True) until
        every peer is also at its wait point — absorbing peers that cross
        more cadence boundaries than we did (skewed batch counts)."""
        if not self._collective_active():
            return
        with self._coll_lock:
            while True:
                all_q = self._collective_exchange(quiescing=True)
                if all_q or self.opts.collective_cadence <= 0:
                    break
            # quiesce is a global sync point: re-base the cadence so all
            # processes agree that past boundaries need no exchange
            K = self.opts.collective_cadence
            if K > 0:
                mc = self._min_active_clock()
                self._cad_joined = 0 if mc is None else mc // K

    def _throttle(self) -> None:
        """Bound sync frequency (reference sync_manager.h:384-411, 805-814:
        --sys.sync.max_per_sec / --sys.sync.pause)."""
        if self.opts.sync_pause_ms > 0:
            time.sleep(self.opts.sync_pause_ms / 1e3)
            return
        if self.effective_max_per_sec <= 0:
            return
        min_gap = 1.0 / self.effective_max_per_sec
        now = time.monotonic()
        wait = self._last_round_t + min_gap - now
        if wait > 0:
            time.sleep(wait)
        self._last_round_t = time.monotonic()

    # ------------------------------------------------------------------

    def quiesce(self) -> None:
        """Force-process all intents and flush every pending delta; after
        this — and in multi-process, after every process quiesces and a
        barrier (WaitSync -> Barrier -> WaitSync) — all reads observe
        identical values (reference test_many_key_operations.cc:375-385)."""
        srv = self.server
        # same self-serialization as run_round (reentrant under the
        # Server.quiesce wrapper)
        with srv._round_lock:
            self._quiesce_locked()

    def _quiesce_locked(self) -> None:
        srv = self.server
        self.drain_intents(force=True)
        for c in range(self.num_channels):
            with srv._lock:
                if len(self.replicas[c]) == 0:
                    continue
                keys, shards = self.replicas[c].snapshot()
                cross = (srv.ab.owner[keys] < 0) \
                    if srv.glob is not None else None
            if cross is not None:
                lk, ls = keys[~cross], shards[~cross]
                rk, rs = keys[cross], shards[cross]
            else:
                lk, ls = keys, shards
                rk = rs = np.empty(0, dtype=np.int64)
            if len(lk):
                # unconditional flush: quiesce bypasses the dirty filter
                # (and sync_threshold) so no pending delta is ever lost
                srv._sync_replicas(lk, ls)
                self.stats.add(keys_synced=len(lk),
                               keys_considered=len(lk))
            if len(rk) and not self.opts.collective_sync:
                srv.glob.sync_replicas(rk, rs)
                self.stats.add(keys_synced=len(rk),
                               keys_considered=len(rk))
        # collective mode: one BSP exchange covers every cross replica
        # (joined by all processes, items or not)
        self._collective_point()
        srv.block()

    def report(self) -> str:
        s = self.stats
        out = (f"sync: rounds={s.rounds} intents={s.intents_processed} "
               f"replicas+={s.replicas_created} -={s.replicas_dropped} "
               f"relocations={s.relocations} "
               f"keys_shipped={s.keys_synced}/"
               f"considered={s.keys_considered}")
        if self.server.glob is not None:
            out += " | " + self.server.glob.report()
        return out
