"""Intent queues, logical clocks, and the ActionTimer.

Reference: ColoKVWorker::Intent pushes FutureIntent{start,end,keys} into
per-channel lock-free SPSC queues drained by the sync managers
(coloc_kv_worker.h:380-408, 723-744); ActionTimer estimates how many clocks a
worker will advance in ~2 sync rounds so intents are acted on just-in-time
(sync_manager.h:62-158).

Here the queues are plain per-worker heaps ordered by start clock (the
single-controller planner drains them synchronously — no lock-freedom needed),
and the ActionTimer is a NumPy-only port of the exponential-smoothing +
Poisson-quantile estimate (no boost::math: we use a normal approximation of
the Poisson quantile, which the reference also falls back to for large means).
"""
from __future__ import annotations

import collections
import heapq
import itertools
import math
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..base import CLOCK_MAX


class IntentQueue:
    """Per-worker future-intent queue ordered by start clock."""

    def __init__(self):
        self._heap: List[Tuple[int, int, int, np.ndarray]] = []
        self._tie = itertools.count()

    def push(self, keys: np.ndarray, start: int, end: int) -> None:
        heapq.heappush(self._heap, (start, next(self._tie), end, keys))

    def pop_relevant(self, max_start: int):
        """Drain intents whose start clock is <= max_start (reference
        getNewRelevantIntents, coloc_kv_worker.h:684-708)."""
        out = []
        while self._heap and self._heap[0][0] <= max_start:
            start, _, end, keys = heapq.heappop(self._heap)
            out.append((keys, start, end))
        return out

    def __len__(self) -> int:
        return len(self._heap)

    def next_start(self) -> Optional[int]:
        return self._heap[0][0] if self._heap else None


class ActionTimer:
    """Estimates, per worker, how many clocks it will advance during the next
    `rounds_lookahead` sync rounds, so the planner registers intents
    just-in-time instead of eagerly (reference sync_manager.h:62-158).

    window(w) = quantile_q( Poisson(rate_w * lookahead_time) ), with the
    Poisson quantile approximated as mean + z_q * sqrt(mean) (normal approx).
    Rates and round duration are exponentially smoothed with alpha.
    """

    def __init__(self, num_workers: int, alpha: float = 0.1,
                 quantile: float = 0.9999, rounds_lookahead: float = 2.0,
                 enabled: bool = True):
        self.enabled = enabled
        self.alpha = alpha
        self.rounds_lookahead = rounds_lookahead
        # z for the standard normal quantile (Acklam-free: fixed table entry
        # for the default 0.9999; otherwise a rational approximation)
        self.z = _norm_quantile(quantile)
        self._rate = np.zeros(num_workers)          # clocks per second
        self._last_clock = np.zeros(num_workers, dtype=np.int64)
        self._last_time: Optional[float] = None
        self._round_secs = 0.01

    def observe(self, clocks: np.ndarray, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        if self._last_time is not None:
            dt = max(now - self._last_time, 1e-6)
            inst = (clocks - self._last_clock) / dt
            self._rate += self.alpha * (inst - self._rate)
            self._round_secs += self.alpha * (dt - self._round_secs)
        self._last_time = now
        self._last_clock = clocks.copy()

    def window(self) -> np.ndarray:
        """Per-worker clock window: intents starting within
        [clock, clock+window] should be acted on now."""
        if not self.enabled:
            return np.full_like(self._last_clock, CLOCK_MAX)
        mean = np.maximum(
            self._rate * self._round_secs * self.rounds_lookahead, 1.0)
        w = np.ceil(mean + self.z * np.sqrt(mean)).astype(np.int64)
        return np.maximum(w, 1)


class PlanCache:
    """Routing-plan cache for the hot Pull/Push path.

    Keyed by (kind, shard, fingerprint-of-keys) and guarded by the
    server's `topology_version`: a plan is a pure function of the key
    batch and the addressbook tables, and every table mutation bumps the
    version as the last step of its critical section
    (Server._topology_mutation), so version-match == plan-valid — the
    same revalidation contract optimistic routing already relies on. The
    fingerprint is a content hash of the key bytes; the stored key array
    is compared exactly on lookup, so a hash collision degrades to a
    cache miss, never to a wrong plan.

    Every training loop replays the same batch *arrays* on two paths:
    the prefetch pipeline plans a batch at intent time and `pull` replans
    it at consume time (or after a write invalidated the staged values —
    writes invalidate staged VALUE buffers, not plans), and benches/test
    harnesses rotate a fixed batch set. Both skip `_plan_pull`/
    `_plan_push` entirely on a hit.

    Thread-safe: the prefetch thread and worker threads share it.

    Hit/miss/stale accounting lives in the metrics registry when one is
    passed (`plan_cache.*`; docs/OBSERVABILITY.md) — the `hits`/
    `misses`/`stale` attributes remain as read-only views so the
    pre-registry accessors keep working.
    """

    def __init__(self, max_entries: int = 64, registry=None):
        from ..obs.metrics import Counter
        self.max_entries = max_entries
        # (kind, shard, fp) -> (keys, topology_version, plan); insertion
        # order doubles as the LRU order
        self._entries: "collections.OrderedDict" = collections.OrderedDict()
        self._lock = threading.Lock()
        use_reg = registry is not None and registry.enabled
        mk = (lambda n: registry.counter(f"plan_cache.{n}")) if use_reg \
            else (lambda n: Counter(f"plan_cache.{n}"))
        self._c_hits = mk("hits")
        self._c_misses = mk("misses")
        self._c_stale = mk("stale")
        if use_reg:
            registry.gauge("plan_cache.entries",
                           fn=lambda: len(self._entries))

    @property
    def hits(self) -> int:
        return int(self._c_hits.value)

    @property
    def misses(self) -> int:
        return int(self._c_misses.value)

    @property
    def stale(self) -> int:
        return int(self._c_stale.value)

    @staticmethod
    def fingerprint(keys: np.ndarray) -> int:
        # siphash over the raw bytes; collisions are caught by the exact
        # compare in get()
        return hash(keys.tobytes())

    def get(self, kind: str, shard: int, keys: np.ndarray, version: int):
        if self.max_entries <= 0:
            return None
        k = (kind, shard, self.fingerprint(keys))
        with self._lock:
            ent = self._entries.get(k)
            if ent is None:
                self._c_misses.inc()
                return None
            k0, v0, plan = ent
            if v0 != version:
                self._c_stale.inc()
                del self._entries[k]
                return None
            if k0.shape != keys.shape or not np.array_equal(k0, keys):
                self._c_misses.inc()  # fingerprint collision: as a miss
                return None
            self._c_hits.inc()
            self._entries.move_to_end(k)
            return plan

    def put(self, kind: str, shard: int, keys: np.ndarray, version: int,
            plan) -> None:
        if self.max_entries <= 0:
            return
        k = (kind, shard, self.fingerprint(keys))
        with self._lock:
            self._entries[k] = (keys.copy(), version, plan)
            self._entries.move_to_end(k)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            n = len(self._entries)
        return {"entries": n, "hits": self.hits,
                "misses": self.misses, "stale": self.stale}


class _StagingAbort(Exception):
    """Internal: a staging attempt hit its pool budget (not an error)."""


class _StagedPull:
    """One pre-gathered pull batch: the device value buffers plus the
    bookkeeping to decide, at consume time, whether they are still the
    values a fresh pull would return."""

    __slots__ = ("keys", "fp", "version", "groups", "n_remote",
                 "worker_id", "end", "acquired")

    def __init__(self, keys, fp, version, groups, n_remote, worker_id,
                 end, acquired):
        self.keys = keys            # the intended (unique, sorted) batch
        self.fp = fp
        self.version = version      # topology_version at gather time
        self.groups = groups        # Server._pull-shaped per-class groups
        self.n_remote = n_remote
        self.worker_id = worker_id
        self.end = end              # intent end clock (expiry)
        self.acquired = acquired    # [(StagingPool, rows)] to release


class PrefetchScheduler:
    """Intent-driven prefetch pipeline: the declared-intent lookahead of
    the reference (coloc_kv_worker.h Intent -> sync-manager action),
    extended to stage the *data plane* ahead of the access (SURVEY §2.5
    "pipeline-style lookahead"; NestPipe's embedding-prefetch overlap).

    One background thread consumes `Worker.intent` declarations and, for
    intents whose start clock falls inside the ActionTimer window:

      1. drives planner rounds delegated via `pump()` — the per-step
         `sync.run_round` moves off the training thread, so relocations,
         replica churn and the table re-uploads they trigger overlap the
         in-flight device step instead of serializing after it;
      2. refreshes registered device-side consumers (DeviceRouter table
         mirrors, local sampling indexes — `register_refresher`) as soon
         as the topology settles, so the next dispatch finds them staged;
      3. pre-gathers intended pull batches into device-resident staged
         buffers (ShardedStore.stage_gather) so `Worker.pull` of an
         intended batch is a staged-buffer hit: no re-planning, no
         `Server._lock`, no dispatch on the consuming thread.

    Consistency: a staged batch records the `topology_version` it was
    gathered under; any topology mutation invalidates it lazily at take
    time (relocation may fold a stale replica base into the moved row,
    so even value-preserving-looking moves are not trusted). Value
    writes are tracked eagerly: every server-side write path calls
    `note_writes(keys)` under the server lock, and staged batches
    intersecting the written keys are dropped and re-staged in the
    background — a pull can therefore never observe a staged buffer
    gathered before an overlapping write (read-your-writes), and a
    staged hit is bit-identical to the pull it replaced.

    PR 6: the dedicated pipeline thread is subsumed by the unified
    executor (adapm_tpu/exec) — staging/round work runs as coalesced,
    self-rescheduling programs on the `prefetch` stream, so prefetch
    staging shares the executor worker pool and overlaps fused compute
    dispatched from the `main` stream (the GraphVite-style episodic
    overlap the `exec.overlap_fraction` gauge measures). Work arrives
    via kicks (on_intent / pump / note_writes restage); an idle
    pipeline owns no queued program, and only deferred (out-of-window)
    intents keep a delayed poll program alive.

    Pull staging is gated by `opts.prefetch_pull`: "auto" stages only
    for workers that actually use the Pull API (fused-runner loops never
    pull, and staging gathers for them would be wasted device work),
    "always"/"off" force it. Staged-buffer memory is bounded by a
    per-class StagingPool (opts.prefetch_staging_rows) and
    opts.prefetch_max_batches per worker.
    """

    def __init__(self, server, opts):
        self.server = server
        self.opts = opts
        self._cond = threading.Condition()
        self._stop = False
        self._busy = False
        self._rounds = 0            # delegated planner rounds (capped)
        self._sweep = False         # explicit expiry/deferred sweep request
        self._pending: List[tuple] = []   # (worker, keys, start, end)
        self._deferred: List[tuple] = []  # beyond the ActionTimer window
        self._restage: List[tuple] = []   # invalidated, still in window
        # staged entries + an O(1)-per-key membership mask for the write
        # intersection test (allocated lazily: it is num_keys ints)
        self._plock = threading.Lock()
        self._staged: Dict[tuple, _StagedPull] = {}
        self._mask: Optional[np.ndarray] = None
        self._refreshers: List = []
        from .store import StagingPool
        self.pools = [StagingPool(opts.prefetch_staging_rows)
                      for _ in server.stores]
        # registry-backed counters behind the pre-registry dict API
        # (`stats["hits"]` etc. keep working; the registry is the single
        # source of truth — docs/OBSERVABILITY.md)
        from ..obs.metrics import CounterGroup
        reg = server.obs
        self.stats = CounterGroup(reg, "prefetch", (
            "staged", "hits", "expired", "invalidated_write",
            "invalidated_topology", "restaged", "rounds_driven",
            "pool_full", "evicted"))
        if reg.enabled:
            reg.gauge("prefetch.live", fn=lambda: len(self._staged))
            # StagingPool occupancy (rows now / high-water mark / budget)
            # summed over the per-class pools — core/store.py
            reg.gauge("staging.rows_in_use",
                      fn=lambda: sum(p.rows_in_use for p in self.pools))
            reg.gauge("staging.rows_hwm",
                      fn=lambda: max((p.rows_hwm for p in self.pools),
                                     default=0))
            reg.gauge("staging.rows_budget",
                      fn=lambda: sum(p.max_rows for p in self.pools))

    # -- producer side (training threads) -----------------------------------

    def on_intent(self, worker, keys: np.ndarray, start: int,
                  end: int) -> None:
        """Called by Worker.intent (keys already unique+sorted). Queues
        the batch for background staging; placement actions themselves
        stay with the planner rounds (inline or delegated via pump)."""
        if not self._should_stage(worker):
            return
        with self._cond:
            self._pending.append((worker, keys, start, end))
            # bound the backlog: a producer outrunning the stager keeps
            # only the freshest window of batches
            limit = 2 * max(1, self.opts.prefetch_max_batches)
            if len(self._pending) > limit:
                del self._pending[: len(self._pending) - limit]
            self._kick_locked()

    def pump(self, rounds: int = 1) -> None:
        """Delegate `rounds` planner rounds to the background thread (the
        apps' per-step `run_round` slot). Backlogged rounds coalesce: a
        single request may ask for a full scan window's rounds, but when
        the training thread outruns the planner the backlog stays
        bounded — each round drains ALL window-eligible intents anyway,
        so coalesced rounds batch the same planner work into fewer,
        larger drains (the reference's background sync managers run at
        their own cadence the same way)."""
        with self._cond:
            # bound accumulation at the LARGEST pending request (floor
            # 2): a scan window's drive_rounds(K) stands even if a
            # smaller per-step pump (or a pump(0) sweep) follows before
            # the thread swaps the backlog out
            self._rounds = min(self._rounds + rounds,
                               max(self._rounds, rounds, 2))
            self._sweep = True  # pump(0) = expiry/deferred sweep only
            self._kick_locked()

    def register_refresher(self, fn) -> None:
        """Register a callable refreshed by the pipeline after planner
        rounds (called under the server lock): device table mirrors,
        local sampling indexes. Idempotent callables only. Bound methods
        are held WEAKLY: a runner that goes away stops being refreshed
        (and stops pinning its device mirrors) instead of leaking into
        every future round."""
        import weakref
        try:
            ref = weakref.WeakMethod(fn)
        except TypeError:
            def ref(f=fn):  # plain function: keep a strong reference
                return f
        self._refreshers.append(ref)

    # -- consumer side (Worker.pull fast path) ------------------------------

    def take_staged(self, worker, keys: np.ndarray) -> Optional[_StagedPull]:
        """Pop a valid staged batch for `keys`, or None. Lock-free with
        respect to the server lock — this IS the fast path."""
        if not self._staged:
            return None
        with self.server._span("prefetch.take"):
            return self._take_staged_impl(worker, keys)

    def _take_staged_impl(self, worker,
                          keys: np.ndarray) -> Optional[_StagedPull]:
        fp = PlanCache.fingerprint(keys)
        with self._plock:
            e = self._staged.pop((worker.worker_id, fp), None)
            if e is None:
                return None
            self._mask_sub(e.keys)
            self._release(e)
        if e.keys.shape != keys.shape or not np.array_equal(e.keys, keys):
            return None  # fingerprint collision
        if e.version != self.server.topology_version:
            # placement moved since the gather (e.g. a relocation folded
            # a stale replica base into the moved row): not trusted
            self.stats.inc("invalidated_topology")
            return None
        self.stats.inc("hits")
        return e

    # -- invalidation (server write paths; caller holds the server lock) ----

    def note_writes(self, keys: np.ndarray) -> None:
        """Drop (and queue for re-staging) staged batches intersecting
        `keys`. Called from every value-write path BEFORE the write could
        be observed missing: push/set scatter, cross-process applies,
        replica sync refreshes. Since the dirty-delta filter (PR 3,
        core/sync.py), sync rounds invoke this only for replicas they
        actually ship — clean replicas are skipped whole, so idle
        staged batches no longer churn through invalidate/re-stage on
        every planner round."""
        if not self._staged or self._mask is None:
            return
        restage = []
        with self._plock:
            if not self._staged:
                return
            flat = keys.reshape(-1)
            if not self._mask[flat].any():
                return
            for k, e in list(self._staged.items()):
                if np.isin(e.keys, flat, assume_unique=False).any():
                    del self._staged[k]
                    self._mask_sub(e.keys)
                    self._release(e)
                    self.stats.inc("invalidated_write")
                    restage.append(e)
        if restage:
            with self._cond:
                for e in restage:
                    w = self.server._workers.get(e.worker_id)
                    if w is not None and e.end >= w.current_clock:
                        self._restage.append((w, e.keys, 0, e.end))
                self._kick_locked()

    def invalidate_all(self) -> None:
        with self._plock:
            for e in self._staged.values():
                self._mask_sub(e.keys)
                self._release(e)
            self._staged.clear()

    # -- lifecycle -----------------------------------------------------------

    def flush(self, timeout: float = 60.0) -> None:
        """Block until the pipeline is idle (tests / quiesce)."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while (self._busy or self._rounds or self._pending
                   or self._restage or self._sweep):
                if not self._cond.wait(timeout=min(
                        0.5, max(0.0, deadline - time.monotonic()))):
                    if time.monotonic() >= deadline:
                        raise TimeoutError("prefetch pipeline flush")

    def close(self) -> None:
        """Idempotent: stop accepting work, drain in-flight passes off
        the `prefetch` stream (a queued pass observes `_stop` and
        returns immediately), release every staged buffer."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        ex = self.server.exec
        if not ex.closed and not ex.drain("prefetch", timeout=30):
            from ..utils import alog
            alog("[prefetch] pipeline failed to drain within 30s of "
                 "close — a staging pass is wedged mid-dispatch")
        self.invalidate_all()

    # -- internals -----------------------------------------------------------

    def _should_stage(self, worker) -> bool:
        mode = self.opts.prefetch_pull
        if mode == "off":
            return False
        # auto: fused-runner loops never Pull — staging gathers for them
        # is wasted device work. A worker that has pulled is a Pull user.
        return mode == "always" or worker.stats["pull_ops"] > 0

    def _kick_locked(self) -> None:
        """Queue one pipeline pass on the `prefetch` stream (caller
        holds _cond; the executor lock is a leaf, so submitting under it
        is safe). Coalesced: kicks landing while a pass is already
        queued are absorbed — the pass swaps out the WHOLE backlog when
        it runs. A kick during a RUNNING pass queues the next one."""
        if not self._stop:
            self.server.exec.submit("prefetch", self._pass,
                                    label="prefetch.pass",
                                    coalesce_key="prefetch.pass")
        self._cond.notify_all()

    def _mask_add(self, keys: np.ndarray) -> None:
        if self._mask is None:
            self._mask = np.zeros(self.server.num_keys, dtype=np.int32)
        self._mask[keys] += 1

    def _mask_sub(self, keys: np.ndarray) -> None:
        if self._mask is not None:
            self._mask[keys] -= 1

    def _release(self, e: _StagedPull) -> None:
        for pool, rows in e.acquired:
            pool.release(rows)
        e.acquired = []

    def _pass(self) -> None:
        """One pipeline pass (an executor program on the `prefetch`
        stream): swap out the whole backlog under _cond, process it
        lock-free, then reschedule only if deferred intents need the
        0.25 s window poll (a fully idle pipeline owns no queued
        program — the executor worker parks on its condvar)."""
        from ..utils import alog
        srv = self.server
        with self._cond:
            if self._stop:
                self._cond.notify_all()
                return
            self._busy = True
            self._sweep = False
            rounds, self._rounds = self._rounds, 0
            pending, self._pending = self._pending, []
            restage, self._restage = self._restage, []
        try:
            for _ in range(rounds):
                srv.sync.run_round()
                self.stats.inc("rounds_driven")
            if rounds:
                self._refresh_consumers()
            self._expire()
            from ..base import WORKER_FINISHED
            now_deferred = []
            for item in self._deferred + pending:
                w, keys, start, end = item
                # a finalized worker never pulls again — its parked
                # intents (even CLOCK_MAX ones) must not keep the
                # deferred poll alive
                if end < w.current_clock or \
                        w.current_clock == WORKER_FINISHED:
                    self.stats.inc("expired")
                    continue
                window = int(srv.sync.timer.window()[w.worker_id])
                if start > w.current_clock + window:
                    now_deferred.append(item)
                    continue
                self._stage_one(w, keys, end)
            self._deferred = now_deferred
            for w, keys, _, end in restage:
                if end >= w.current_clock:
                    # record=False: the original staging already
                    # counted this batch in the locality stats; a
                    # write-invalidation restage must not count the
                    # same eventual pull twice
                    if self._stage_one(w, keys, end, record=False):
                        self.stats.inc("restaged")
        except Exception as e:  # noqa: BLE001 — keep the pipeline up
            alog(f"[prefetch] background task failed: "
                 f"{type(e).__name__}: {e}")
        finally:
            with self._cond:
                self._busy = False
                if self._deferred and not self._stop:
                    # deferred intents enter the window as clocks
                    # advance: keep a DELAYED poll queued (coalesces
                    # with — and is tightened to "now" by — any real
                    # kick that lands first)
                    self.server.exec.submit("prefetch", self._pass,
                                            label="prefetch.pass",
                                            coalesce_key="prefetch.pass",
                                            delay=0.25)
                self._cond.notify_all()

    def _refresh_consumers(self) -> None:
        if not self._refreshers:
            return
        with self.server._lock:
            live = []
            for ref in self._refreshers:
                fn = ref()
                if fn is not None:  # consumer still alive
                    fn()
                    live.append(ref)
            self._refreshers = live

    def _expire(self) -> None:
        """Drop staged batches whose intent window has passed."""
        if not self._staged:
            return
        with self._plock:
            for k, e in list(self._staged.items()):
                w = self.server._workers.get(e.worker_id)
                if w is None or e.end < w.current_clock:
                    del self._staged[k]
                    self._mask_sub(e.keys)
                    self._release(e)
                    self.stats.inc("expired")

    def _stage_one(self, worker, keys: np.ndarray, end: int,
                   record: bool = True) -> bool:
        """Plan (through the plan cache) and pre-gather one intended
        batch; returns True when a staged entry was recorded. `record`
        gates the locality-stats record (False on restage — the first
        staging already counted the batch)."""
        srv = self.server
        if len(keys) == 0:
            return False
        with srv._span("prefetch.stage"):
            return self._stage_one_impl(worker, keys, end, record)

    def _stage_one_impl(self, worker, keys: np.ndarray, end: int,
                        record: bool) -> bool:
        srv = self.server
        from .store import OOB
        shard = worker.shard
        tv = srv.topology_version
        plan = srv._plan_cached("pull", shard, keys, tv,
                                lambda: srv._plan_pull(keys, shard))
        rem, loc_map, cls = plan
        if rem is not None:
            return False  # process-remote keys: normal pull path handles
        fp = PlanCache.fingerprint(keys)
        acquired = []
        groups = []
        n_remote = 0
        with srv._lock:
            if srv.topology_version != tv:
                return False  # placement moved mid-plan: retry next round
            try:
                for cid, pos, ks, (o_sh, o_sl, c_sh, c_sl, use_c, nr,
                                   local) in cls:
                    out = srv.stores[cid].stage_gather(
                        o_sh, np.where(use_c, OOB, o_sl).astype(np.int32),
                        c_sh, c_sl, use_c, self.pools[cid])
                    if out is None:  # staging pool budget exhausted
                        self.stats.inc("pool_full")
                        raise _StagingAbort()
                    vals, rows = out
                    acquired.append((self.pools[cid], rows))
                    n_remote += nr
                    if record and srv.locality is not None:
                        # recorded at stage time, mirroring _pull's
                        # per-pull record; an expired (never-consumed)
                        # entry skews the counters by at most
                        # prefetch_max_batches batches, and restages
                        # pass record=False so an eventual pull is
                        # counted exactly once
                        srv.locality.record(ks.ravel(), local.ravel())
                    gpos = pos if loc_map is None else loc_map[pos]
                    groups.append((cid, gpos, srv.value_lengths[ks], vals,
                                   len(ks)))
            except BaseException as e:
                # release every row already accounted — a mid-loop
                # failure (pool budget, a flaky dispatch) must not leak
                # budget until staging is permanently wedged
                for pool, rows in acquired:
                    pool.release(rows)
                if isinstance(e, _StagingAbort):
                    dc = srv.decisions
                    if dc is not None:
                        # ISSUE 17: staging skipped on pool pressure
                        dc.record_prefetch("skip", len(keys),
                                           self.stats)
                    return False
                raise
            entry = _StagedPull(keys, fp, srv.topology_version, groups,
                                n_remote, worker.worker_id, end, acquired)
            # register while STILL holding the server lock: note_writes
            # runs under it, so a write can never land between the
            # gather above and the entry becoming visible for
            # invalidation (the read-your-writes guarantee)
            with self._plock:
                old = self._staged.pop((worker.worker_id, fp), None)
                if old is not None:
                    self._mask_sub(old.keys)
                    self._release(old)
                mine = [k for k in self._staged
                        if k[0] == worker.worker_id]
                while len(mine) >= max(1, self.opts.prefetch_max_batches):
                    victim = self._staged.pop(mine.pop(0))
                    self._mask_sub(victim.keys)
                    self._release(victim)
                    self.stats.inc("evicted")
                self._staged[(worker.worker_id, fp)] = entry
                self._mask_add(keys)
        self.stats.inc("staged")
        dc = srv.decisions
        if dc is not None:
            # ISSUE 17: staged — the outcome window reads the
            # hit/expired counter deltas to judge whether the staged
            # batch was ever consumed
            dc.record_prefetch("stage", len(keys), self.stats)
        return True

    def report(self) -> Dict[str, int]:
        out = self.stats.as_dict()
        out["live"] = len(self._staged)
        return out


def _norm_quantile(q: float) -> float:
    """Standard normal quantile via Beasley-Springer/Moro approximation."""
    if q == 0.9999:
        return 3.719
    # Moro's approximation (sufficient accuracy for a planning heuristic)
    a = [2.50662823884, -18.61500062529, 41.39119773534, -25.44106049637]
    b = [-8.47351093090, 23.08336743743, -21.06224101826, 3.13082909833]
    c = [0.3374754822726147, 0.9761690190917186, 0.1607979714918209,
         0.0276438810333863, 0.0038405729373609, 0.0003951896511919,
         0.0000321767881768, 0.0000002888167364, 0.0000003960315187]
    y = q - 0.5
    if abs(y) < 0.42:
        r = y * y
        num = y * (((a[3] * r + a[2]) * r + a[1]) * r + a[0])
        den = (((b[3] * r + b[2]) * r + b[1]) * r + b[0]) * r + 1.0
        return num / den
    r = q if y > 0 else 1.0 - q
    s = math.log(-math.log(1.0 - r))
    t = c[0]
    for i in range(1, 9):
        t += c[i] * s**i
    return t if y > 0 else -t
