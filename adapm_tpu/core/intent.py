"""Intent queues, logical clocks, and the ActionTimer.

Reference: ColoKVWorker::Intent pushes FutureIntent{start,end,keys} into
per-channel lock-free SPSC queues drained by the sync managers
(coloc_kv_worker.h:380-408, 723-744); ActionTimer estimates how many clocks a
worker will advance in ~2 sync rounds so intents are acted on just-in-time
(sync_manager.h:62-158).

Here the queues are plain per-worker heaps ordered by start clock (the
single-controller planner drains them synchronously — no lock-freedom needed),
and the ActionTimer is a NumPy-only port of the exponential-smoothing +
Poisson-quantile estimate (no boost::math: we use a normal approximation of
the Poisson quantile, which the reference also falls back to for large means).
"""
from __future__ import annotations

import heapq
import itertools
import math
import time
from typing import List, Optional, Tuple

import numpy as np

from ..base import CLOCK_MAX


class IntentQueue:
    """Per-worker future-intent queue ordered by start clock."""

    def __init__(self):
        self._heap: List[Tuple[int, int, int, np.ndarray]] = []
        self._tie = itertools.count()

    def push(self, keys: np.ndarray, start: int, end: int) -> None:
        heapq.heappush(self._heap, (start, next(self._tie), end, keys))

    def pop_relevant(self, max_start: int):
        """Drain intents whose start clock is <= max_start (reference
        getNewRelevantIntents, coloc_kv_worker.h:684-708)."""
        out = []
        while self._heap and self._heap[0][0] <= max_start:
            start, _, end, keys = heapq.heappop(self._heap)
            out.append((keys, start, end))
        return out

    def __len__(self) -> int:
        return len(self._heap)

    def next_start(self) -> Optional[int]:
        return self._heap[0][0] if self._heap else None


class ActionTimer:
    """Estimates, per worker, how many clocks it will advance during the next
    `rounds_lookahead` sync rounds, so the planner registers intents
    just-in-time instead of eagerly (reference sync_manager.h:62-158).

    window(w) = quantile_q( Poisson(rate_w * lookahead_time) ), with the
    Poisson quantile approximated as mean + z_q * sqrt(mean) (normal approx).
    Rates and round duration are exponentially smoothed with alpha.
    """

    def __init__(self, num_workers: int, alpha: float = 0.1,
                 quantile: float = 0.9999, rounds_lookahead: float = 2.0,
                 enabled: bool = True):
        self.enabled = enabled
        self.alpha = alpha
        self.rounds_lookahead = rounds_lookahead
        # z for the standard normal quantile (Acklam-free: fixed table entry
        # for the default 0.9999; otherwise a rational approximation)
        self.z = _norm_quantile(quantile)
        self._rate = np.zeros(num_workers)          # clocks per second
        self._last_clock = np.zeros(num_workers, dtype=np.int64)
        self._last_time: Optional[float] = None
        self._round_secs = 0.01

    def observe(self, clocks: np.ndarray, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        if self._last_time is not None:
            dt = max(now - self._last_time, 1e-6)
            inst = (clocks - self._last_clock) / dt
            self._rate += self.alpha * (inst - self._rate)
            self._round_secs += self.alpha * (dt - self._round_secs)
        self._last_time = now
        self._last_clock = clocks.copy()

    def window(self) -> np.ndarray:
        """Per-worker clock window: intents starting within
        [clock, clock+window] should be acted on now."""
        if not self.enabled:
            return np.full_like(self._last_clock, CLOCK_MAX)
        mean = np.maximum(
            self._rate * self._round_secs * self.rounds_lookahead, 1.0)
        w = np.ceil(mean + self.z * np.sqrt(mean)).astype(np.int64)
        return np.maximum(w, 1)


def _norm_quantile(q: float) -> float:
    """Standard normal quantile via Beasley-Springer/Moro approximation."""
    if q == 0.9999:
        return 3.719
    # Moro's approximation (sufficient accuracy for a planning heuristic)
    a = [2.50662823884, -18.61500062529, 41.39119773534, -25.44106049637]
    b = [-8.47351093090, 23.08336743743, -21.06224101826, 3.13082909833]
    c = [0.3374754822726147, 0.9761690190917186, 0.1607979714918209,
         0.0276438810333863, 0.0038405729373609, 0.0003951896511919,
         0.0000321767881768, 0.0000002888167364, 0.0000003960315187]
    y = q - 0.5
    if abs(y) < 0.42:
        r = y * y
        num = y * (((a[3] * r + a[2]) * r + a[1]) * r + a[0])
        den = (((b[3] * r + b[2]) * r + b[1]) * r + b[0]) * r + 1.0
        return num / den
    r = q if y > 0 else 1.0 - q
    s = math.log(-math.log(1.0 - r))
    t = c[0]
    for i in range(1, 9):
        t += c[i] * s**i
    return t if y > 0 else -t
