"""Device-resident sharded parameter pools + the jitted data-plane programs.

This replaces the reference's `DefaultColoServerHandle` (the node-local store
with a 16384-mutex lock array, coloc_kv_server_handle.h) with three pooled
`jax.Array`s sharded over the mesh "kv" axis:

    main  [S, slots, L]   main copies          (owner shard holds the row)
    cache [S, cslots, L]  replica base values  (value at last refresh)
    delta [S, cslots, L]  additive updates accumulated against replicas

No locks are needed: AdaPM's merge function is additive (reference
handle.h:404-415), so XLA scatter-add expresses concurrent pushes exactly, and
single-controller dispatch order serializes programs on the (donated) buffers.
The reference's `sync_state` copy + subtraction (`val - sync_state`,
handle.h:601-662) is replaced by *storing the delta directly*; a replica read
returns `cache + delta`, which preserves read-your-writes.

A `ShardedStore` is one uniform-value-length pool (a "length class"); routing
from keys to (shard, slot) indices lives in Server/Addressbook. All programs
take fixed-shape index buffers; batches are padded to power-of-two buckets and
padding entries carry out-of-range indices so JAX's mode="drop" (scatter) and
mode="fill" (gather) make them no-ops.

Since ISSUE 14 the store holds NO device programs of its own: every
dispatch goes through the narrow DevicePort (adapm_tpu/device — the
jitted programs moved verbatim into device/jaxport.py), so a
real-accelerator backend is one new port implementation rather than a
store rewrite. The port brackets each enqueue in the process-wide
sharded-dispatch gate internally (docs/EXECUTOR.md); this module is
device-API-free (adapm-lint APM008).
"""
from __future__ import annotations

import math

import jax
import numpy as np

from ..device import default_port
from ..device.jaxport import F16_MAX, OOB  # noqa: F401  (re-exported:
# OOB/F16_MAX are part of this module's historical API — routing, tier,
# serve, and quant layers import them from here)
from ..parallel.mesh import MeshContext


def bucket_size(n: int, minimum: int = 8) -> int:
    """Pad n up to a power of two (bounds the number of compiled variants)."""
    if n <= minimum:
        return minimum
    return 1 << math.ceil(math.log2(n))


def pad_to(arr: np.ndarray, size: int, fill) -> np.ndarray:
    out = np.full((size,) + arr.shape[1:], fill, dtype=arr.dtype)
    out[: arr.shape[0]] = arr
    return out


def pad_bucket(n: int, *arrays_and_fills, minimum: int = 8):
    b = bucket_size(n, minimum)
    # numpy (uncommitted) on purpose: jit places numpy args directly with
    # each executable's expected sharding. A jnp.asarray here would commit
    # them to device 0, and every mesh-jitted op would then RESHARD them
    # host-side per call (measured: ~10x slowdown of planner device ops on
    # an 8-device mesh, profile dominated by Array._value readbacks).
    # Caveat (docs/PERF.md staging rule): on remote-attached backends bare
    # numpy uploads synchronously through the relay; fine for planner-
    # frequency ops and the bindings' per-op pull/push, but anything
    # per-STEP hot must pre-stage via MeshContext.put_replicated the way
    # ops/fused.py build_routes does.
    return [pad_to(a, b, fill) for a, fill in arrays_and_fills]


# ---------------------------------------------------------------------------
# (the jitted data-plane programs formerly defined here live in
# adapm_tpu/device/jaxport.py since ISSUE 14 — same names, same bits)
# ---------------------------------------------------------------------------


# ---------------------------------------------------------------------------


class StagingPool:
    """Row budget for device-resident staged gather buffers (one per
    length class; core/intent.py PrefetchScheduler).

    Not a preallocated arena: XLA's gather already materializes its
    output in a fresh device buffer, so copying that into a reserved
    pool would only add a device-to-device copy. What staging needs is a
    BOUND — prefetch must not be able to OOM HBM by racing ahead of the
    consumer — so the pool accounts rows (buffers stay owned by the
    staged entries) and `stage_gather` refuses to gather past the
    budget. Thread-safe: the prefetch thread acquires, any thread that
    drops/consumes an entry releases."""

    def __init__(self, max_rows: int):
        import threading
        self.max_rows = max_rows
        self._rows = 0
        self._hwm = 0  # occupancy high-water mark (obs: staging.rows_hwm)
        self._lock = threading.Lock()

    def try_acquire(self, rows: int) -> bool:
        with self._lock:
            if self._rows + rows > self.max_rows:
                return False
            self._rows += rows
            if self._rows > self._hwm:
                self._hwm = self._rows
            return True

    def release(self, rows: int) -> None:
        with self._lock:
            self._rows -= rows
            assert self._rows >= 0, "staging pool released more than held"

    @property
    def rows_in_use(self) -> int:
        return self._rows

    @property
    def rows_hwm(self) -> int:
        """Highest concurrent row occupancy seen (never resets)."""
        return self._hwm


class ShardedStore:
    """Pools for one length class. Index-level API; key routing lives above."""

    def __init__(self, num_keys_in_class: int, value_length: int,
                 ctx: MeshContext, dtype=np.float32, over_alloc: float = 1.25,
                 cache_slots_per_shard: int = 0, bucket_min: int = 8,
                 tier_hot_rows: int = 0, tier_cold_dtype: str = "fp32",
                 port=None):
        self.value_length = value_length
        self.ctx = ctx
        self.dtype = dtype
        # the device plane (ISSUE 14): every program dispatch below goes
        # through this narrow port — swap it to target a new backend
        self.port = port if port is not None else default_port()
        # min padded batch size (--sys equivalent: remote_bucket_min) — a
        # larger floor means fewer distinct bucket shapes, i.e. fewer XLA
        # compilations, at the cost of padding work on tiny batches
        self.bucket_min = max(1, bucket_min)
        S = ctx.num_shards

        def _round8(n: int) -> int:
            # Slot counts are rounded to a multiple of 8: the TPU backend
            # picks the pool layout from the SHAPE, and an odd slot count
            # gets a (1,0,2):T(1,128) layout whose scatter operand then
            # needs a pool-sized layout-conversion copy inside every fused
            # step (observed +9.6 GiB peak HBM on a Wikidata5M-sized
            # table — the difference between fitting on a chip and OOM).
            # 8-aligned counts get the scatter-native T(8,128) layout.
            return -8 * (-n // 8)

        per_shard = max(1, math.ceil(num_keys_in_class / S))
        # floor at per_shard: an over_alloc < 1 (user squeezing HBM) must
        # not produce a pool smaller than the initial allocation
        self.main_slots = _round8(max(per_shard,
                                      math.ceil(per_shard * over_alloc)))
        self.cache_slots = _round8(max(1, cache_slots_per_shard or
                                       per_shard))

        # -- tiered residency (ISSUE 5 tentpole; adapm_tpu/tier) -----------
        # tier_hot_rows > 0 caps the DEVICE main pool at that many rows
        # per shard; the authoritative table spans main_slots rows per
        # shard, with rows beyond the hot set living in the host cold
        # store (`self.cold`, layout mirroring the pool row format).
        # Replica cache/delta pools stay fully device-resident. All
        # index-level ops keep taking (shard, SLOT) coordinates — the
        # residency map translates slots to hot rows at dispatch time,
        # so routing plans and the addressbook never see the tier.
        self.res = None
        self.cold = None          # fp32 alias of coldq.q (back-compat)
        self.coldq = None         # QuantCold (tier/quant.py)
        self.tier_hot_hits = 0   # owner-served gather entries, hot
        self.tier_cold_hits = 0  # owner-served gather entries, cold
        self.tier_hist = None    # cold-serve latency hist (TierManager)
        dev_main_slots = self.main_slots
        if tier_hot_rows > 0:
            from ..tier.quant import QuantCold
            from ..tier.residency import Residency
            dev_main_slots = _round8(
                min(self.main_slots, max(8, tier_hot_rows)))
            self.res = Residency(S, self.main_slots, dev_main_slots)
            # the cold tier, in --sys.tier.cold_dtype format (fp32 is a
            # bit-identical raw-array passthrough — the pre-PR pin);
            # residual capacity scales with the hot pool: the rows that
            # cycle promote/demote are the ones that park remainders
            self.coldq = QuantCold(
                S, self.main_slots, value_length, mode=tier_cold_dtype,
                resid_cap=min(65536, max(1024, 4 * dev_main_slots)))
            if tier_cold_dtype == "fp32":
                self.cold = self.coldq.q

        # donation-aware pool allocation through the port: the returned
        # buffers are the roots of the donated program chain
        sh = ctx.shard0()
        self.main = self.port.alloc_pool(
            (S, dev_main_slots, value_length), dtype, sh)
        self.cache = self.port.alloc_pool(
            (S, self.cache_slots, value_length), dtype, sh)
        self.delta = self.port.alloc_pool(
            (S, self.cache_slots, value_length), dtype, sh)

        # -- dirty-delta tracking (host-side, PR 3 tentpole) ---------------
        # NOTE (PR 5, tiering): the epochs below are indexed by SLOT,
        # not by device row, so the tracking extends to cold rows for
        # free — a write that lands in the cold store bumps the same
        # main_epoch[o, os] cell a hot write would, and the dirty-delta
        # sync filter keeps working across promotions/demotions (which
        # move values without changing them, hence without bumping).
        # A sync of replica (s, cs) against owner row (o, os) is a
        # bit-for-bit no-op iff its pending delta is zero AND its base
        # still equals the main row. Both facts are tracked on the host
        # so the planner can skip no-op syncs without a device readback:
        #   main_epoch[o, os]   — bumped (from one per-store counter) by
        #                         every program that can change a main
        #                         row's VALUE;
        #   repl_epoch[s, cs]   — the main row's epoch at the replica's
        #                         last base refresh;
        #   delta_dirty[s, cs]  — a delta write landed since that refresh.
        # dirty  :=  delta_dirty | (main_epoch != repl_epoch).
        # Conservative only toward syncing (a zero-valued push still
        # marks dirty); never toward skipping — the invariant the
        # dirty-vs-full consistency test pins (tests/test_replica_table).
        self._epoch = 1
        self.main_epoch = np.zeros((S, self.main_slots), dtype=np.int64)
        self.repl_epoch = np.zeros((S, self.cache_slots), dtype=np.int64)
        self.delta_dirty = np.zeros((S, self.cache_slots), dtype=bool)

        # -- sync wire accounting (ISSUE 8; --sys.sync.compress) -----------
        # bytes one sync round ships in the configured wire format vs
        # what full-width f32 would have cost for the same rows —
        # bumped by sync_replicas under the server lock; read by the
        # sync.bytes_* gauges (core/sync.py). With sync_threshold > 0
        # the ship/hold decision is on device, so these count the
        # CONSIDERED rows (an exact on-device count would cost a
        # readback per round) — same convention as keys_synced.
        self.sync_bytes_shipped = 0
        self.sync_bytes_full = 0
        # max-abs residual parked by the last compressed round: a jnp
        # scalar kept UNCONVERTED (float() would block the round);
        # sync.ef_residual_norm converts it lazily at snapshot time
        self._ef_resid_dev = None
        self._ef_resid_host = 0.0  # tiered cold-owner (host) rounds

        # host-side count of dispatched gather programs. Lock-free (a
        # racing increment may be lost): this is a LIVENESS probe — the
        # serve idle guard (scripts/serve_latency_check.py) asserts it
        # does not move while the serving plane is idle — not an exact
        # accounting surface.
        self.gathers = 0

    def _next_epoch(self) -> int:
        self._epoch += 1
        return self._epoch

    def reset_write_tracking(self) -> None:
        """Conservatively mark everything dirty (checkpoint restore
        replaces the pools wholesale): the first sync round after a
        reset re-ships every live replica once, then the filter
        reconverges."""
        self._epoch += 1
        self.main_epoch.fill(self._epoch)
        self.repl_epoch.fill(0)
        self.delta_dirty.fill(True)

    def mark_shard_written(self, shard: int) -> None:
        """Conservative write-tracking for in-program scatters whose row
        set the host cannot enumerate (device-drawn negatives in the
        device-routed fused step): every row `shard` holds counts as
        written. Two contiguous row fills — cheap relative to the step
        dispatch — at the cost of making the dirty filter inert for
        this shard's replicas until they resync (exactly the pre-filter
        behavior, never a missed sync)."""
        self.main_epoch[shard, :] = self._next_epoch()
        self.delta_dirty[shard, :] = True

    def mark_routed_writes(self, shard: int, cache_rows: np.ndarray,
                           owner_sh: np.ndarray,
                           owner_sl: np.ndarray) -> None:
        """Exact write-tracking for a fused-step scatter of host-known
        keys routed by the shared policy (replica delta row where
        `cache_rows` >= 0, else the owner main row). Caller resolves the
        coordinates from the addressbook under the server lock — the
        same tables the device program routes with."""
        repl = cache_rows >= 0
        if repl.any():
            self.delta_dirty[shard, cache_rows[repl]] = True
        # owner_sl < 0 (process-remote key not yet localized) would wrap
        # as a negative fancy index — skip; its write lands remotely
        m = ~repl & (owner_sl >= 0)
        if m.any():
            self.main_epoch[owner_sh[m], owner_sl[m]] = self._next_epoch()

    # -- write-epoch export (ISSUE 9; serve/replica.py) ----------------------

    def export_epochs(self, o_sh: np.ndarray,
                      o_sl: np.ndarray) -> np.ndarray:
        """Copy of the main-row write epochs at (shard, slot) coords —
        the serve replica records these under the server lock at
        snapshot time. A row whose epoch later differs has (or may
        have) a changed VALUE; promotions/demotions move rows without
        changing them and deliberately do not bump."""
        return self.main_epoch[o_sh, o_sl].copy()

    def epochs_unchanged(self, o_sh: np.ndarray, o_sl: np.ndarray,
                         epochs: np.ndarray) -> bool:
        """True iff every (shard, slot) row's main epoch still equals
        the exported value — the serve replica's read-your-writes /
        staleness guard. Pure host read, safe without the lock: every
        write path bumps the epoch cell BEFORE its device program is
        enqueued (under the server lock), so a write that completed
        before this check is always visible; a concurrent write that
        is not yet visible linearizes after the lock-free read."""
        return bool(np.array_equal(self.main_epoch[o_sh, o_sl], epochs))

    def _vals_bucket(self, vals, bucket: int):
        # numpy (uncommitted) for the same reason as pad_bucket: a device-0
        # committed array would be host-resharded by every mesh-jitted op
        v = np.zeros((bucket, self.value_length), dtype=self.dtype)
        n = vals.shape[0]
        v[:n] = np.asarray(vals)
        return v

    # index-level ops (all index arrays are np.int32, padded by caller or
    # padded here via pad_bucket)

    def gather(self, o_shard, o_slot, c_shard, c_slot, use_cache):
        n = len(o_shard)
        self.gathers += 1
        if self.res is not None:
            from ..tier import coldpath
            return coldpath.gather_tiered(self, o_shard, o_slot,
                                          c_shard, c_slot, use_cache)
        a = pad_bucket(n, (o_shard, 0), (o_slot, OOB), (c_shard, 0),
                       (c_slot, OOB), (use_cache, False),
                       minimum=self.bucket_min)
        return self.port.gather(self.main, self.cache, self.delta, *a)

    def gather_pool(self, o_shard, o_slot, c_shard, c_slot, use_cache,
                    seg, nbags: int, pooling: str = "sum"):
        """Fused embedding-bag read (ISSUE 16): gather member rows
        exactly as `gather` and reduce them into per-bag vectors in ONE
        port program. `seg` maps each member entry to its bag index
        (< nbags); the result's first `nbags` rows are the pooled
        vectors (the rest is bucket padding — slice `[:nbags]`).
        Bit-identical to host-pooling this batch's `gather` rows with
        `np.add.at` (the batch-order accumulation contract)."""
        n = len(o_shard)
        self.gathers += 1
        nb = bucket_size(max(int(nbags), 1), self.bucket_min)
        out = np.zeros((nb, self.value_length),
                       dtype=np.dtype(self.dtype))
        if self.res is not None:
            from ..tier import coldpath
            return coldpath.gather_pool_tiered(
                self, o_shard, o_slot, c_shard, c_slot, use_cache,
                seg, out, pooling)
        a = pad_bucket(n, (o_shard, 0), (o_slot, OOB), (c_shard, 0),
                       (c_slot, OOB), (use_cache, False),
                       (np.asarray(seg, dtype=np.int32), OOB),
                       minimum=self.bucket_min)
        return self.port.gather_pool(self.main, self.cache, self.delta,
                                     *a, out, pooling=pooling)

    def stage_gather(self, o_shard, o_slot, c_shard, c_slot, use_cache,
                     pool: "StagingPool"):
        """The gather-into-staging program (prefetch pipeline): identical
        program and result to `gather` — a staged pull must be
        bit-identical to the pull it replaces — but accounted against
        `pool`'s row budget. Returns (device rows, accounted row count),
        or None when the budget is exhausted (the caller skips staging;
        the consumer falls back to a plain pull — slower, never wrong).
        The caller must `pool.release(rows)` when the staged buffer is
        consumed or dropped."""
        rows = bucket_size(len(o_shard), self.bucket_min)
        if not pool.try_acquire(rows):
            return None
        return self.gather(o_shard, o_slot, c_shard, c_slot,
                           use_cache), rows

    def scatter_add(self, o_shard, o_slot, d_shard, d_slot, vals):
        n = len(o_shard)
        m = np.asarray(o_slot) != OOB
        if m.any():
            self.main_epoch[np.asarray(o_shard)[m],
                            np.asarray(o_slot)[m]] = self._next_epoch()
        md = np.asarray(d_slot) != OOB
        if md.any():
            self.delta_dirty[np.asarray(d_shard)[md],
                             np.asarray(d_slot)[md]] = True
        if self.res is not None:
            from ..tier import coldpath
            coldpath.scatter_add_tiered(self, o_shard, o_slot,
                                        d_shard, d_slot, vals)
            return
        a = pad_bucket(n, (o_shard, 0), (o_slot, OOB), (d_shard, 0),
                       (d_slot, OOB), minimum=self.bucket_min)
        v = self._vals_bucket(vals, a[0].shape[0])
        self.main, self.delta = self.port.scatter_add(
            self.main, self.delta, *a, v)

    def set_rows(self, o_shard, o_slot, vals, c_shard, c_slot):
        n = len(o_shard)
        e = self._next_epoch()
        m = np.asarray(o_slot) != OOB
        if m.any():
            self.main_epoch[np.asarray(o_shard)[m],
                            np.asarray(o_slot)[m]] = e
        # the writer's refreshed replica carries the set value with a
        # cleared delta: clean at the new epoch (rows are index-aligned
        # with the owner rows, so both sides stamp the same e)
        mc = np.asarray(c_slot) != OOB
        if mc.any():
            cs, cl = np.asarray(c_shard)[mc], np.asarray(c_slot)[mc]
            self.repl_epoch[cs, cl] = e
            self.delta_dirty[cs, cl] = False
        if self.res is not None:
            from ..tier import coldpath
            coldpath.set_rows_tiered(self, o_shard, o_slot, vals,
                                     c_shard, c_slot)
            return
        a = pad_bucket(n, (o_shard, 0), (o_slot, OOB), (c_shard, 0),
                       (c_slot, OOB), minimum=self.bucket_min)
        v = self._vals_bucket(vals, a[0].shape[0])
        self.main, self.cache, self.delta = self.port.set_rows(
            self.main, self.cache, self.delta, a[0], a[1], v,
            a[2], a[3])

    def replica_create(self, o_shard, o_slot, c_shard, c_slot):
        n = len(o_shard)
        # a fresh replica copies the CURRENT main row: clean at the main
        # row's epoch (no sync needed until someone writes)
        self.repl_epoch[c_shard, c_slot] = self.main_epoch[o_shard, o_slot]
        self.delta_dirty[c_shard, c_slot] = False
        if self.res is not None:
            from ..tier import coldpath
            coldpath.replica_create_tiered(self, o_shard, o_slot,
                                           c_shard, c_slot)
            return
        a = pad_bucket(n, (o_shard, 0), (o_slot, OOB), (c_shard, 0),
                       (c_slot, OOB), minimum=self.bucket_min)
        self.cache, self.delta = self.port.replica_create(
            self.main, self.cache, self.delta, *a)

    def sync_replicas(self, r_shard, r_cslot, o_shard, o_slot,
                      threshold: float = 0.0, compress: str = "off"):
        n = len(r_shard)
        if n:
            # wire accounting: what this batch ships in `compress`
            # format vs full-width f32 (tier/quant.py wire table)
            from ..tier.quant import wire_bytes_per_row
            self.sync_bytes_shipped += n * wire_bytes_per_row(
                compress, self.value_length)
            self.sync_bytes_full += n * 4 * self.value_length
        if threshold <= 0.0:
            r_sh, r_cs = np.asarray(r_shard), np.asarray(r_cslot)
            o_sh, o_sl = np.asarray(o_shard), np.asarray(o_slot)
            # only owner rows receiving a DIRTY delta advance the epoch:
            # a clean-but-stale replica's refresh merges a zero delta and
            # leaves main unchanged — bumping for it would re-stale every
            # sibling replica and the filter would ping-pong forever
            dd = self.delta_dirty[r_sh, r_cs]
            if dd.any():
                self.main_epoch[o_sh[dd], o_sl[dd]] = self._next_epoch()
            # refresh: every replica in the batch now equals its main row
            # (read AFTER the bump; duplicate owner rows agree by
            # construction — one fresh gather feeds them all)
            self.repl_epoch[r_sh, r_cs] = self.main_epoch[o_sh, o_sl]
            self.delta_dirty[r_sh, r_cs] = False
        # threshold > 0: the ship/hold decision is made ON DEVICE, so the
        # host cannot know which deltas merged or which bases refreshed —
        # leave the tracking untouched (replicas stay dirty and are
        # re-considered every round, the pre-filter behavior)
        if self.res is not None:
            from ..tier import coldpath
            coldpath.sync_replicas_tiered(self, r_shard, r_cslot,
                                          o_shard, o_slot,
                                          threshold=threshold,
                                          compress=compress)
            return
        a = pad_bucket(n, (r_shard, 0), (r_cslot, OOB), (o_shard, 0),
                       (o_slot, OOB), minimum=self.bucket_min)
        out = self.port.sync_replicas(self.main, self.cache, self.delta,
                                      *a, threshold=threshold,
                                      compress=compress)
        if compress != "off":
            (self.main, self.cache, self.delta,
             self._ef_resid_dev) = out
        else:
            self.main, self.cache, self.delta = out

    def ef_residual_norm(self) -> float:
        """Max-abs residual parked by the most recent compressed sync
        round (device + tiered host paths). Converting the device
        scalar synchronizes with the round's program — snapshot-time
        cost only, never on the round itself."""
        dev = 0.0
        if self._ef_resid_dev is not None:
            dev = float(np.asarray(self._ef_resid_dev))
        return max(dev, self._ef_resid_host)

    def relocate_rows(self, old_shard, old_slot, new_shard, new_slot,
                      rc_shard, rc_slot):
        n = len(old_shard)
        # the moved (possibly delta-merged) main rows get a fresh epoch:
        # conservative — surviving replicas of the key resync once
        m = np.asarray(new_slot) != OOB
        if m.any():
            self.main_epoch[np.asarray(new_shard)[m],
                            np.asarray(new_slot)[m]] = self._next_epoch()
        mr = np.asarray(rc_slot) != OOB
        if mr.any():  # upgraded replica slot is freed; leave it clean
            self.delta_dirty[np.asarray(rc_shard)[mr],
                             np.asarray(rc_slot)[mr]] = False
        if self.res is not None:
            from ..tier import coldpath
            coldpath.relocate_tiered(self, old_shard, old_slot,
                                     new_shard, new_slot,
                                     rc_shard, rc_slot)
            return
        a = pad_bucket(n, (old_shard, 0), (old_slot, OOB), (new_shard, 0),
                       (new_slot, OOB), (rc_shard, 0), (rc_slot, OOB),
                       minimum=self.bucket_min)
        self.main, self.delta = self.port.relocate(
            self.main, self.delta, *a)

    # -- cross-process helpers (parallel/pm.py GlobalPM) ---------------------

    def read_rows(self, which: str, sh, sl) -> np.ndarray:
        """Host readback of pool rows (non-destructive). `which` selects the
        pool; padding rows are dropped from the result. Slot-indexed for
        "main" — tier-aware (hot rows via a device gather, cold rows
        from the host cold store)."""
        if which == "main" and self.res is not None:
            from ..tier import coldpath
            return coldpath.read_main_rows_tiered(self, sh, sl)
        n = len(sh)
        a = pad_bucket(n, (sh, 0), (sl, OOB), minimum=self.bucket_min)
        arr = {"main": self.main, "cache": self.cache,
               "delta": self.delta}[which]
        rows = self.port.read_rows_at(arr, *a)
        return np.asarray(rows)[:n]

    # -- tiered-residency helpers (adapm_tpu/tier; no-ops untiered) ----------

    def read_hot_rows_at(self, sh: np.ndarray, row: np.ndarray) -> np.ndarray:
        """Host readback of hot-pool rows by DEVICE ROW index (the
        demotion/relocation readback; non-destructive)."""
        n = len(sh)
        a = pad_bucket(n, (sh, 0), (row, OOB), minimum=self.bucket_min)
        rows = self.port.read_rows_at(self.main, *a)
        return np.asarray(rows)[:n]

    def main_host(self) -> np.ndarray:
        """The full authoritative main table [S, main_slots, L] on host
        (checkpoint save, bulk reads) — one whole-pool copy untiered,
        cold store overlaid with the hot pool's rows tiered."""
        if self.res is None:
            return np.asarray(self.main)
        from ..tier import coldpath
        return coldpath.main_full_host(self)

    @property
    def main_shape_full(self):
        """Shape of the authoritative main table (checkpoint geometry —
        identical whether or not the store is tiered, so checkpoints
        restore across tier configurations)."""
        S = self.ctx.num_shards
        return (S, self.main_slots, self.value_length)

    def install_replica_rows(self, c_shard, c_slot, vals) -> None:
        n = len(c_shard)
        # cross-process replica: its base comes from a remote owner, so
        # local epochs cannot track it (cross replicas are exempt from
        # the dirty filter — core/sync.py sync_channel)
        self.delta_dirty[c_shard, c_slot] = False
        a = pad_bucket(n, (c_shard, 0), (c_slot, OOB),
                       minimum=self.bucket_min)
        v = self._vals_bucket(vals, a[0].shape[0])
        self.cache, self.delta = self.port.install_rows(
            self.cache, self.delta, *a, v)

    def refresh_after_sync(self, c_shard, c_slot, fresh, shipped) -> None:
        n = len(c_shard)
        a = pad_bucket(n, (c_shard, 0), (c_slot, OOB),
                       minimum=self.bucket_min)
        b = a[0].shape[0]
        self.cache, self.delta = self.port.refresh_after_sync(
            self.cache, self.delta, *a,
            self._vals_bucket(fresh, b), self._vals_bucket(shipped, b))

    def block(self) -> None:
        jax.block_until_ready((self.main, self.cache, self.delta))
