from .kv import Server, Worker  # noqa: F401
