"""Runtime telemetry: metrics registry, span tracing, crash breadcrumbs.

One layer every subsystem reports into (see docs/OBSERVABILITY.md):

  - `metrics.MetricsRegistry`: process-wide counters / gauges /
    bounded-bucket histograms, lock-cheap via per-thread shards merged
    at snapshot time. Owned by the Server (`Server.obs`); snapshot via
    `Server.metrics_snapshot()`. `--sys.metrics` (default on).
  - `spans.SpanTracer`: begin/end events for named phases, exported as
    Chrome trace-event JSON loadable in Perfetto. `--sys.trace.spans`
    (default off).
  - `crash.enable_crash_dumps`: faulthandler with a per-rank dump file,
    plus a last-open-span breadcrumb so an abort is attributable.
  - `reporter.Reporter`: optional periodic one-line summary
    (`--sys.metrics.report`). Imported ONLY when enabled — the hot path
    never pays for it.
"""
from .metrics import (Counter, Gauge, Histogram,  # noqa: F401
                      MetricsRegistry, get_global_registry,
                      observe_global, set_global_registry)
from .spans import NULL_SPAN, SpanTracer  # noqa: F401

__all__ = ["MetricsRegistry", "Counter", "Gauge", "Histogram",
           "SpanTracer", "NULL_SPAN", "get_global_registry",
           "set_global_registry", "observe_global"]
