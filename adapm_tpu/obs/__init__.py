"""Runtime telemetry: metrics registry, span tracing, crash breadcrumbs.

One layer every subsystem reports into (see docs/OBSERVABILITY.md):

  - `metrics.MetricsRegistry`: process-wide counters / gauges /
    bounded-bucket histograms, lock-cheap via per-thread shards merged
    at snapshot time. Owned by the Server (`Server.obs`); snapshot via
    `Server.metrics_snapshot()`. `--sys.metrics` (default on).
  - `spans.SpanTracer`: begin/end events for named phases, exported as
    Chrome trace-event JSON loadable in Perfetto. `--sys.trace.spans`
    (default off).
  - `crash.enable_crash_dumps`: faulthandler with a per-rank dump file,
    plus a last-open-span breadcrumb so an abort is attributable.
  - `flight.FlightTracer`: per-request causal traces across admission
    -> batch -> executor -> device, exported as Perfetto FLOW events —
    one served lookup renders as one connected chain.
    `--sys.trace.flight` (default off). `flight.FlightRecorder`: the
    bounded per-stream ring of the last executor programs, mirrored to
    a ring file for abort post-mortems (rides `--sys.crash_dumps`).
  - `slo.SLOController`: the closed-loop tail-latency controller that
    adapts the serve micro-batch window toward `--sys.serve.slo_ms`.
    Imported ONLY when a target is set.
  - `reporter.Reporter`: optional periodic one-line summary
    (`--sys.metrics.report`). Imported ONLY when enabled — the hot path
    never pays for it.
"""
from .metrics import (Counter, Gauge, Histogram,  # noqa: F401
                      MetricsRegistry, get_global_registry,
                      observe_global, set_global_registry)
from .spans import NULL_SPAN, SpanTracer  # noqa: F401

__all__ = ["MetricsRegistry", "Counter", "Gauge", "Histogram",
           "SpanTracer", "NULL_SPAN", "get_global_registry",
           "set_global_registry", "observe_global"]
