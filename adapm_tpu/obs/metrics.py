"""Process-wide metrics registry: counters, gauges, bounded histograms.

Design goals (ISSUE 2 tentpole):

  - **Lock-cheap on the hot path.** A Counter/Histogram increment touches
    only a per-thread cell (one dict lookup on `threading.local` + a
    float add); shards are merged under a lock only at snapshot time.
    Worker threads, the prefetch thread, sync threads, and DCN handler
    threads all report without contending.
  - **Bounded memory.** Histograms have a fixed geometric bucket ladder
    (`LATENCY_BOUNDS_S`: 1 µs .. ~17 s, 14 buckets) — never per-value
    storage.
  - **One namespace.** Metric names are dotted (`section.name`); the
    first segment groups the snapshot (`kv.pull_s` lands in
    `snapshot()["kv"]["pull_s"]`). Registering the same name twice
    raises unless the caller declares the metric `shared` (several
    DeviceRoutedRunners legitimately feed one `fused.*` counter) — the
    duplicate-name check that keeps two subsystems from silently
    splitting one counter.
  - **Free when off.** A disabled registry hands out null metric
    singletons whose ops are no-ops and whose snapshot is empty;
    callers that want to skip even the `perf_counter()` bracketing
    check `registry.enabled` once and cache the decision.

The registry is owned by the Server (`Server.obs`). Module-level
`set_global_registry`/`observe_global` exist for call sites with no
server handle (parallel/control.py barrier/allreduce waits): the most
recently constructed live Server registers itself, held weakly.
"""
from __future__ import annotations

import bisect
import threading
import weakref
from typing import Callable, Dict, List, Optional

# default latency ladder, seconds: geometric x4 from 1 µs; the +inf
# overflow bucket is implicit (len(bounds) + 1 buckets total)
LATENCY_BOUNDS_S = tuple(1e-6 * 4 ** i for i in range(13))

# serving-path latency ladder: geometric x2 from 20 µs to ~2.6 s. The
# serve plane reports P50/P99 through `hist_percentile`, whose in-bucket
# interpolation error is bounded by the bucket ratio — x2 halves the
# worst-case error of the x4 default where the latency SLO lives
# (adapm_tpu/serve; docs/SERVING.md "Tuning").
SERVE_LATENCY_BOUNDS_S = tuple(2e-5 * 2 ** i for i in range(18))

# micro-batch size ladder (requests per coalesced batch): powers of two
# up to 1024 — `serve.batch_size` is a count histogram, not a latency
BATCH_SIZE_BOUNDS = tuple(float(2 ** i) for i in range(11))


class Counter:
    """Monotonic float counter, per-thread sharded."""

    kind = "counter"

    def __init__(self, name: str, unit: str = ""):
        self.name = name
        self.unit = unit
        self._local = threading.local()
        self._cells: List[List[float]] = []
        self._lock = threading.Lock()

    def _cell(self) -> List[float]:
        c = getattr(self._local, "c", None)
        if c is None:
            c = self._local.c = [0.0]
            with self._lock:
                self._cells.append(c)
        return c

    def inc(self, n: float = 1) -> None:
        self._cell()[0] += n

    @property
    def value(self) -> float:
        with self._lock:
            return sum(c[0] for c in self._cells)

    def snap(self):
        v = self.value
        return int(v) if float(v).is_integer() else v


class Gauge:
    """Last-writer-wins value, or a callable evaluated at snapshot time
    (zero hot-path cost: occupancy/version gauges read live structures
    only when someone asks)."""

    kind = "gauge"

    def __init__(self, name: str, unit: str = "",
                 fn: Optional[Callable[[], float]] = None):
        self.name = name
        self.unit = unit
        self._fn = fn
        self._value = 0.0

    def set(self, v: float) -> None:
        self._value = v

    @property
    def value(self):
        if self._fn is not None:
            return self._fn()
        return self._value

    def snap(self):
        return self.value


class Histogram:
    """Bounded-bucket histogram, per-thread sharded.

    Each thread owns [bucket_counts..., count, sum, max]; `observe` is a
    bisect + three adds on the thread's own cell. Merge happens at
    snapshot time under the cell-list lock.
    """

    kind = "histogram"

    def __init__(self, name: str, unit: str = "s",
                 bounds=LATENCY_BOUNDS_S):
        self.name = name
        self.unit = unit
        self.bounds = tuple(float(b) for b in bounds)
        self._nb = len(self.bounds) + 1  # + overflow
        self._local = threading.local()
        self._cells: List[List[float]] = []
        self._lock = threading.Lock()

    def _cell(self) -> List[float]:
        c = getattr(self._local, "c", None)
        if c is None:
            c = self._local.c = [0.0] * (self._nb + 3)
            with self._lock:
                self._cells.append(c)
        return c

    def observe(self, v: float) -> None:
        c = self._cell()
        c[bisect.bisect_left(self.bounds, v)] += 1
        c[self._nb] += 1
        c[self._nb + 1] += v
        if v > c[self._nb + 2]:
            c[self._nb + 2] = v

    def snap(self) -> Dict:
        with self._lock:
            cells = [list(c) for c in self._cells]
        buckets = [0] * self._nb
        count = 0
        total = 0.0
        mx = 0.0
        for c in cells:
            for i in range(self._nb):
                buckets[i] += int(c[i])
            count += int(c[self._nb])
            total += c[self._nb + 1]
            mx = max(mx, c[self._nb + 2])
        return {"count": count, "sum": total,
                "avg": (total / count) if count else 0.0,
                "max": mx, "bounds": list(self.bounds),
                "buckets": buckets}

    @property
    def count(self) -> int:
        with self._lock:
            return int(sum(c[self._nb] for c in self._cells))


class _NullMetric:
    """Shared no-op stand-in handed out by a disabled registry."""

    name = "<disabled>"
    unit = ""
    value = 0
    count = 0

    def inc(self, n: float = 1) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def observe(self, v: float) -> None:
        pass

    def snap(self):
        return 0


_NULL = _NullMetric()


class MetricsRegistry:
    """One namespace of metrics; see module docstring. `--sys.metrics 0`
    constructs it disabled: every factory returns the null metric and
    `snapshot()` is `{}` — subsystems keep their wiring, the process
    pays nothing."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    # -- factories -----------------------------------------------------------

    def _register(self, name: str, kind: str, make, shared: bool):
        if not self.enabled:
            return _NULL
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not shared or m.kind != kind:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{m.kind} (declare shared=True only for a "
                        f"metric several reporters legitimately feed)")
                return m
            m = make()
            self._metrics[name] = m
            return m

    def counter(self, name: str, unit: str = "",
                shared: bool = False) -> Counter:
        return self._register(name, "counter",
                              lambda: Counter(name, unit), shared)

    def gauge(self, name: str, unit: str = "", fn=None,
              shared: bool = False) -> Gauge:
        g = self._register(name, "gauge",
                           lambda: Gauge(name, unit, fn=fn), shared)
        if shared and fn is not None and isinstance(g, Gauge):
            # a shared gauge rebinds to the LATEST provider: a subsystem
            # torn down and rebuilt on the same server (e.g. a second
            # ServePlane after close()) must not leave the gauge reading
            # the dead instance's structures
            g._fn = fn
        return g

    def histogram(self, name: str, unit: str = "s",
                  bounds=LATENCY_BOUNDS_S,
                  shared: bool = False) -> Histogram:
        return self._register(
            name, "histogram",
            lambda: Histogram(name, unit, bounds=bounds), shared)

    def find(self, name: str):
        """Existing metric or None (never creates)."""
        with self._lock:
            return self._metrics.get(name)

    # -- snapshot ------------------------------------------------------------

    def snapshot(self) -> Dict[str, Dict]:
        """{section: {metric: value}} — section is the first dotted
        segment of the name; histogram values are dicts (count / sum /
        avg / max / bounds / buckets). Empty when disabled."""
        if not self.enabled:
            return {}
        with self._lock:
            items = list(self._metrics.items())
        out: Dict[str, Dict] = {}
        for name, m in items:
            sec, _, rest = name.partition(".")
            out.setdefault(sec, {})[rest or name] = m.snap()
        return out

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)


class CounterGroup:
    """Dict-like view over a fixed set of registry counters
    (`prefix.key`) — how the pre-existing ad-hoc stat dicts
    (PrefetchScheduler.stats) fold into the registry while their old
    read accessors (`stats["hits"]`, `dict(stats)`) keep working. When
    the registry is off, standalone counters back the view so the
    subsystem's own accounting survives `--sys.metrics 0`."""

    def __init__(self, registry: Optional[MetricsRegistry], prefix: str,
                 keys, unit: str = ""):
        use_reg = registry is not None and registry.enabled
        self._counters: Dict[str, Counter] = {
            k: (registry.counter(f"{prefix}.{k}", unit) if use_reg
                else Counter(f"{prefix}.{k}", unit))
            for k in keys}

    def inc(self, key: str, n: float = 1) -> None:
        self._counters[key].inc(n)

    def __getitem__(self, key: str):
        return self._counters[key].snap()

    def __setitem__(self, key: str, v) -> None:
        # legacy `stats[k] += n` support: apply the delta
        c = self._counters[key]
        c.inc(v - c.value)

    def __contains__(self, key: str) -> bool:
        return key in self._counters

    def __iter__(self):
        return iter(self._counters)

    def keys(self):
        return self._counters.keys()

    def items(self):
        return ((k, c.snap()) for k, c in self._counters.items())

    def as_dict(self) -> Dict[str, float]:
        return {k: c.snap() for k, c in self._counters.items()}


def hist_percentile(snap: Dict, q: float) -> float:
    """Approximate quantile `q` (0..1) from a `Histogram.snap()` dict by
    linear interpolation inside the containing bucket — the consumer-side
    P50/P90 extraction for bounded-bucket histograms (bench.py `mgmt`
    phase, staleness reporting). Observations in the +inf overflow bucket
    clamp to the last finite bound; an empty histogram returns 0."""
    count = snap.get("count", 0)
    if not count:
        return 0.0
    bounds = snap["bounds"]
    buckets = snap["buckets"]
    target = q * count
    acc = 0.0
    for i, b in enumerate(buckets):
        below = acc
        acc += b
        if acc >= target:
            if i >= len(bounds):
                return float(bounds[-1])
            lo = float(bounds[i - 1]) if i > 0 else 0.0
            hi = float(bounds[i])
            frac = (target - below) / b if b else 0.0
            return lo + frac * (hi - lo)
    return float(bounds[-1])


# -- global hook (call sites with no Server handle) --------------------------

_global_ref: Optional["weakref.ref"] = None


def set_global_registry(reg: Optional[MetricsRegistry]) -> None:
    """Register `reg` as the process default (weakly held; the most
    recently constructed live Server wins). Pass None to clear."""
    global _global_ref
    _global_ref = weakref.ref(reg) if reg is not None else None


def clear_global_registry(reg: MetricsRegistry) -> None:
    """Clear the process default iff it is still `reg` (a later Server
    may have replaced it; its registration must survive our shutdown)."""
    global _global_ref
    if _global_ref is not None and _global_ref() is reg:
        _global_ref = None


def get_global_registry() -> Optional[MetricsRegistry]:
    ref = _global_ref
    if ref is None:
        return None
    reg = ref()
    return reg if reg is not None and reg.enabled else None


def observe_global(name: str, value: float) -> None:
    """Record into a pre-registered histogram of the process-default
    registry; silently a no-op when no enabled registry is live or the
    metric was never created (the Server registers the collective.*
    histograms at construction)."""
    reg = get_global_registry()
    if reg is None:
        return
    h = reg.find(name)
    if h is not None:
        h.observe(value)


class timed:
    """THE wall-time histogram bracket (one implementation, not a
    per-site perf_counter/try-finally copy): observes elapsed seconds
    into `target` on exit — a Histogram (or the null metric), or a
    metric NAME resolved through the process-default registry at exit
    (observe_global semantics, for call sites with no server handle)."""

    __slots__ = ("target", "_t0")

    def __init__(self, target):
        self.target = target
        self._t0 = 0.0

    def __enter__(self):
        import time
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        import time
        dt = time.perf_counter() - self._t0
        if isinstance(self.target, str):
            observe_global(self.target, dt)
        else:
            self.target.observe(dt)
        return False
