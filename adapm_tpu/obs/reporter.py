"""Periodic one-line metrics report (`--sys.metrics.report N` seconds).

IMPORTANT: this module is imported ONLY when the reporter is enabled
(Server checks `opts.metrics and opts.metrics_report_s > 0` before
importing) — with `--sys.metrics 0` the hot path never loads it, which
tests/test_observability.py asserts. Keep it free of side effects at
import time.

The report reads the REGISTRY only (no fused locstat drain, no device
sync): a line every N seconds must not force device readbacks the way a
full `Server.metrics_snapshot()` may.

Line format (STABLE — tests/test_flight.py::test_reporter_line_format
pins it; tools that grep logs for these fields may rely on it):
space-separated `field=value` groups, each emitted only when its
subsystem has activity, always in this order:

    pull=<n> avg=<ms>ms  push=<n> avg=<ms>ms   kv op counts + mean
    staged_hit=<ratio>                         prefetch hit rate
    plan_hit=<ratio>                           plan-cache hit rate
    rounds=<n> reloc=<n> repl=<n>              sync activity
    serve=<n> p50=<ms>ms p99=<ms>ms            lookups + latency tail
    overlap=<ratio>                            exec overlap_fraction
    hot_hit=<ratio>                            tier hot-hit rate
    fresh=<ms>ms                               push-to-servable P99
                                               (flight.freshness_s)
    regret=<ratio>                             worst per-plane decision
                                               regret rate (ISSUE 17)
    policy=<applied>/<consults>                learned-policy verdicts
                                               applied vs consults;
                                               `shadow_dis=<n>` rides
                                               along when shadow mode
                                               disagreed (ISSUE 18)
    net=<msgs>/<bytes> peers=<live>/<total>    transport-plane frames
                                               sent + peer liveness
                                               once a NetPort is
                                               attached (ISSUE 19)

Ratios are 2-decimal, latencies 2-decimal milliseconds."""
from __future__ import annotations

import threading
from typing import Optional

from .metrics import hist_percentile


def _fmt(snap: dict) -> str:
    """Compress a registry snapshot into one line of the load-bearing
    numbers (format contract in the module docstring); unknown sections
    degrade to counts, never crash."""
    parts = []
    kv = snap.get("kv", {})
    for h in ("pull_s", "push_s"):
        d = kv.get(h)
        if isinstance(d, dict) and d.get("count"):
            parts.append(f"{h[:-2]}={d['count']} "
                         f"avg={d['avg'] * 1e3:.2f}ms")
    pf = snap.get("prefetch", {})
    if pf.get("staged"):
        tot = pf.get("hits", 0) + pf.get("expired", 0) or 1
        parts.append(f"staged_hit={pf.get('hits', 0) / tot:.2f}")
    pc = snap.get("plan_cache", {})
    att = pc.get("hits", 0) + pc.get("misses", 0) + pc.get("stale", 0)
    if att:
        parts.append(f"plan_hit={pc.get('hits', 0) / att:.2f}")
    sy = snap.get("sync", {})
    if sy.get("rounds"):
        parts.append(f"rounds={sy['rounds']} "
                     f"reloc={sy.get('relocations', 0)} "
                     f"repl={sy.get('replicas_created', 0)}")
    # serving plane: lookup count + the latency tail the SLO lives on
    sv = snap.get("serve", {})
    lat = sv.get("latency_s")
    if isinstance(lat, dict) and lat.get("count"):
        parts.append(
            f"serve={sv.get('lookups_total', lat['count'])} "
            f"p50={hist_percentile(lat, 0.50) * 1e3:.2f}ms "
            f"p99={hist_percentile(lat, 0.99) * 1e3:.2f}ms")
    # executor: cross-stream overlap once any program has run
    ex = snap.get("exec", {})
    if ex.get("programs_total"):
        parts.append(f"overlap={ex.get('overlap_fraction', 0.0):.2f}")
    # tiered storage: hot-hit rate once any tiered gather ran
    tr = snap.get("tier", {})
    if tr.get("hot_hits", 0) or tr.get("cold_hits", 0):
        parts.append(f"hot_hit={tr.get('hot_hit_rate', 0.0):.2f}")
    # push-to-servable freshness tail (flight probe) once it has samples
    fr = snap.get("flight", {}).get("freshness_s")
    if isinstance(fr, dict) and fr.get("count"):
        parts.append(f"fresh={hist_percentile(fr, 0.99) * 1e3:.2f}ms")
    # decision telemetry: the worst per-plane regret rate once any
    # outcome window resolved (ISSUE 17)
    dc = snap.get("decision", {})
    rates = [v for k, v in dc.items() if k.startswith("regret_rate.")
             and isinstance(v, (int, float))]
    if dc.get("events_total") and rates:
        parts.append(f"regret={max(rates):.2f}")
    # learned-policy plane: verdicts applied vs consults once any
    # decision site consulted a model (ISSUE 18); absent by default —
    # the policy counters only register when a policy file is loaded
    po = snap.get("policy", {})
    if po.get("consults_total"):
        parts.append(f"policy={po.get('applied_total', 0)}"
                     f"/{po['consults_total']}")
        if po.get("shadow_disagree"):
            parts.append(f"shadow_dis={po['shadow_disagree']}")
    # transport plane: frames sent + peer liveness once a NetPort is
    # attached (ISSUE 19); absent by default — the net.* names only
    # register when a membership plane exists (loopback/tcp node)
    nt = snap.get("net", {})
    if nt.get("msgs_out") or nt.get("msgs_in"):
        parts.append(f"net={nt.get('msgs_out', 0)}"
                     f"/{nt.get('bytes_out', 0)} "
                     f"peers={nt.get('peers_live', 0)}"
                     f"/{nt.get('peers_total', 0)}")
    return " ".join(parts) or "no activity yet"


class Reporter:
    """Background thread logging `_fmt(registry.snapshot())` every
    `interval_s`. Daemon; `stop()` joins it."""

    def __init__(self, registry, interval_s: float, rank: int = 0):
        self.registry = registry
        self.interval_s = interval_s
        self.rank = rank
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="adapm-metrics-report")
        self._thread.start()

    def _loop(self) -> None:
        from ..utils.log import alog
        while not self._stop.wait(self.interval_s):
            alog(f"[metrics r{self.rank}] "
                 f"{_fmt(self.registry.snapshot())}")

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
            self._thread = None
