"""Workload trace capture: the semantic op stream, recorded once,
replayable forever (ISSUE 15 tentpole, capture half; ROADMAP item 3).

Every policy in this system — tier scoring, relocation-vs-replication,
the SLO control law, admission windows — is a hand-tuned constant that
can only be evaluated by running the live system. The
`WorkloadTraceRecorder` (`--sys.trace.workload PATH`, default **off**)
fixes the other half of that equation: it records the workload's
SEMANTIC op stream — pull/push/set key batches, intent windows, clock
advances, serve lookups with tenant/priority/deadline,
PrepareSample/PullSample, and the relocation/sync/promotion decisions
as they landed — into a versioned, checksummed `.wtrace` file that the
offline replay engine (`adapm_tpu/replay/`) re-drives deterministically
against a fresh server under candidate knob overrides. Capture once,
score policies forever — the COGNATE transferred-trace methodology
(PAPERS.md) applied to parameter management; the DLRM embedding-bag
access shapes (multi-class gathers, zipf skew, intent windows) are
exactly what the key-batch events preserve.

Disciplines (all inherited from earlier planes):

  - **Default off at the r7 skip-wrapper cost.** With no
    `--sys.trace.workload`, `Server.wtrace is None`, every instrumented
    site pays one `is None` check, and the registry holds zero
    `wtrace.*` names (pinned by `scripts/metrics_overhead_check.py` and
    adapm-lint APM003 — `wtrace` is an OPTIONAL_HANDLE).
  - **Lossless-or-loudly-sampled.** Key batches up to
    `--sys.trace.workload_keys` record their EXACT keys; larger batches
    record an evenly-strided sample plus the true count and a
    `sampled` marker (`wtrace.sampled_batches_total` counts them —
    never a silent truncation). The event buffer itself is bounded
    (`max_events`); events beyond it are counted in
    `wtrace.dropped_total` and logged once, never silently lost.
  - **Both clock domains, always.** Every event carries the logical
    clock (the issuing worker's, or the server-wide max for
    server-side events), `wall` (`time.time()`) AND `mono`
    (`time.monotonic()`) — merged timelines and replay alignment must
    not skew across NTP steps (the ISSUE 15 clock-domain satellite
    applies the same rule to the flight recorder and SLO move log).
  - **Atomic, versioned, checksummed file.** `flush()` writes a
    one-line JSON header (format name, version, body sha256, body
    byte count) followed by the JSON body via the r15 checkpoint
    discipline (tmp + fsync + rename). `load_wtrace` verifies format,
    version, length, and digest BEFORE returning anything — a
    truncated or flipped file raises the named `WorkloadTraceError`,
    never a half-parsed trace (and therefore never a half-replayed
    server).

Event kinds (the `kind` field):

  `pull` / `push` / `set`   worker data-plane ops (wid, clock, keys)
  `intent`                  intent window (wid, clock, keys, start, end)
  `clock`                   advance_clock (wid, new clock)
  `serve`                   ServeSession.lookup (keys, tenant,
                            priority, deadline_ms)
  `prep_sample` / `pull_sample` / `finish_sample`
                            managed sampling (wid, handle, n, window)
  `sync`                    a completed sync round (forced,
                            all_channels, wire bytes) — replay
                            re-drives these instead of running a
                            timer-driven background loop
  `quiesce`                 full quiesce points
  `reloc` / `promote`       management decisions as they landed
                            (observational: replay lets the candidate
                            policy re-decide; the recorded stream is
                            the baseline to compare against)
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
import time
import zlib
from typing import Dict, List, Optional

import numpy as np

WTRACE_FORMAT = "adapm-wtrace"
WTRACE_VERSION = 1

# hard bounds on the buffered stream (loud drop counter beyond either);
# the per-event key budget is the --sys.trace.workload_keys knob. The
# byte bound is an APPROXIMATE host-memory guard: an event-count bound
# alone would let 1M max-budget key batches grow to tens of GB resident
DEFAULT_MAX_EVENTS = 1_000_000
DEFAULT_MAX_BYTES = 256 * 1024 * 1024


class WorkloadTraceError(RuntimeError):
    """The `.wtrace` file is unreadable: wrong format/version, truncated
    body, checksum mismatch, or malformed JSON. Raised by `load_wtrace`
    during verification, BEFORE any replay server exists — a corrupt
    trace can never half-drive a replay (fault/ckpt.py discipline)."""


from ..utils import write_atomic as _write_atomic  # noqa: E402 — the
# shared tmp+fsync+rename discipline (adapm_tpu/utils): a crash
# mid-flush leaves the previous file (or nothing), never a torn trace


# ---------------------------------------------------------------------------
# shared trace-file machinery (ISSUE 17): the .dtrace decision trace
# (obs/decisions.py) writes and verifies through the SAME header/
# checksum/atomic-rename code path as the .wtrace — one discipline,
# two formats, zero drift between their corruption guarantees
# ---------------------------------------------------------------------------


def write_trace_file(path: str, doc: Dict, fmt: str,
                     version: int) -> int:
    """Serialize `doc` and write it atomically as a one-line JSON
    header (format, version, body sha256, body byte count) + JSON
    body. Returns the total bytes written."""
    body = json.dumps(doc, separators=(",", ":")).encode()
    header = json.dumps(
        {"format": fmt, "version": version,
         "body_sha256": hashlib.sha256(body).hexdigest(),
         "body_bytes": len(body)}).encode()
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    _write_atomic(path, header + b"\n" + body)
    return len(header) + 1 + len(body)


def load_trace_doc(path: str, fmt: str, version: int, err_cls,
                   noun: str) -> Dict:
    """Read + verify one header-lined trace file; returns the parsed
    body dict. Verification order (format -> version -> length ->
    sha256) runs BEFORE any parse of the body — a truncated or flipped
    file raises the caller's named `err_cls`, never a half-parsed
    trace."""
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError as e:
        raise err_cls(f"cannot read {noun} {path!r}: {e}") from e
    nl = raw.find(b"\n")
    if nl < 0:
        raise err_cls(f"{noun} {path!r}: missing header line "
                      f"(truncated or not a {fmt} file)")
    try:
        header = json.loads(raw[:nl])
    except ValueError as e:
        raise err_cls(f"{noun} {path!r}: unparseable header: {e}") from e
    if header.get("format") != fmt:
        raise err_cls(f"{noun} {path!r}: format "
                      f"{header.get('format')!r} is not {fmt!r}")
    if header.get("version") != version:
        raise err_cls(f"{noun} {path!r}: version "
                      f"{header.get('version')!r} unsupported (this "
                      f"build reads v{version})")
    body = raw[nl + 1:]
    want_bytes = header.get("body_bytes")
    if want_bytes != len(body):
        raise err_cls(f"{noun} {path!r}: body is {len(body)} bytes, "
                      f"header promised {want_bytes} (truncated "
                      f"write?)")
    digest = hashlib.sha256(body).hexdigest()
    if digest != header.get("body_sha256"):
        raise err_cls(f"{noun} {path!r}: body sha256 mismatch "
                      f"(bit flip / partial overwrite) — refusing to "
                      f"load")
    try:
        return json.loads(body)
    except ValueError as e:
        raise err_cls(f"{noun} {path!r}: checksummed body failed to "
                      f"parse ({e}) — file written by an incompatible "
                      f"recorder?") from e


class WorkloadTraceRecorder:
    """One per Server when `--sys.trace.workload` names a path; owned
    and closed by the server (shutdown step 9, after every producer is
    stopped). Thread-safe: client threads, executor workers, and the
    sync round all record concurrently under one small lock (append +
    counter bumps only — never a device wait, never the server lock)."""

    def __init__(self, server, path: str, key_budget: int = 4096,
                 max_events: int = DEFAULT_MAX_EVENTS,
                 max_bytes: int = DEFAULT_MAX_BYTES):
        from .metrics import Counter, Gauge
        if not path:
            raise ValueError("workload trace capture needs a path "
                             "(--sys.trace.workload)")
        self._server = server
        self.path = path
        self.key_budget = max(1, int(key_budget))
        self.max_events = int(max_events)
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        # serializes snapshot->serialize->rename so a mid-run flush
        # racing close() cannot publish an older snapshot over a newer
        # one (the torn-file half of that race is already gone: the
        # shared write_atomic uses writer-unique tmp names)
        self._flush_lock = threading.Lock()
        self._events: List[Dict] = []
        self._approx_bytes = 0
        self._seq = 0
        self._closed = False
        self._flushes = 0
        self._warned_drop = False
        self.wall_t0 = time.time()
        self.mono_t0 = time.monotonic()
        reg = server.obs
        use_reg = reg is not None and reg.enabled
        if use_reg:
            self.c_events = reg.counter("wtrace.events_total")
            self.c_dropped = reg.counter("wtrace.dropped_total")
            self.c_sampled = reg.counter("wtrace.sampled_batches_total")
            self.g_bytes = reg.gauge("wtrace.bytes_written")
        else:  # capture works with --sys.metrics 0 (standalone tallies)
            self.c_events = Counter("wtrace.events_total")
            self.c_dropped = Counter("wtrace.dropped_total")
            self.c_sampled = Counter("wtrace.sampled_batches_total")
            self.g_bytes = Gauge("wtrace.bytes_written")

    # -- recording -----------------------------------------------------------

    def _server_clock(self) -> int:
        c = self._server._clocks
        return int(c.max()) if len(c) else 0

    def _key_fields(self, keys: np.ndarray) -> Dict:
        """Exact keys up to the budget; an evenly-strided sample plus
        the true count beyond it (sampled-with-counts, counted loudly —
        never a silent truncation)."""
        n = len(keys)
        out: Dict = {"n": int(n),
                     "fp": int(zlib.crc32(np.ascontiguousarray(
                         keys, dtype=np.int64).tobytes()))}
        if n <= self.key_budget:
            out["keys"] = [int(k) for k in keys]
        else:
            stride = -(-n // self.key_budget)  # ceil: <= budget samples
            out["sample"] = [int(k) for k in keys[::stride]]
            out["sampled"] = True
            self.c_sampled.inc()
        return out

    def _append(self, ev: Dict) -> None:
        # approximate resident cost: fixed stamps + the boxed key ints
        # (8 bytes of JSON/int each is the right order of magnitude)
        cost = 96 + 8 * (len(ev.get("keys", ())) +
                         len(ev.get("sample", ())))
        with self._lock:
            if self._closed:
                return
            if len(self._events) >= self.max_events or \
                    self._approx_bytes + cost > self.max_bytes:
                self.c_dropped.inc()
                if not self._warned_drop:
                    self._warned_drop = True
                    from ..utils import alog
                    alog(f"[wtrace] event buffer full "
                         f"({len(self._events)} events, "
                         f"~{self._approx_bytes >> 20} MiB); further "
                         f"events are DROPPED (counted in "
                         f"wtrace.dropped_total) — the captured trace "
                         f"is a loud prefix, not a silent lie")
                return
            ev["seq"] = self._seq
            self._seq += 1
            self._events.append(ev)
            self._approx_bytes += cost
        self.c_events.inc()

    def _base(self, kind: str, clock: int,
              wid: Optional[int] = None) -> Dict:
        ev: Dict = {"kind": kind, "clock": int(clock),
                    "wall": time.time(), "mono": time.monotonic()}
        if wid is not None:
            ev["wid"] = int(wid)
        return ev

    def record_kv(self, op: str, wid: int, clock: int,
                  keys: np.ndarray) -> None:
        """A worker data-plane op: op in {"pull", "push", "set"}."""
        ev = self._base(op, clock, wid)
        ev.update(self._key_fields(keys))
        self._append(ev)

    def record_intent(self, wid: int, clock: int, keys: np.ndarray,
                      start: int, end: int) -> None:
        ev = self._base("intent", clock, wid)
        ev.update(self._key_fields(keys))
        ev["start"] = int(start)
        ev["end"] = min(int(end), 2**62)  # CLOCK_MAX stays JSON-safe
        self._append(ev)

    def record_clock(self, wid: int, clock: int) -> None:
        self._append(self._base("clock", clock, wid))

    def record_serve(self, keys: np.ndarray, tenant: Optional[str],
                     priority: int, deadline_ms: float) -> None:
        ev = self._base("serve", self._server_clock())
        ev.update(self._key_fields(keys))
        ev["tenant"] = tenant
        ev["priority"] = int(priority)
        ev["deadline_ms"] = float(deadline_ms or 0.0)
        self._append(ev)

    def record_sample(self, op: str, wid: int, clock: int, handle: int,
                      n: Optional[int], start: Optional[int] = None,
                      end: Optional[int] = None) -> None:
        """Managed-sampling lifecycle: op in {"prep_sample",
        "pull_sample", "finish_sample"}."""
        ev = self._base(op, clock, wid)
        ev["handle"] = int(handle)
        if n is not None:
            ev["n"] = int(n)
        if start is not None:
            ev["start"] = int(start)
        if end is not None:
            ev["end"] = int(end)
        self._append(ev)

    def record_sync(self, forced: bool, all_channels: bool,
                    bytes_shipped: int) -> None:
        """A completed sync round — replay re-drives these events
        instead of running the timer-driven background loop (the
        determinism lever: rounds happen where the WORKLOAD put them,
        not where a wall clock did)."""
        ev = self._base("sync", self._server_clock())
        ev["forced"] = bool(forced)
        ev["all"] = bool(all_channels)
        ev["bytes"] = int(bytes_shipped)
        self._append(ev)

    def record_quiesce(self) -> None:
        self._append(self._base("quiesce", self._server_clock()))

    def record_decision(self, kind: str, n: int, **fields) -> None:
        """A management decision as it landed (kind in {"reloc",
        "promote"}): observational — replay lets the candidate policy
        re-decide, and the recorded stream is the baseline it is
        scored against."""
        ev = self._base(kind, self._server_clock())
        ev["n"] = int(n)
        for k, v in fields.items():
            ev[k] = v
        self._append(ev)

    # -- meta / stats --------------------------------------------------------

    def _meta(self) -> Dict:
        import dataclasses
        import enum
        srv = self._server
        lens = srv.value_lengths
        uniform = len(np.unique(lens)) == 1
        knobs = {}
        for k, v in dataclasses.asdict(srv.opts).items():
            knobs[k] = v.value if isinstance(v, enum.Enum) else v
        return {"num_keys": int(srv.num_keys),
                "value_lengths": (int(lens[0]) if uniform
                                  else [int(x) for x in lens]),
                "num_shards": int(srv.ctx.num_shards),
                "rank": int(srv.pid),
                "key_budget": self.key_budget,
                "wall_t0": self.wall_t0,
                "mono_t0": self.mono_t0,
                "knobs": knobs}

    def stats(self) -> Dict:
        """Plain-value summary for `metrics_snapshot()["wtrace"]` (the
        registry-backed wtrace.* counters land in the same section)."""
        with self._lock:
            n = len(self._events)
        return {"path": self.path, "events_buffered": n,
                "flushes": self._flushes, "closed": self._closed}

    # -- flush / close -------------------------------------------------------

    def flush(self) -> str:
        """Write the full trace (header line + checksummed JSON body)
        atomically; returns the path. Safe to call mid-run for a
        point-in-time trace (concurrent flushes serialize on the flush
        lock, so the file on disk is always SOME complete snapshot and
        snapshots publish in order); close() performs the final
        flush."""
        with self._flush_lock:
            with self._lock:
                doc = {"meta": self._meta(),
                       "events": list(self._events),
                       "dropped": int(self.c_dropped.value)}
            nbytes = write_trace_file(self.path, doc, WTRACE_FORMAT,
                                      WTRACE_VERSION)
            with self._lock:
                self._flushes += 1
            self.g_bytes.set(float(nbytes))
        return self.path

    def close(self) -> None:
        """Final flush + seal (idempotent). Events recorded after close
        are ignored — the server is tearing down and the file on disk
        is the trace."""
        with self._lock:
            if self._closed:
                return
        self.flush()
        with self._lock:
            self._closed = True


# ---------------------------------------------------------------------------
# loading (shared by the replay engine and tooling)
# ---------------------------------------------------------------------------


class WorkloadTrace:
    """A verified, parsed `.wtrace`: `meta` dict + `events` list (seq
    order). Construction implies the checksum passed."""

    __slots__ = ("path", "meta", "events", "dropped")

    def __init__(self, path: str, meta: Dict, events: List[Dict],
                 dropped: int):
        self.path = path
        self.meta = meta
        self.events = events
        self.dropped = dropped

    @property
    def value_lengths(self):
        return self.meta["value_lengths"]

    def max_worker_id(self) -> int:
        return max((ev.get("wid", 0) for ev in self.events), default=0)

    def kinds(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for ev in self.events:
            out[ev["kind"]] = out.get(ev["kind"], 0) + 1
        return out


def event_keys(ev: Dict, rng: Optional[np.random.Generator] = None,
               ) -> np.ndarray:
    """The event's key batch. Exact events return their recorded keys;
    sampled events reconstruct a batch of the TRUE size by drawing from
    the recorded sample — deterministic given the caller's seeded
    `rng` (required for sampled events: reconstruction without a seed
    would be a silent nondeterminism hole)."""
    if "keys" in ev:
        return np.asarray(ev["keys"], dtype=np.int64)
    sample = np.asarray(ev["sample"], dtype=np.int64)
    if rng is None:
        raise ValueError(
            f"event seq={ev.get('seq')} was key-sampled at capture "
            f"(n={ev['n']} > budget); reconstructing its batch needs "
            f"a seeded rng")
    return rng.choice(sample, size=int(ev["n"]), replace=True)


def load_wtrace(path: str) -> WorkloadTrace:
    """Read + verify a `.wtrace` file. Raises `WorkloadTraceError` on a
    missing/truncated/corrupt/incompatible file — named, and BEFORE any
    replay state exists."""
    doc = load_trace_doc(path, WTRACE_FORMAT, WTRACE_VERSION,
                         WorkloadTraceError, "workload trace")
    try:
        meta = doc["meta"]
        events = doc["events"]
    except (KeyError, TypeError) as e:
        raise WorkloadTraceError(
            f"workload trace {path!r}: checksummed body failed to "
            f"parse ({e}) — file written by an incompatible "
            f"recorder?") from e
    return WorkloadTrace(path, meta, events, int(doc.get("dropped", 0)))
