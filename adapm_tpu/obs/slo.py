"""SLO autopilot: a closed-loop tail-latency controller for the serve
plane (ISSUE 7 tentpole part 2; the ROADMAP-2 adaptive-wait
controller).

`--sys.serve.max_wait_us` — the micro-batch coalescing window — is the
throughput/latency dial of the serving plane, and before this module it
was a hand-tuned constant every deployment shared. With
`--sys.serve.slo_ms` set, an `SLOController` observes the serve P99
from the existing `serve.latency_s` histogram ladder (windowed: each
control tick diffs the cumulative buckets against the previous tick and
extracts the quantile of JUST that window via `hist_percentile`) and
walks the batcher's effective `max_wait_us` so the observed tail tracks
the target instead:

  - P99 above `target * (1 + tol)`  -> shrink the window
    (multiplicative, floor 0: stop lingering, dispatch immediately);
  - P99 below `target * (1 - tol)`  -> grow the window (multiplicative
    with a minimum step so growth escapes 0, capped) — latency budget
    is being left on the table that coalescing can spend;
  - inside the deadband                -> no change (the hysteresis
    that keeps the knob from chattering on a noisy box).

Bounded: the window never exceeds `max(static knob, 75% of the SLO)` —
the operator's explicit knob stays reachable as the ceiling, and a
tiny knob may still grow to 75% of the SLO for useful batching (note:
with a knob set ABOVE the SLO, a quiet period can regrow the window
past the target; the next busy window overshoots once before the law
re-shrinks) — and never goes below 0. Every adjustment increments
`slo.adjustments_total`, updates the `slo.wait_us` / `slo.p99_ms`
gauges, and lands in a bounded adjustment log (the bench artifact's
`wait_us_adjustments`). With `--sys.serve.slo_ms` unset (the default)
no controller exists and the static knob path is untouched.

The controller runs as a self-rescheduling delayed program on the
unified executor's `slo` stream (PR 6 discipline: timer work without a
sleeping thread); `close()` stops the reschedule and the executor's
shutdown cancels any queued tick.

Requires `--sys.metrics` (the controller is blind without the latency
histogram); `SystemOptions.validate_serve` rejects the combination
loudly.
"""
from __future__ import annotations

import collections
import time
from typing import Dict, List, Optional, Tuple

from .metrics import hist_percentile

# growth needs a minimum absolute step so the window can escape 0
_MIN_GROW_US = 50


class SLOController:
    """One per ServePlane when `--sys.serve.slo_ms > 0`; owned and
    closed by the plane."""

    def __init__(self, server, batcher, target_ms: float,
                 class_targets: Optional[Dict[int, float]] = None,
                 interval_s: float = 0.1, tol: float = 0.25,
                 step: float = 1.5, min_samples: int = 4,
                 quantile: float = 0.99):
        assert target_ms > 0, "SLO target must be positive"
        self.server = server
        self.batcher = batcher
        self.target_s = float(target_ms) * 1e-3
        self.interval_s = float(interval_s)
        self.tol = float(tol)
        self.step = float(step)
        self.min_samples = int(min_samples)
        self.quantile = float(quantile)
        self.lo_us = 0
        # ceiling: the operator's explicit knob stays reachable, and a
        # knob far below the SLO may still grow to 75% of the target
        # for useful batching. An oversized knob (> SLO) remains the
        # cap, so quiet periods can regrow past the target — one
        # overshoot window before the law re-shrinks, by design.
        self.hi_us = max(int(batcher.max_wait_us),
                         int(self.target_s * 1e6 * 0.75))
        self._h = batcher.h_latency     # serve.latency_s (real Histogram;
        # validate_serve guarantees metrics are on when slo_ms is set)
        self._prev_snap: Optional[Dict] = None
        self._closed = False
        # bounded adjustment log:
        # (wall_time, mono_time, old_us, new_us, p99_ms)
        self.adjustments: "collections.deque" = collections.deque(
            maxlen=256)
        # the very first move, kept past the deque bound: the
        # convergence guard checks ITS direction (the oldest of the
        # last-8 window is not the first once the law has oscillated)
        self.first_adjustment: Optional[Tuple] = None
        reg = server.obs
        self.c_adjust = reg.counter("slo.adjustments_total", shared=True)
        self.c_ticks = reg.counter("slo.ticks_total", shared=True)
        self.g_wait = reg.gauge("slo.wait_us", shared=True)
        self.g_p99 = reg.gauge("slo.p99_ms", shared=True)
        self.g_target = reg.gauge("slo.target_ms", shared=True)
        self.g_target.set(float(target_ms))
        self.g_wait.set(float(batcher.max_wait_us))
        # per-priority-class targets (ISSUE 20 satellite;
        # `--sys.serve.slo_ms 20,1=5,2=50`): each overridden class gets
        # its OWN effective lane window, walked by the same law against
        # that class's windowed quantile. Batches are priority-pure
        # (admission.take pins the class after the first claim), so a
        # class's window is well-defined per batch; the base window
        # still serves classes without an override. Empty (the default)
        # touches nothing — the batcher's class hooks stay None and the
        # take() path is byte-identical.
        self.class_targets_s: Dict[int, float] = {
            int(p): float(ms) * 1e-3
            for p, ms in (class_targets or {}).items()}
        self.class_adjustments: "collections.deque" = \
            collections.deque(maxlen=256)
        self._class_prev_cut: Optional[float] = None
        self.class_hi_us: Dict[int, int] = {}
        if self.class_targets_s:
            base = int(batcher.max_wait_us)
            batcher.class_wait_us = {p: base
                                     for p in self.class_targets_s}
            batcher._class_samples = collections.deque(maxlen=4096)
            self.class_hi_us = {
                p: max(base, int(ts * 1e6 * 0.75))
                for p, ts in self.class_targets_s.items()}

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._resubmit()

    def close(self) -> None:
        """Stop rescheduling. Idempotent; a tick already queued on the
        `slo` stream sees `_closed` and exits without resubmitting (and
        executor close cancels it outright)."""
        self._closed = True

    def _resubmit(self) -> None:
        if self._closed:
            return
        # coalesce per controller INSTANCE: a plane rebuilt within one
        # tick interval must not have its first tick absorbed into the
        # closed predecessor's still-queued tick (which early-returns
        # without rescheduling — the new controller would never run)
        self.server.exec.submit("slo", self._tick, label="slo.tick",
                                coalesce_key=f"slo.tick.{id(self)}",
                                delay=self.interval_s)

    def _tick(self) -> None:
        if self._closed or self.server.exec.closed:
            return
        try:
            self._control()
            if self.class_targets_s:
                self._control_classes()
        finally:
            self._resubmit()

    # -- control law ---------------------------------------------------------

    def _window_p99(self) -> Optional[float]:
        """Quantile of the observations since the LAST tick (cumulative
        histogram diffed against the previous snapshot); None when the
        window holds too few samples to act on."""
        snap = self._h.snap()
        prev = self._prev_snap
        self._prev_snap = snap
        if prev is None:
            return None
        count = snap["count"] - prev["count"]
        if count < self.min_samples:
            return None
        buckets = [a - b for a, b in zip(snap["buckets"],
                                         prev["buckets"])]
        return hist_percentile({"count": count, "bounds": snap["bounds"],
                                "buckets": buckets}, self.quantile)

    def _control(self) -> None:
        self.c_ticks.inc()
        p99 = self._window_p99()
        if p99 is None:
            return
        self.g_p99.set(p99 * 1e3)
        cur = int(self.batcher.max_wait_us)
        if p99 > self.target_s * (1.0 + self.tol):
            if cur <= self.lo_us:
                return  # already dispatching immediately; the tail is
                # now dominated by dispatch/device time, not the window
            new = max(self.lo_us, min(cur - 1, int(cur / self.step)))
        elif p99 < self.target_s * (1.0 - self.tol):
            if cur >= self.hi_us:
                return
            new = min(self.hi_us, max(cur + _MIN_GROW_US,
                                      int(cur * self.step)))
        else:
            return  # deadband: hysteresis against knob chatter
        if new == cur:
            return
        pol = getattr(self.server, "policy", None)
        if pol is not None and pol.active("serve"):
            # ISSUE 18 learned serve law: the heuristic still PROPOSES
            # every move (bounded by [lo_us, hi_us] above); a
            # predicted made-the-tail-worse verdict holds the window
            # at its current value instead of applying the move. The
            # batch window only changes WHEN requests dispatch, never
            # the rows a lookup returns, so no further
            # value-preservation guard is needed. Features are
            # rounded exactly as record_serve captures them — the
            # train/serve contract (policy/features.py).
            if pol.consult("serve",
                           {"old_us": cur, "new_us": new,
                            "p99_ms": round(p99 * 1e3, 3),
                            "target_ms": round(self.target_s * 1e3,
                                               3)}, 1):
                pol.applied("serve")
                return
        self.batcher.max_wait_us = new
        self.c_adjust.inc()
        self.g_wait.set(float(new))
        # BOTH clock domains (ISSUE 15 satellite): the serve latency
        # slices this log is read against are monotonic — a wall-only
        # stamp skews the merged timeline across NTP steps
        move = (time.time(), time.monotonic(), cur, new, p99 * 1e3)
        if self.first_adjustment is None:
            self.first_adjustment = move
        self.adjustments.append(move)
        dc = self.server.decisions
        if dc is not None:
            # ISSUE 17: the autopilot move with its window/target
            # features; the outcome probe re-reads the windowed P99
            # gauge to judge whether the move helped the tail
            dc.record_serve(cur, new, p99 * 1e3, self.target_s * 1e3,
                            lambda: float(self.g_p99.value))

    def _control_classes(self) -> None:
        """Walk each overridden class's lane window against its own
        windowed quantile (the batcher's bounded (t, latency, prio)
        sample ring — per-class percentiles without per-class registry
        names). Same law, same deadband, same bounds discipline as the
        base window; moves land in `class_adjustments` and count into
        `slo.adjustments_total`."""
        samples = self.batcher._class_samples
        cw = self.batcher.class_wait_us
        if samples is None or cw is None:
            return
        now = time.perf_counter()  # the sample stamps' clock
        cut = self._class_prev_cut
        self._class_prev_cut = now
        if cut is None:
            return
        by_prio: Dict[int, List[float]] = {}
        for (t, lat, prio) in list(samples):
            if t > cut and prio in self.class_targets_s:
                by_prio.setdefault(prio, []).append(lat)
        for prio in sorted(self.class_targets_s):
            target_s = self.class_targets_s[prio]
            lats = by_prio.get(prio)
            if lats is None or len(lats) < self.min_samples:
                continue
            lats.sort()
            p99 = lats[min(len(lats) - 1,
                           int(self.quantile * len(lats)))]
            cur = int(cw.get(prio, self.batcher.max_wait_us))
            hi = self.class_hi_us[prio]
            if p99 > target_s * (1.0 + self.tol):
                if cur <= self.lo_us:
                    continue
                new = max(self.lo_us, min(cur - 1, int(cur / self.step)))
            elif p99 < target_s * (1.0 - self.tol):
                if cur >= hi:
                    continue
                new = min(hi, max(cur + _MIN_GROW_US,
                                  int(cur * self.step)))
            else:
                continue  # deadband
            if new == cur:
                continue
            cw[prio] = new
            self.c_adjust.inc()
            self.class_adjustments.append(
                (time.time(), time.monotonic(), prio, cur, new,
                 p99 * 1e3))

    # -- reporting -----------------------------------------------------------

    def report(self) -> Dict:
        """JSON-safe summary for `metrics_snapshot()["slo"]` and the
        bench artifact."""
        last: List = [
            {"t": round(t, 3), "t_mono": round(tm, 6), "old_us": o,
             "new_us": n, "p99_ms": round(p, 3)}
            for (t, tm, o, n, p) in list(self.adjustments)[-8:]]
        first = None
        if self.first_adjustment is not None:
            t, tm, o, n, p = self.first_adjustment
            first = {"t": round(t, 3), "t_mono": round(tm, 6),
                     "old_us": o, "new_us": n, "p99_ms": round(p, 3)}
        out = {"active": True,
               "target_ms": round(self.target_s * 1e3, 3),
               "wait_us": int(self.batcher.max_wait_us),
               "bounds_us": [self.lo_us, self.hi_us],
               "adjustments": int(self.c_adjust.value),
               "first_adjustment": first,
               "recent_adjustments": last}
        if self.class_targets_s:
            # per-class keys present ONLY with class overrides — the
            # no-override report (and every pre-existing consumer of
            # it) is byte-identical
            cw = self.batcher.class_wait_us or {}
            out["class_targets_ms"] = {
                str(p): round(ts * 1e3, 3)
                for p, ts in sorted(self.class_targets_s.items())}
            out["class_wait_us"] = {str(p): int(w)
                                    for p, w in sorted(cw.items())}
            out["class_adjustments"] = [
                {"t": round(t, 3), "t_mono": round(tm, 6),
                 "priority": pr, "old_us": o, "new_us": n,
                 "p99_ms": round(p, 3)}
                for (t, tm, pr, o, n, p)
                in list(self.class_adjustments)[-8:]]
        return out
