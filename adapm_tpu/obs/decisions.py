"""Decision telemetry capture: every adaptive choice, with the features
it saw and the outcome it bought (ISSUE 17 tentpole; ROADMAP item 3).

AdaPM's core claim is *autonomous* per-key management — the system
decides, per key and per point in time, whether to relocate or
replicate (PAPER.md) — yet until this plane the stack recorded *what*
it decided (wtrace `reloc`/`promote` events) but never *why* or
*whether it paid off*. The `DecisionRecorder`
(`--sys.trace.decisions PATH`, default **off**) captures every adaptive
decision as a structured event:

  `reloc`     relocate-vs-replicate classification (core/sync.py
              `_decide_batch` via `_register`) and the landed ownership
              move (core/kv.py `_relocate_to`, incl. pool-full
              demotions to replication)
  `tier`      hot-pool promotion with the anti-thrash verdict
              (tier/promote.py `ensure_hot_rows`: pinned/unpinned
              split, victims scanned, victims strictly beaten) and
              pressure demotion (`PromotionEngine.run_once`)
  `sync`      dirty-sync ship/hold per replica batch (core/sync.py
              `sync_channel`: considered/dirty/ridealong/held)
  `serve`     SLO autopilot batch-window moves (obs/slo.py `_control`)
  `prefetch`  stage vs pool-full skip (core/intent.py)
  `costs`     measured-cost fused-vs-hostpool overrides (ops/costs.py
              consulted by serve/batcher.py)

Disciplines (all inherited from earlier planes):

  - **Default off at the r7 skip-wrapper cost.** With no
    `--sys.trace.decisions`, `Server.decisions is None`, every
    instrumented site pays one `is None` check, and the registry holds
    zero `decision.*` names (pinned by
    `scripts/metrics_overhead_check.py` and adapm-lint APM003 —
    `decisions` is an OPTIONAL_HANDLE).
  - **Both clock domains, always** (the ISSUE 15/18 rule): every event
    carries the logical clock, `wall` (`time.time()`) AND `mono`
    (`time.monotonic()`).
  - **A complete feature vector on every decision.** Each event's
    `features` dict carries at least `CORE_FEATURES` — the logical
    clock, live replica count, dirty fraction, hot-pool free/total
    rows, and the batch size — plus plane-specific fields (pin split,
    victim scores beaten, window sizes). All reads are lock-free host
    reads; capture never takes the server lock and never waits on the
    device.
  - **Atomic, versioned, checksummed file.** `flush()` writes the
    `.dtrace` through the exact wtrace header/write_atomic machinery
    (`obs/wtrace.py write_trace_file`); `load_dtrace` verifies format,
    version, length, and digest BEFORE returning anything — a
    truncated or flipped file raises the named `DecisionTraceError`,
    never a half-parsed trace.

Outcome attribution: each decision may open a bounded follow-up window
(`follow_events` same-plane events, `8 x follow_events` any-plane
events, or `follow_s` seconds — whichever comes first; close() resolves
stragglers with `truncated: true`). Resolution appends an `outcome`
event referencing the decision's `seq` and folds per-plane regret:

  `decision.promoted_never_hit`     promoted rows never re-touched
                                    while hot inside the window
  `decision.replicated_never_read`  replicas dead with no renewed
                                    intent by window close (sampled)
  `decision.shipped_clean`          clean replicas shipped in a sync
                                    batch (sibling ride-alongs, or a
                                    fully-clean ship with the dirty
                                    filter off)
  `decision.regret_rate.<plane>`    regretted / resolved windows,
                                    cumulative per plane

The labeled (features, decision, outcome) join lives in
`adapm_tpu/replay/dataset.py` (docs/REPLAY.md "Policy scoring");
docs/OBSERVABILITY.md has the catalog rows and the "Explain a
decision" recipe.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

# the shared feature-extraction contract (ISSUE 18 tentpole a): the
# SAME module the runtime PolicyPlane vectorizes through, so a feature
# the capture records is by construction a feature inference computes
# identically. CORE_FEATURES is re-exported here for existing
# consumers (scripts/decision_quality_check.py).
from ..policy.features import CORE_FEATURES, core_features  # noqa: F401

DTRACE_FORMAT = "adapm-dtrace"
DTRACE_VERSION = 1

# hard bounds on the buffered stream (loud drop counter beyond either),
# mirroring wtrace: decisions are management-plane events, far sparser
# than the op stream, so the defaults are generous
DEFAULT_MAX_EVENTS = 1_000_000
DEFAULT_MAX_BYTES = 256 * 1024 * 1024

# planes that open follow-up windows and fold a regret rate
_REGRET_PLANES = ("reloc", "tier", "sync", "serve", "prefetch")
_PLANES = _REGRET_PLANES + ("costs",)

# per-decision key/slot sample bound for outcome probes: windows
# re-read addressbook/residency state for at most this many entries
# (outcome fields are therefore sample-based for larger batches — the
# event says so via "sampled": true)
_PROBE_CAP = 64


class DecisionTraceError(RuntimeError):
    """The `.dtrace` file is unreadable: wrong format/version, truncated
    body, checksum mismatch, or malformed JSON. Raised by `load_dtrace`
    during verification, BEFORE anything consumes the trace (the
    wtrace/ckpt verify-before-use discipline)."""


class _Window:
    """One open follow-up window: resolves into an `outcome` event via
    `resolve(truncated)` -> (fields, regret-or-None)."""

    __slots__ = ("seq", "plane", "deadline_mono", "plane_due",
                 "total_due", "resolve")

    def __init__(self, seq: int, plane: str, deadline_mono: float,
                 plane_due: int, total_due: int,
                 resolve: Callable[[bool], Tuple[Dict, Optional[bool]]]):
        self.seq = seq
        self.plane = plane
        self.deadline_mono = deadline_mono
        self.plane_due = plane_due
        self.total_due = total_due
        self.resolve = resolve


def _sample(arr: np.ndarray, cap: int = _PROBE_CAP) -> np.ndarray:
    """Evenly-strided sample of at most `cap` entries (the wtrace
    sampled-with-counts discipline, applied to outcome probes)."""
    a = np.ascontiguousarray(arr, dtype=np.int64)
    if len(a) <= cap:
        return a
    stride = -(-len(a) // cap)  # ceil: <= cap samples
    return a[::stride]


class DecisionRecorder:
    """One per Server when `--sys.trace.decisions` names a path; owned
    and closed by the server (shutdown, after every producer is
    stopped, alongside the wtrace recorder). Thread-safe: decision
    sites record concurrently under one small lock (append + counter
    bumps only — never a device wait, never the server lock); window
    resolution runs outside it on pure host reads."""

    def __init__(self, server, path: Optional[str],
                 follow_events: int = 8, follow_s: float = 2.0,
                 max_events: int = DEFAULT_MAX_EVENTS,
                 max_bytes: int = DEFAULT_MAX_BYTES):
        from .metrics import Counter, Gauge
        if path is not None and not path:
            raise ValueError("decision trace capture needs a path "
                             "(--sys.trace.decisions)")
        # path=None is the METRICS-ONLY mode (internal; the CLI knob
        # always names a file): windows open, outcomes resolve, and
        # the regret gauges fold exactly as in capture mode, but
        # flush() writes nothing. The replay engine uses this to score
        # a candidate's decision quality (`score_decisions=True`)
        # while still PINNING `trace_decisions` off — the simulator
        # scores itself through the registry, it never emits a trace
        # of itself (replay/engine.py).
        self._server = server
        self.path = path
        self.follow_events = max(1, int(follow_events))
        self.follow_s = float(follow_s)
        self.max_events = int(max_events)
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._flush_lock = threading.Lock()  # wtrace ordering discipline
        self._wlock = threading.Lock()
        self._events: List[Dict] = []
        self._windows: List[_Window] = []
        self._sweeping = False
        self._approx_bytes = 0
        self._seq = 0
        self._closed = False
        self._flushes = 0
        self._warned_drop = False
        self.wall_t0 = time.time()
        self.mono_t0 = time.monotonic()
        # per-plane tallies (plain ints; the regret gauges are the
        # registry-visible ratio view over these)
        self._decided = {p: 0 for p in _PLANES}
        self._resolved = {p: 0 for p in _PLANES}
        self._regrets = {p: 0 for p in _PLANES}
        self._plane_seen = {p: 0 for p in _PLANES}
        self._total_seen = 0
        self._opened = 0
        self._forced = 0
        reg = server.obs
        use_reg = reg is not None and reg.enabled
        if use_reg:
            self.c_events = reg.counter("decision.events_total")
            self.c_dropped = reg.counter("decision.dropped_total")
            self.g_bytes = reg.gauge("decision.bytes_written")
            self.c_promoted_never_hit = \
                reg.counter("decision.promoted_never_hit")
            self.c_replicated_never_read = \
                reg.counter("decision.replicated_never_read")
            self.c_shipped_clean = reg.counter("decision.shipped_clean")
            self.g_regret = {p: reg.gauge(f"decision.regret_rate.{p}")
                             for p in _REGRET_PLANES}
        else:  # capture works with --sys.metrics 0 (standalone tallies)
            self.c_events = Counter("decision.events_total")
            self.c_dropped = Counter("decision.dropped_total")
            self.g_bytes = Gauge("decision.bytes_written")
            self.c_promoted_never_hit = \
                Counter("decision.promoted_never_hit")
            self.c_replicated_never_read = \
                Counter("decision.replicated_never_read")
            self.c_shipped_clean = Counter("decision.shipped_clean")
            self.g_regret = {p: Gauge(f"decision.regret_rate.{p}")
                             for p in _REGRET_PLANES}

    # -- event plumbing ------------------------------------------------------

    def _server_clock(self) -> int:
        c = self._server._clocks
        return int(c.max()) if len(c) else 0

    def _base(self, kind: str, plane: str) -> Dict:
        return {"kind": kind, "plane": plane,
                "clock": self._server_clock(),
                "wall": time.time(), "mono": time.monotonic()}

    def _append(self, ev: Dict) -> Optional[int]:
        """Buffer one event; returns its seq (None when dropped)."""
        cost = 96 + 8 * (len(ev.get("features", ())) +
                         len(ev.get("sample", ())))
        with self._lock:
            if self._closed:
                return None
            if len(self._events) >= self.max_events or \
                    self._approx_bytes + cost > self.max_bytes:
                self.c_dropped.inc()
                if not self._warned_drop:
                    self._warned_drop = True
                    from ..utils import alog
                    alog(f"[decisions] event buffer full "
                         f"({len(self._events)} events, "
                         f"~{self._approx_bytes >> 20} MiB); further "
                         f"decision/outcome events are DROPPED (counted "
                         f"in decision.dropped_total) — the captured "
                         f"trace is a loud prefix, not a silent lie")
                return None
            seq = self._seq
            ev["seq"] = seq
            self._seq += 1
            self._events.append(ev)
            self._approx_bytes += cost
        self.c_events.inc()
        return seq

    def _features(self, batch_n: int) -> Dict:
        """The CORE_FEATURES context visible at decision time, through
        the SHARED extractor (policy/features.py) — the same code path
        runtime inference reads, so a trained model's inputs mean
        exactly what the captured rows meant."""
        return core_features(self._server, batch_n)

    def _record(self, plane: str, action: str, features: Dict,
                **fields) -> Optional[int]:
        ev = self._base("decision", plane)
        ev["action"] = action
        ev["features"] = features
        for k, v in fields.items():
            ev[k] = v
        seq = self._append(ev)
        if seq is not None:
            self._decided[plane] += 1
        self._tick(plane)
        return seq

    # -- follow-up windows ---------------------------------------------------

    def _open_window(self, seq: Optional[int], plane: str,
                     resolve: Callable) -> None:
        if seq is None:
            return  # the decision itself was dropped: nothing to tie to
        w = _Window(seq, plane,
                    time.monotonic() + self.follow_s,
                    self._plane_seen[plane] + self.follow_events,
                    self._total_seen + 8 * self.follow_events,
                    resolve)
        with self._wlock:
            self._windows.append(w)
            self._opened += 1

    def _tick(self, plane: str) -> None:
        """Advance the window clocks and resolve due windows. Reentrancy
        guard: outcome appends inside a sweep never re-sweep."""
        with self._wlock:
            self._plane_seen[plane] += 1
            self._total_seen += 1
            if self._sweeping or not self._windows:
                return
            self._sweeping = True
        try:
            self._sweep(forced=False)
        finally:
            with self._wlock:
                self._sweeping = False

    def _sweep(self, forced: bool) -> None:
        now = time.monotonic()
        with self._wlock:
            due, rest = [], []
            for w in self._windows:
                if forced or now >= w.deadline_mono or \
                        self._plane_seen[w.plane] >= w.plane_due or \
                        self._total_seen >= w.total_due:
                    due.append(w)
                else:
                    rest.append(w)
            self._windows = rest
            if forced:
                self._forced += len(due)
        for w in due:
            try:
                fields, regret = w.resolve(forced)
            except Exception as e:  # a probe racing teardown resolves
                fields, regret = {"error": type(e).__name__}, None
            ev = self._base("outcome", w.plane)
            ev["ref"] = w.seq
            ev["truncated"] = bool(forced)
            ev.update(fields)
            if regret is not None:
                ev["regret"] = bool(regret)
            self._append(ev)
            self._fold(w.plane, regret)

    def _fold(self, plane: str, regret: Optional[bool]) -> None:
        self._resolved[plane] += 1
        if regret:
            self._regrets[plane] += 1
        g = self.g_regret.get(plane)
        if g is not None and self._resolved[plane]:
            g.set(self._regrets[plane] / self._resolved[plane])

    def _immediate(self, plane: str, seq: Optional[int], fields: Dict,
                   regret: Optional[bool]) -> None:
        """A decision whose outcome is known at decision time: append
        the outcome event directly (the dataset join is uniform — every
        decision has an outcome ref) and fold the tallies."""
        if seq is None:
            return
        self._opened += 1
        ev = self._base("outcome", plane)
        ev["ref"] = seq
        ev["truncated"] = False
        ev.update(fields)
        if regret is not None:
            ev["regret"] = bool(regret)
        self._append(ev)
        self._fold(plane, regret)

    # -- decision sites ------------------------------------------------------

    def record_classify(self, shard: int, n_relocate: int,
                        n_replicate: int, n_remote: int,
                        replicate_keys: np.ndarray) -> None:
        """sync._register: the relocate-vs-replicate split for one
        intent batch. Replications open a window probing whether the
        replicas were ever worth it (still live, or intent renewed, by
        window close — sampled at `_PROBE_CAP`)."""
        f = self._features(n_relocate + n_replicate + n_remote)
        f["n_relocate"] = int(n_relocate)
        f["n_replicate"] = int(n_replicate)
        f["n_remote"] = int(n_remote)
        seq = self._record("reloc", "classify", f, shard=int(shard),
                           sampled=len(replicate_keys) > _PROBE_CAP)
        if n_replicate == 0:
            self._immediate("reloc", seq, {"replicated": 0}, False)
            return
        srv = self._server
        sample = _sample(replicate_keys)

        def resolve(truncated: bool):
            from ..base import NO_SLOT
            ab = srv.ab
            live = ab.cache_slot[shard, sample] != NO_SLOT
            mc = srv.shard_min_clocks()[int(shard)]
            active = srv.sync.intent_end[shard, sample] >= mc
            never = int((~live & ~active).sum())
            if never:
                self.c_replicated_never_read.inc(never)
            return ({"replicated": int(n_replicate),
                     "probed": int(len(sample)),
                     "replicas_live": int(live.sum()),
                     "intent_active": int(active.sum()),
                     "never_read": never},
                    never == len(sample) and len(sample) > 0)

        self._open_window(seq, "reloc", resolve)

    def record_move(self, dest: int, n_moved: int, n_demoted: int,
                    moved_keys: np.ndarray) -> None:
        """kv._relocate_to: the landed ownership move (plus pool-full
        demotions to replication). The window probes post-move
        locality: the fraction of moved keys still owned by `dest` at
        close — a move immediately undone is a regretted thrash."""
        f = self._features(n_moved + n_demoted)
        f["n_moved"] = int(n_moved)
        f["n_demoted"] = int(n_demoted)
        seq = self._record("reloc", "move", f, dest=int(dest),
                           sampled=len(moved_keys) > _PROBE_CAP)
        if n_moved == 0:
            self._immediate("reloc", seq, {"locality": 0.0}, None)
            return
        srv = self._server
        sample = _sample(moved_keys)

        def resolve(truncated: bool):
            still = int((srv.ab.owner[sample] == dest).sum())
            loc = still / len(sample) if len(sample) else 0.0
            return ({"probed": int(len(sample)),
                     "still_owned": still,
                     "locality": round(loc, 4)},
                    len(sample) > 0 and still == 0)

        self._open_window(seq, "reloc", resolve)

    def record_tier(self, store, shard: int, promoted: np.ndarray,
                    n_pinned: int, n_unpinned: int, n_victims: int,
                    n_beat: int, min_clock: int) -> None:
        """tier ensure_hot_rows (background path): one shard's
        promotion batch with the anti-thrash verdict — the pinned/
        unpinned candidate split, victims scanned, and victims whose
        scores were STRICTLY beaten. The window probes whether the
        promoted rows were re-touched while still hot; a batch with
        zero such hits is a regretted promotion
        (decision.promoted_never_hit counts the rows)."""
        f = self._features(n_pinned + n_unpinned)
        f["n_pinned"] = int(n_pinned)
        f["n_unpinned"] = int(n_unpinned)
        f["n_victims"] = int(n_victims)
        f["n_beat"] = int(n_beat)
        seq = self._record("tier", "promote", f, shard=int(shard),
                           promoted=int(len(promoted)),
                           min_clock=int(min_clock),
                           sampled=len(promoted) > _PROBE_CAP)
        if len(promoted) == 0:
            self._immediate("tier", seq, {"hit_rows": 0}, None)
            return
        res = store.res
        slots = _sample(promoted)
        score_then = np.array(res.score[shard, slots], copy=True)

        def resolve(truncated: bool):
            now = res.score[shard, slots]
            hot = res.dev_row[shard, slots] >= 0
            hit = (now > score_then) & hot
            hits, never = int(hit.sum()), int((~hit).sum())
            if never:
                self.c_promoted_never_hit.inc(never)
            return ({"probed": int(len(slots)), "hit_rows": hits,
                     "never_hit_rows": never,
                     "still_hot_rows": int(hot.sum())},
                    hits == 0)

        self._open_window(seq, "tier", resolve)

    def record_tier_demote(self, shard: int, n: int, free: int,
                           target: int) -> None:
        """tier run_once pressure demotion: headroom reclaim. Outcome is
        immediate — the demotion's cost shows up as later promotions'
        regret, not its own."""
        f = self._features(n)
        f["free_before"] = int(free)
        f["target_free"] = int(target)
        seq = self._record("tier", "demote", f, shard=int(shard),
                           demoted=int(n))
        self._immediate("tier", seq, {"demoted": int(n)}, None)

    def record_sync(self, channel: int, considered: int, dirty: int,
                    shipped: int) -> None:
        """sync_channel ship/hold for one channel round: `considered`
        live local replicas, `dirty` with unshipped writes (-1 = dirty
        filter off), `shipped` after sibling propagation. Outcome is
        immediate: clean ride-alongs count in decision.shipped_clean; a
        ship with ZERO dirty rows (filter off) is regretted wire."""
        f = self._features(considered)
        f["n_dirty"] = int(dirty)
        f["n_shipped"] = int(shipped)
        f["n_held"] = int(considered - shipped)
        action = "ship" if shipped else "hold"
        seq = self._record("sync", action, f, channel=int(channel))
        clean = (shipped - dirty) if dirty >= 0 else shipped
        clean = max(0, int(clean)) if shipped else 0
        if clean:
            self.c_shipped_clean.inc(clean)
        regret = bool(shipped) and dirty == 0
        self._immediate("sync", seq, {"shipped": int(shipped),
                                      "shipped_clean": clean}, regret)

    def record_serve(self, old_us: int, new_us: int, p99_ms: float,
                     target_ms: float,
                     p99_fn: Callable[[], float]) -> None:
        """obs/slo.py _control: one autopilot batch-window move. The
        window re-reads the controller's windowed P99 at close: a move
        that left the tail FARTHER from target than it found it is
        regretted."""
        f = self._features(1)
        f["old_us"] = int(old_us)
        f["new_us"] = int(new_us)
        f["p99_ms"] = round(float(p99_ms), 3)
        f["target_ms"] = round(float(target_ms), 3)
        action = "shrink" if new_us < old_us else "grow"
        seq = self._record("serve", action, f)
        then_err = abs(float(p99_ms) - float(target_ms))

        def resolve(truncated: bool):
            now = float(p99_fn())
            now_err = abs(now - float(target_ms))
            return ({"p99_after_ms": round(now, 3),
                     "err_before_ms": round(then_err, 3),
                     "err_after_ms": round(now_err, 3)},
                    now > 0 and now_err > then_err + 1e-9)

        self._open_window(seq, "serve", resolve)

    def record_prefetch(self, action: str, n_keys: int, stats) -> None:
        """core/intent.py staging: `stage` (batch staged) or `skip`
        (pool budget exhausted). The stage window reads the prefetch
        hit/expired counter deltas at close: staged work that only ever
        expired is regretted staging."""
        f = self._features(n_keys)
        f["pool_full"] = int(action == "skip")
        seq = self._record("prefetch", action, f)
        if action != "stage":
            self._immediate("prefetch", seq, {"hits_delta": 0}, None)
            return
        h0, e0 = int(stats["hits"]), int(stats["expired"])

        def resolve(truncated: bool):
            dh = int(stats["hits"]) - h0
            de = int(stats["expired"]) - e0
            return ({"hits_delta": dh, "expired_delta": de},
                    de > 0 and dh == 0)

        self._open_window(seq, "prefetch", resolve)

    def record_costs(self, fused: bool, n_groups: int, n_keys: int,
                     n_false: int, n_none: int) -> None:
        """serve/batcher.py bag dispatch: the measured-cost verdict —
        fused gather_pool kept, or overridden to flat-gather+host-pool.
        Purely observational (the table is already measured); outcome is
        immediate and never regretted here."""
        f = self._features(n_keys)
        f["n_groups"] = int(n_groups)
        f["verdicts_false"] = int(n_false)
        f["verdicts_none"] = int(n_none)
        seq = self._record("costs", "fused" if fused else "hostpool", f)
        self._immediate("costs", seq, {"overridden": not fused}, None)

    # -- meta / stats --------------------------------------------------------

    def _meta(self) -> Dict:
        import dataclasses
        import enum
        srv = self._server
        knobs = {}
        for k, v in dataclasses.asdict(srv.opts).items():
            knobs[k] = v.value if isinstance(v, enum.Enum) else v
        return {"num_keys": int(srv.num_keys),
                "num_shards": int(srv.ctx.num_shards),
                "rank": int(srv.pid),
                "follow_events": self.follow_events,
                "follow_s": self.follow_s,
                "probe_cap": _PROBE_CAP,
                "wall_t0": self.wall_t0,
                "mono_t0": self.mono_t0,
                "knobs": knobs}

    def stats(self) -> Dict:
        """Plain-value summary for `metrics_snapshot()["decision"]` (the
        registry-backed decision.* counters land in the same section)."""
        with self._lock:
            n = len(self._events)
        with self._wlock:
            open_w = len(self._windows)
        out: Dict = {"path": self.path, "events_buffered": n,
                     "flushes": self._flushes, "closed": self._closed,
                     "windows_opened": self._opened,
                     "windows_resolved": sum(self._resolved.values()),
                     "windows_forced": self._forced,
                     "windows_open": open_w}
        for p in _PLANES:
            out[f"decided.{p}"] = self._decided[p]
            out[f"resolved.{p}"] = self._resolved[p]
            out[f"regretted.{p}"] = self._regrets[p]
        return out

    # -- flush / close -------------------------------------------------------

    def flush(self) -> str:
        """Write the full trace atomically (wtrace header discipline);
        returns the path (empty string in metrics-only mode — there is
        no file to write). Safe to call mid-run for a point-in-time
        trace; close() performs the final flush."""
        if self.path is None:
            return ""
        from .wtrace import write_trace_file
        with self._flush_lock:
            with self._lock:
                doc = {"meta": self._meta(),
                       "events": list(self._events),
                       "dropped": int(self.c_dropped.value)}
            nbytes = write_trace_file(self.path, doc, DTRACE_FORMAT,
                                      DTRACE_VERSION)
            with self._lock:
                self._flushes += 1
            self.g_bytes.set(float(nbytes))
        return self.path

    def close(self) -> None:
        """Resolve every still-open window (truncated — the follow-up
        horizon is the run's end), then final flush + seal (idempotent).
        Called by Server.shutdown AFTER every producer is stopped, so
        the probes read settled state."""
        with self._lock:
            if self._closed:
                return
        self._sweep(forced=True)
        self.flush()
        with self._lock:
            self._closed = True


# ---------------------------------------------------------------------------
# loading (shared by replay/dataset.py and tooling)
# ---------------------------------------------------------------------------


class DecisionTrace:
    """A verified, parsed `.dtrace`: `meta` dict + `events` list (seq
    order). Construction implies the checksum passed."""

    __slots__ = ("path", "meta", "events", "dropped")

    def __init__(self, path: str, meta: Dict, events: List[Dict],
                 dropped: int):
        self.path = path
        self.meta = meta
        self.events = events
        self.dropped = dropped

    def decisions(self) -> List[Dict]:
        return [e for e in self.events if e["kind"] == "decision"]

    def outcomes(self) -> Dict[int, Dict]:
        """outcome events keyed by the decision seq they reference."""
        return {int(e["ref"]): e for e in self.events
                if e["kind"] == "outcome"}

    def planes(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for e in self.decisions():
            out[e["plane"]] = out.get(e["plane"], 0) + 1
        return out


def load_dtrace(path: str) -> DecisionTrace:
    """Read + verify a `.dtrace` file. Raises `DecisionTraceError` on a
    missing/truncated/corrupt/incompatible file — named, and BEFORE
    anything consumes the trace."""
    from .wtrace import load_trace_doc
    doc = load_trace_doc(path, DTRACE_FORMAT, DTRACE_VERSION,
                         DecisionTraceError, "decision trace")
    return DecisionTrace(path, doc["meta"], doc["events"],
                         int(doc.get("dropped", 0)))
