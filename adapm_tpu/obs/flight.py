"""Request-flight tracing: causal traces across admission -> batch ->
executor -> device, plus the freshness probe and the executor
flight-recorder ring (ISSUE 7 tentpole).

Three pieces, each independently cheap:

  - **FlightTracer** (`--sys.trace.flight`, default **off**): a
    per-request trace id minted at `ServeSession.lookup` (and at
    `Worker.pull|push`, which are single-segment flights), carried on
    the `AdmissionQueue` entry, recorded when the batcher coalesces
    requests into a fused gather, and stamped onto the dispatched
    program. Exported as Chrome trace-event JSON with Perfetto **flow
    events** (`ph: s/t/f`, bound by id), so ONE served lookup renders
    as a single connected chain: client wait -> queue -> batch window
    -> dispatch -> device gather -> reply. Per-request breakdown
    histograms (`flight.queue_s` / `batch_wait_s` / `dispatch_s` /
    `device_s`) quantify where each millisecond went — the
    "Dissecting Embedding Bag Performance" attribution, per request.
    Default-off discipline (same as r7 spans): when off the Server
    holds no tracer and every instrumented site pays one `is None`
    check; the registry holds zero `flight.*` metric names.

  - **FreshnessProbe** (rides the tracer): event-to-servable staleness
    — the wall time from a `Worker.push` of a key to the FIRST serve
    lookup that reads it, sampled (every Nth push records one key into
    a bounded probe table; the batcher checks the union key set
    against it). The ROADMAP-5 freshness gauge's pre-work:
    `flight.freshness_s` is the histogram a streaming-online-learning
    SLA will be measured by.

  - **FlightRecorder** (rides `--sys.crash_dumps`, default **on**): a
    bounded per-stream ring of the last K executor programs (stream,
    label, coalesce key, queue-wait and run times). Each record also
    overwrites one fixed-width slot of a ring FILE via `pwrite` (the
    crash-breadcrumb discipline, obs/spans.py), so after one of this
    image's known XLA-CPU hard aborts the file is a post-mortem of
    what was in flight. Not gated by `--sys.trace.flight`: it records
    per executor PROGRAM (drains, sync rounds, tier passes), never per
    Pull/Push op, so the hot path never sees it.
"""
from __future__ import annotations

import collections
import itertools
import json
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

# the causal phases of one served lookup, in flow order; the exporter
# emits one Perfetto flow chain (s -> t -> t -> t -> f) per trace id
# that completed all five
FLIGHT_PHASES = ("flight.lookup", "flight.queue", "flight.batch",
                 "flight.program", "flight.reply")
_PHASE_IDX = {n: i for i, n in enumerate(FLIGHT_PHASES)}

# virtual Perfetto tracks for phases that happen on no one thread
# (queue residence) or across threads (the coalescing window)
_VIRTUAL_TRACKS = ("serve.queue", "serve.batch-window")


class FlightTrace:
    """One request's causal context: the minted id plus the phase
    timestamps stamped along the way (perf_counter values; 0.0 = the
    phase never happened, e.g. a shed request has no claim)."""

    __slots__ = ("id", "t_mint", "t_claim", "t_dispatch", "t_enqueued",
                 "t_done", "t_deliver")

    def __init__(self, trace_id: int, t_mint: float):
        self.id = trace_id
        self.t_mint = t_mint
        self.t_claim = 0.0      # AdmissionQueue try_claim (dispatcher side)
        self.t_dispatch = 0.0   # batcher starts the coalesced lookup
        self.t_enqueued = 0.0   # device gather programs enqueued
        self.t_done = 0.0       # union values materialized on host
        self.t_deliver = 0.0    # result handed to the waiting client

    def breakdown_s(self) -> Dict[str, float]:
        """queue / batch_wait / dispatch / device split in seconds
        (only meaningful for a completed trace)."""
        return {"queue_s": max(0.0, self.t_claim - self.t_mint),
                "batch_wait_s": max(0.0, self.t_dispatch - self.t_claim),
                "dispatch_s": max(0.0, self.t_enqueued - self.t_dispatch),
                "device_s": max(0.0, self.t_done - self.t_enqueued)}


class FreshnessProbe:
    """Event-to-servable staleness, sampled (see module docstring).

    `note_push` is called per Worker.push ONLY when flight tracing is
    on (the caller holds the `server.flight is not None` gate); every
    `sample_every`-th push stamps its first key + the event clock into
    a bounded table and returns a token; the pusher calls
    `push_visible(token)` once the scatter is ENQUEUED (under the
    server lock — enqueue order is this codebase's read-visibility
    order). `note_read` (the serve batcher, per coalesced union,
    passing the gather's own under-lock enqueue stamp) resolves a
    probed key only when the gather was enqueued AFTER the push became
    visible — a batch already in flight when the push landed returns
    the OLD value and must not retire the probe — then observes
    read-materialize minus push-EVENT time and retires the entry:
    FIRST servable read, measured once per probe entry."""

    def __init__(self, registry=None, sample_every: int = 8,
                 bound: int = 256):
        from .metrics import Counter, Histogram
        self._sample = max(1, int(sample_every))
        self._bound = int(bound)
        self._lock = threading.Lock()
        # key -> [t_event, t_visible|None] (t_visible None until the
        # scatter is enqueued; unresolvable probes never observe)
        self._pending: Dict[int, List[Optional[float]]] = {}
        self._n_pushes = 0
        self.evicted = 0    # probes displaced by newer ones at bound
        use_reg = registry is not None and registry.enabled
        if use_reg:
            self.h_freshness = registry.histogram("flight.freshness_s")
            self.c_samples = registry.counter("flight.freshness_samples")
        else:  # flight tracing works with --sys.metrics 0 (standalone)
            self.h_freshness = Histogram("flight.freshness_s")
            self.c_samples = Counter("flight.freshness_samples")

    def note_push(self, keys) -> Optional[int]:
        with self._lock:
            self._n_pushes += 1
            if self._n_pushes % self._sample or len(keys) == 0:
                return None
            k = int(keys[0])
            if k in self._pending:
                return None
            if len(self._pending) >= self._bound:
                # evict the oldest unresolved probe (insertion order)
                # so never-served keys can't permanently silence the
                # gauge once they fill the table
                self._pending.pop(next(iter(self._pending)))
                self.evicted += 1
            self._pending[k] = [time.perf_counter(), None]
            return k

    def push_visible(self, token: Optional[int]) -> None:
        """Stamp the probed push as enqueued. Call with the server lock
        held, right after the scatter enqueue, so the stamp totally
        orders against gather enqueue stamps taken under the same
        lock."""
        if token is None:
            return
        with self._lock:
            ent = self._pending.get(token)
            if ent is not None and ent[1] is None:
                ent[1] = time.perf_counter()

    def note_read(self, keys, t_enqueued: Optional[float] = None) -> None:
        if not self._pending:   # lock-free fast path: nothing probed
            return
        import numpy as np
        now = time.perf_counter()
        cutoff = now if t_enqueued is None else t_enqueued
        with self._lock:
            if not self._pending:
                return
            probed = np.fromiter(self._pending, dtype=np.int64,
                                 count=len(self._pending))
            hits = probed[np.isin(probed, keys)]
            for k in hits:
                ent = self._pending.get(int(k))
                if ent is None or ent[1] is None or ent[1] > cutoff:
                    continue  # gather predates the push: old data
                del self._pending[int(k)]
                self.h_freshness.observe(now - ent[0])
                self.c_samples.inc()


class FlightTracer:
    """Records flight slices + phase timestamps; exports Perfetto flow
    chains. Appends are GIL-atomic list appends (client threads, the
    serve drain on the executor pool, and worker threads all record
    concurrently); memory is bounded at `max_slices`, beyond which new
    slices are counted as dropped."""

    def __init__(self, registry=None, rank: int = 0,
                 max_slices: int = 200_000,
                 freshness_bound: int = 1024):
        from .metrics import (Counter, Histogram,
                              SERVE_LATENCY_BOUNDS_S)
        self.rank = rank
        self.max_slices = max_slices
        self.dropped = 0
        self._t0 = time.perf_counter()
        self._next_id = itertools.count(1)
        # guards the plain-int tallies below: += from concurrent client
        # threads is a load/add/store that loses increments (the
        # GIL-atomic-append claim covers _slices only). Trace counts
        # are derived from the sharded registry counter instead.
        self._stats_lock = threading.Lock()
        self._complete = 0
        self._last_complete: Optional[FlightTrace] = None
        # (name, tid_key, t0, t1, ids, args) — tid_key is a real thread
        # ident (int) or a virtual-track name (str)
        self._slices: List[Tuple] = []
        # probe-table bound: --sys.flight.freshness_samples (ISSUE 20
        # satellite — the streaming controller samples this histogram
        # every tick, so the table must be deep enough that the hot
        # head's probes aren't all evicted between serve reads)
        self.freshness = FreshnessProbe(registry, bound=freshness_bound)
        use_reg = registry is not None and registry.enabled

        def _hist(name):
            return registry.histogram(name, bounds=SERVE_LATENCY_BOUNDS_S) \
                if use_reg else Histogram(name,
                                          bounds=SERVE_LATENCY_BOUNDS_S)

        # the per-request breakdown ladder (x2 serve ladder: this is
        # where the SLO lives, docs/OBSERVABILITY.md)
        self.h_queue = _hist("flight.queue_s")
        self.h_batch_wait = _hist("flight.batch_wait_s")
        self.h_dispatch = _hist("flight.dispatch_s")
        self.h_device = _hist("flight.device_s")
        if use_reg:
            self.c_traces = registry.counter("flight.traces_total")
            self.c_programs = registry.counter("flight.programs_total")
        else:
            self.c_traces = Counter("flight.traces_total")
            self.c_programs = Counter("flight.programs_total")

    # -- recording -----------------------------------------------------------

    def mint(self) -> FlightTrace:
        """New per-request trace id (ServeSession.lookup)."""
        self.c_traces.inc()
        return FlightTrace(next(self._next_id), time.perf_counter())

    def _slice(self, name: str, tid_key, t0: float, t1: float,
               ids: Tuple[int, ...], args: Optional[Dict]) -> None:
        if len(self._slices) >= self.max_slices:
            with self._stats_lock:
                self.dropped += 1
            return
        self._slices.append((name, tid_key, t0, t1, ids, args))

    def record_op(self, name: str, t0: float) -> int:
        """Single-segment flight for a plain Worker op (kv.pull /
        kv.push / kv.set): mints an id and records one slice on the
        caller's thread. Returns the id."""
        self.c_traces.inc()
        i = next(self._next_id)
        self._slice("flight." + name, threading.get_ident(), t0,
                    time.perf_counter(), (i,), None)
        return i

    def record_serve_batch(self, traces: Sequence[FlightTrace],
                           t_dispatch: float, t_enqueued: float,
                           t_done: float, n_requests: int, n_keys: int,
                           n_unique: int) -> None:
        """One coalesced micro-batch: stamps the program timestamps on
        every member trace, records the queue slice per member, the
        batch-window slice (which N requests rode this program — the
        membership attribution), the program slice on the dispatching
        thread with a nested device slice, and observes the breakdown
        histograms."""
        if not traces:
            return
        self.c_programs.inc()
        ids = tuple(t.id for t in traces)
        claims = [t.t_claim for t in traces if t.t_claim > 0.0]
        t_first_claim = min(claims) if claims else t_dispatch
        tid = threading.get_ident()
        args = {"requests": int(n_requests), "keys": int(n_keys),
                "unique_keys": int(n_unique)}
        self._slice("flight.batch", "serve.batch-window", t_first_claim,
                    t_dispatch, ids, args)
        self._slice("flight.program", tid, t_dispatch, t_done, ids,
                    {"stream": "serve"})
        self._slice("flight.device", tid, t_enqueued, t_done, ids, None)
        for tr in traces:
            tr.t_dispatch = t_dispatch
            tr.t_enqueued = t_enqueued
            tr.t_done = t_done
            if tr.t_claim > 0.0:
                self._slice("flight.queue", "serve.queue", tr.t_mint,
                            tr.t_claim, (tr.id,), None)
                self.h_queue.observe(max(0.0, tr.t_claim - tr.t_mint))
                self.h_batch_wait.observe(
                    max(0.0, t_dispatch - tr.t_claim))
            self.h_dispatch.observe(max(0.0, t_enqueued - t_dispatch))
            self.h_device.observe(max(0.0, t_done - t_enqueued))

    def finish_lookup(self, tr: FlightTrace, ok: bool) -> None:
        """Client side, at lookup return (success or shed/error): the
        reply + lookup slices close the flow; a request that never got
        served records a terminal lookup slice with its status so no
        trace dangles silently."""
        now = time.perf_counter()
        tid = threading.get_ident()
        if ok and tr.t_deliver > 0.0:
            self._slice("flight.reply", tid, tr.t_deliver, now,
                        (tr.id,), None)
            self._slice("flight.lookup", tid, tr.t_mint, now,
                        (tr.id,), None)
            if tr.t_claim > 0.0 and tr.t_dispatch > 0.0:
                with self._stats_lock:
                    self._complete += 1
                    self._last_complete = tr
        else:
            self._slice("flight.lookup", tid, tr.t_mint, now, (tr.id,),
                        {"status": "shed"})

    # -- summaries -----------------------------------------------------------

    def stats(self) -> Dict[str, int]:
        return {"traces": int(self.c_traces.value),
                "slices": len(self._slices),
                "complete": self._complete, "dropped": self.dropped}

    def exemplar(self) -> Optional[Dict[str, float]]:
        """One sampled complete trace's queue/batch/dispatch/device
        split (ms) — the bench artifact's 'where did the time go'
        exhibit. None until a lookup completed under tracing."""
        tr = self._last_complete
        if tr is None:
            return None
        out = {"trace_id": tr.id}
        out.update({k.replace("_s", "_ms"): round(v * 1e3, 4)
                    for k, v in tr.breakdown_s().items()})
        return out

    # -- export --------------------------------------------------------------

    def _us(self, t: float) -> float:
        return (t - self._t0) * 1e6

    def export(self, path: str) -> str:
        """Chrome trace-event JSON with flow events: load in
        https://ui.perfetto.dev, click any `flight.lookup` slice and
        follow the flow arrows through queue -> batch -> program ->
        reply (docs/OBSERVABILITY.md has the recipe)."""
        slices = list(self._slices)
        # tid assignment: real thread idents first (named from live
        # threads), then the virtual tracks
        tids: Dict = {}
        names: Dict[int, str] = {t.ident: t.name
                                 for t in threading.enumerate()
                                 if t.ident is not None}
        out = []
        # per-id phase index for the flow chains: id -> {phase: slice}
        by_id: Dict[int, Dict[int, Tuple]] = {}
        for sl in slices:
            name, tid_key, t0, t1, ids, args = sl
            tid = tids.setdefault(tid_key, len(tids))
            ev_args = dict(args or {})
            ev_args["traces"] = list(ids[:64])
            out.append({"name": name, "cat": "flight", "ph": "X",
                        "ts": round(self._us(t0), 3),
                        "dur": round(max(0.0, (t1 - t0) * 1e6), 3),
                        "pid": self.rank, "tid": tid, "args": ev_args})
            pi = _PHASE_IDX.get(name)
            if pi is not None:
                for i in ids:
                    by_id.setdefault(i, {}).setdefault(pi, sl)
        flows = []
        complete = 0
        for trace_id, phases in sorted(by_id.items()):
            if len(phases) != len(FLIGHT_PHASES):
                continue  # incomplete (shed / still in flight): slices
                # are exported above, but no flow chain is fabricated
            complete += 1
            for pi in range(len(FLIGHT_PHASES)):
                name, tid_key, t0, t1, _ids, _args = phases[pi]
                tid = tids[tid_key]
                # anchor INSIDE the slice: the chain start sits at the
                # lookup's begin, every later step near its phase's end
                # so the flow ts order mirrors causal order
                eps = min(0.5, max(0.0, (t1 - t0) * 1e6 / 2))
                ts = self._us(t0) if pi == 0 else self._us(t1) - eps
                ev = {"name": "flight", "cat": "flight",
                      "ph": "s" if pi == 0 else
                      ("f" if pi == len(FLIGHT_PHASES) - 1 else "t"),
                      "id": int(trace_id), "pid": self.rank,
                      "tid": tid, "ts": round(ts, 3)}
                if ev["ph"] == "f":
                    ev["bp"] = "e"  # bind the finish to the enclosing
                    # slice, like the steps
                flows.append(ev)
        meta = []
        for tid_key, tid in tids.items():
            label = tid_key if isinstance(tid_key, str) else \
                names.get(tid_key, f"thread-{tid_key}")
            meta.append({"name": "thread_name", "ph": "M",
                         "pid": self.rank, "tid": tid,
                         "args": {"name": label}})
        meta.append({"name": "process_name", "ph": "M",
                     "pid": self.rank,
                     "args": {"name": f"adapm flight rank {self.rank}"}})
        doc = {"traceEvents": meta + out + flows,
               "displayTimeUnit": "ms",
               "adapm_flight": {"complete_flows": complete,
                                **self.stats()}}
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(doc, f)
        return path


# ---------------------------------------------------------------------------
# executor flight-recorder ring
# ---------------------------------------------------------------------------

_RING_WIDTH = 192


class FlightRecorder:
    """Bounded per-stream ring of the last executor programs, mirrored
    into a fixed-size ring FILE one `pwrite` per program (see module
    docstring). Always cheap: one deque append + one small write per
    executor PROGRAM — never on the per-op hot path."""

    def __init__(self, path: Optional[str] = None, per_stream: int = 32,
                 file_slots: int = 128):
        self.path = path
        self._per_stream = int(per_stream)
        self._slots = int(file_slots)
        # several executor workers record concurrently: the lock covers
        # the ring/count mutation only (per PROGRAM, never per op)
        self._lock = threading.Lock()
        self._rings: Dict[str, collections.deque] = {}
        self._counts: Dict[str, int] = {}
        self._total = 0
        self._seq = itertools.count()
        self._fd = None
        if path:
            try:
                self._fd = os.open(path,
                                   os.O_CREAT | os.O_WRONLY | os.O_TRUNC,
                                   0o644)
            except OSError:  # unwritable dir must not block startup
                self._fd = None

    def record(self, stream: str, label: str,
               coalesce_key: Optional[str], wait_s: float, run_s: float,
               failed: bool = False) -> None:
        # BOTH clock domains (ISSUE 15 satellite): span/flight slices
        # are monotonic, so a ring stamped with wall time alone skews
        # against them across NTP steps when timelines are merged —
        # record wall (for humans/post-mortems) AND monotonic (for
        # ordering/replay alignment)
        entry = (time.time(), time.monotonic(), label, coalesce_key,
                 wait_s, run_s, failed)
        with self._lock:
            dq = self._rings.get(stream)
            if dq is None:
                dq = self._rings.setdefault(
                    stream, collections.deque(maxlen=self._per_stream))
            dq.append(entry)
            self._counts[stream] = self._counts.get(stream, 0) + 1
            self._total += 1
        fd = self._fd
        if fd is not None:
            line = (f"{entry[0]:.3f} stream={stream} label={label} "
                    f"key={coalesce_key or '-'} "
                    f"wait_us={wait_s * 1e6:.0f} run_us={run_s * 1e6:.0f}"
                    f"{' FAILED' if failed else ''}").encode()
            line = line[:_RING_WIDTH - 1].ljust(_RING_WIDTH - 1) + b"\n"
            try:
                os.pwrite(fd, line,
                          (next(self._seq) % self._slots) * _RING_WIDTH)
            except OSError:
                pass  # a full disk must not take the executor down

    def tail(self, stream: Optional[str] = None) -> List[Dict]:
        """Most-recent-last entries of one stream's ring (or all
        streams merged by the MONOTONIC stamp — wall time can step
        backwards under NTP; each entry carries both as `t`/`t_mono`)."""
        if stream is not None:
            rings = [(stream, self._rings.get(stream, ()))]
        else:
            rings = list(self._rings.items())
        out = []
        for name, dq in rings:
            for (t, mono, label, ck, wait_s, run_s, failed) in list(dq):
                out.append({"t": t, "t_mono": mono, "stream": name,
                            "label": label, "coalesce_key": ck,
                            "wait_s": wait_s, "run_s": run_s,
                            "failed": failed})
        # merge by the MONOTONIC stamp: wall time can step backwards
        # under NTP, and a merged timeline must never reorder
        out.sort(key=lambda e: e["t_mono"])
        return out

    def summary(self) -> Dict:
        return {"programs_recorded": self._total,
                "per_stream": dict(sorted(self._counts.items())),
                "ring_path": self.path}

    def close(self) -> None:
        fd = self._fd
        self._fd = None
        if fd is not None:
            try:
                os.close(fd)
            except OSError:
                pass
