"""Crash breadcrumbs for hard aborts (segfault / SIGABRT).

This image's XLA CPU intermittently segfaults on pre-existing code paths
(CHANGES.md r6: checkpoint restore -> first sync_replicas, reproduced on
the unmodified seed). A Python traceback never appears for those, so:

  - `faulthandler` is enabled with a PER-RANK dump file
    (`--sys.crash_dumps`, default on): the native-signal handler writes
    every thread's Python stack into the file as the process dies.
  - span begins overwrite a last-open-span breadcrumb file
    (obs/spans.py) when `--sys.trace.spans` is on, naming the phase the
    process died inside.
  - the executor flight-recorder ring (obs/flight.py FlightRecorder)
    mirrors the last K executor programs — stream, label, coalesce key,
    wait/run times — into a fixed-size ring file next to the dump, one
    `pwrite` per PROGRAM, so the abort's post-mortem also says what was
    in flight when the process died.

Dump files go to `--sys.stats.out` when set, else the system temp dir;
they are tiny, overwritten per process, and cost nothing until a crash.
`faulthandler.enable` is idempotent per file; re-enabling (a second
Server in one process, common in tests) just repoints the handler.
"""
from __future__ import annotations

import faulthandler
import os
import tempfile
from typing import Optional, Tuple

_dump_file = None  # keep the handle alive: faulthandler writes by fd


def crash_dir(stats_out: Optional[str]) -> str:
    d = stats_out if stats_out else tempfile.gettempdir()
    os.makedirs(d, exist_ok=True)
    return d


def enable_crash_dumps(rank: int,
                       stats_out: Optional[str]) -> Tuple[str, str, str]:
    """Enable faulthandler into a per-rank dump file; returns
    (dump_path, breadcrumb_path, flight_ring_path). The breadcrumb file
    is only written when span tracing is on (SpanTracer owns that fd);
    the flight-ring file is written by the executor's FlightRecorder
    (obs/flight.py, one pwrite per program)."""
    global _dump_file
    d = crash_dir(stats_out)
    dump_path = os.path.join(d, f"adapm_crash.{rank}.{os.getpid()}.log")
    bc_path = os.path.join(d, f"adapm_breadcrumb.{rank}.{os.getpid()}.txt")
    ring_path = os.path.join(d, f"adapm_flightring.{rank}.{os.getpid()}.log")
    if _dump_file is not None:
        try:
            _dump_file.close()
        except OSError:
            pass
    _dump_file = open(dump_path, "w")
    faulthandler.enable(file=_dump_file, all_threads=True)
    return dump_path, bc_path, ring_path
