"""Span tracer: begin/end events for named phases, Perfetto-loadable.

`SpanTracer.span(name)` brackets a phase; completed spans are stored as
(thread, name, start_us, dur_us) tuples and exported as Chrome
trace-event JSON (`ph: "X"` complete events + thread-name metadata),
which chrome://tracing and https://ui.perfetto.dev load directly.

Off by default (`--sys.trace.spans`); when off the Server holds no
tracer and instrumented sites pay one `is None` check (or enter
`NULL_SPAN`, a shared no-op context manager).

Crash breadcrumb (ISSUE 2 satellite): when given a breadcrumb path, the
tracer overwrites a small fixed-size file with the span name + wall time
at every span BEGIN (one `pwrite`, no seek state). After a hard abort —
this image's XLA CPU segfaults intermittently on pre-existing
checkpoint-restore paths (CHANGES.md r6) — the file names the phase the
process died inside, complementing the faulthandler stack
(obs/crash.py).

Memory is bounded: beyond `max_events` spans
(`--sys.trace.spans.max_events`, validated >= 1000 in config.py), new
ones are counted as dropped instead of stored — loudly: one warning
log on the first drop plus the `spans.dropped` registry counter
(ISSUE 17 satellite; the old behavior capped silently at a hardcoded
1M), and the exported trace states the truncation.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

_BREADCRUMB_WIDTH = 256


class _NullSpan:
    """Shared no-op context manager for disabled tracing."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("tracer", "name", "t0")

    def __init__(self, tracer: "SpanTracer", name: str):
        self.tracer = tracer
        self.name = name
        self.t0 = 0.0

    def __enter__(self):
        # apm-lint: disable=APM003 a _Span is only ever constructed BY
        # a live SpanTracer (disabled tracing hands out NULL_SPAN), so
        # this tracer attribute is never the optional server handle
        self.t0 = self.tracer.begin(self.name)
        return self

    def __exit__(self, *exc):
        # apm-lint: disable=APM003 same invariant as __enter__ above
        self.tracer.end(self.name, self.t0)
        return False


class SpanTracer:
    def __init__(self, rank: int = 0, max_events: int = 1_000_000,
                 breadcrumb_path: Optional[str] = None, registry=None):
        self.rank = rank
        self.max_events = max_events
        self.dropped = 0
        # overflow drops are loud (ISSUE 17 satellite): a registry
        # counter when the server's registry is live, else the plain
        # `dropped` tally alone (spans.* names exist only while a
        # tracer does — the skip-wrapper naming discipline)
        self._c_dropped = None
        if registry is not None and registry.enabled:
            self._c_dropped = registry.counter("spans.dropped")
        self._warned_drop = False
        # (tid, name, t0_us, dur_us); list.append is atomic under the GIL
        self._events: List[Tuple[int, str, float, float]] = []
        self._t0 = time.perf_counter()
        self._bc_fd = None
        self._bc_path = breadcrumb_path
        if breadcrumb_path:
            self._bc_fd = os.open(breadcrumb_path,
                                  os.O_CREAT | os.O_WRONLY, 0o644)

    # -- recording -----------------------------------------------------------

    def span(self, name: str) -> _Span:
        return _Span(self, name)

    def begin(self, name: str) -> float:
        if self._bc_fd is not None:
            line = (f"{name} thread={threading.current_thread().name} "
                    f"wall={time.time():.3f}\n").encode()
            os.pwrite(self._bc_fd, line.ljust(_BREADCRUMB_WIDTH), 0)
        return time.perf_counter()

    def end(self, name: str, t0: float) -> None:
        t1 = time.perf_counter()
        if len(self._events) >= self.max_events:
            self.dropped += 1
            if self._c_dropped is not None:
                self._c_dropped.inc()
            if not self._warned_drop:
                self._warned_drop = True
                from ..utils import alog
                alog(f"[spans] event buffer full ({self.max_events} "
                     f"spans; --sys.trace.spans.max_events); further "
                     f"spans are DROPPED (counted in spans.dropped) — "
                     f"the exported trace is a loud prefix, not a "
                     f"silent lie")
            return
        self._events.append((threading.get_ident(), name,
                             (t0 - self._t0) * 1e6, (t1 - t0) * 1e6))

    # -- export --------------------------------------------------------------

    def stats(self) -> Dict[str, int]:
        return {"events": len(self._events), "dropped": self.dropped}

    def export(self, path: str) -> str:
        """Write Chrome trace-event JSON; returns the path."""
        events = list(self._events)
        tids: Dict[int, int] = {}
        names: Dict[int, str] = {t.ident: t.name
                                 for t in threading.enumerate()
                                 if t.ident is not None}
        out = []
        for ident, name, ts, dur in events:
            tid = tids.setdefault(ident, len(tids))
            out.append({"name": name, "cat": "adapm", "ph": "X",
                        "ts": round(ts, 3), "dur": round(dur, 3),
                        "pid": self.rank, "tid": tid})
        meta = [{"name": "thread_name", "ph": "M", "pid": self.rank,
                 "tid": tid,
                 "args": {"name": names.get(ident, f"thread-{ident}")}}
                for ident, tid in tids.items()]
        meta.append({"name": "process_name", "ph": "M", "pid": self.rank,
                     "args": {"name": f"adapm rank {self.rank}"}})
        doc = {"traceEvents": meta + out, "displayTimeUnit": "ms"}
        if self.dropped:
            doc["adapm_dropped_events"] = self.dropped
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(doc, f)
        return path

    def close(self) -> None:
        if self._bc_fd is not None:
            os.close(self._bc_fd)
            self._bc_fd = None
