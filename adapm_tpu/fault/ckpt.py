"""Incremental dirty-slot checkpoints (ISSUE 10 tentpole, layer 1).

`utils/checkpoint.py` snapshots the WHOLE manager every time — at
NestPipe scale (PAPERS.md: recommendation models on 1,500+
accelerators) restart-from-full-checkpoint stops being viable, and the
r8/r10 write-epoch tracking already knows exactly which slots changed.
This module ships only those:

  - a **chain** lives in one directory: `base-000000.npz` (the full
    authoritative main tables + placement tables) followed by
    `delta-NNNNNN.npz` files, each holding only the main-row slots
    whose write epoch advanced since the previous link plus the
    currently-dirty replicas' (cache, delta) rows, plus any placement
    table that changed (ownership, replica map, clocks, intent
    horizons — skipped byte-identical, so a pure-push trickle's delta
    is rows + a few scalars);
  - every link is written **atomically** (tmp + fsync + rename) and
    carries a sha256 over its bytes; `chain.json` (also atomic) lists
    the links with their checksums AND each link's predecessor digest,
    so a truncated, bit-flipped, missing, or spliced link fails
    verification by name (`CheckpointCorruptError` /
    `CheckpointChainError`) — never a half-restore;
  - **restore** verifies and loads the ENTIRE chain into host memory
    first (the live server is untouched by any failure up to that
    point), then replays base + deltas under one topology-mutation
    critical section, rebuilds allocators/replica registries exactly
    like `utils.checkpoint.restore_server`, and resets write tracking.
    While the apply runs the server is DEGRADED (`Server.
    begin_degraded`): the serve plane sheds loudly with
    `ServeDegradedError` instead of risking a read that mixes pre- and
    post-restore bits (serve/batcher.py, serve/session.py).

Exactness argument (why replay == the state at the last save): every
path that can change a main row's VALUE bumps its `main_epoch` cell
under the server lock before the device program enqueues (core/
store.py), and the capture runs under that same lock with a device
readback that synchronizes with everything enqueued — so each link
captures exactly the cells changed since the previous link, with their
save-time bits, and cell-wise last-writer replay reconstructs the final
table. Replicas: a CLEAN replica (per `Server._dirty_replica_mask`) is
bitwise `cache == main row, delta == 0` — the dirty-filter invariant
tests/test_replica_table.py pins — so restore rebuilds clean replicas
from the replayed mains and overlays only the last link's captured
dirty (cache, delta) rows. Pinned by tests/test_fault.py and the
kill/restore drill (scripts/fault_drill_check.py).

Periodic operation: `--sys.checkpoint.every S --sys.checkpoint.path D`
runs `save()` as a self-rescheduling program on the executor's `ckpt`
stream (no thread; the executor-subsumption discipline of PR 6).
`Server.shutdown()` closes the checkpointer BEFORE pool teardown and
drains the `ckpt` stream, so an in-flight save never races the pools
out from under itself (ISSUE 10 satellite).

Multi-process is out of scope for the incremental chain (use
`utils.checkpoint.save_server`'s quiesced per-rank shards); save and
restore raise loudly under a GlobalPM.
"""
from __future__ import annotations

import hashlib
import io
import json
import os
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

MANIFEST_FORMAT = 1
FORMAT_VERSION = 1
MANIFEST_NAME = "chain.json"

# placement/meta tables captured per link iff changed since the
# previous link (byte-identical tables are skipped — a pure-push
# trickle's delta carries rows only)
_AUX_KEYS = ("owner", "slot", "cache_slot", "relocation_counter",
             "intent_end", "clocks")


class CheckpointCorruptError(RuntimeError):
    """A chain link's bytes do not match its recorded sha256 (truncated
    write, bit flip, unreadable archive). Raised during verification,
    BEFORE any server mutation."""


class CheckpointChainError(RuntimeError):
    """The chain itself is broken: missing manifest, missing/spliced
    link, non-contiguous sequence, predecessor-digest mismatch, or a
    geometry/format incompatibility with the restoring server. Raised
    during verification, BEFORE any server mutation."""


from ..utils import write_atomic as _write_atomic  # noqa: E402 — the
# shared tmp+fsync+rename discipline (adapm_tpu/utils; also used by the
# workload-trace recorder and the replay artifact writer)


def _npz_bytes(arrs: Dict[str, np.ndarray]) -> bytes:
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrs)
    return buf.getvalue()


class IncrementalCheckpointer:
    """Owns one checkpoint chain for one (single-process) Server. The
    first `save()` writes the base; every later one a delta.
    Constructing a checkpointer on a directory STARTS A NEW CHAIN
    (existing links are superseded by the fresh manifest) — the resume
    workflow is restore_chain() first, then a new checkpointer."""

    def __init__(self, server, path: str):
        if server.glob is not None:
            raise NotImplementedError(
                "incremental checkpoint chains are single-process; "
                "multi-process jobs use utils.checkpoint.save_server's "
                "quiesced per-rank shards")
        if not path:
            raise ValueError("--sys.checkpoint.path is required for "
                             "incremental checkpoints")
        self.server = server
        self.path = path
        os.makedirs(path, exist_ok=True)
        self.chain_id = os.urandom(8).hex()
        self._entries: List[Dict] = []
        self._marks: List[int] = [0] * len(server.stores)
        self._aux_last: Dict[str, np.ndarray] = {}
        self._seq = 0
        import threading
        self._save_lock = threading.Lock()
        self._stop = False
        self._closed = False
        self._every_s = 0.0
        self._token = None
        # accounting (snapshot `ckpt` section; plain values — the
        # section is populated only when a checkpointer is attached)
        self.saves_total = 0
        self.bases_total = 0
        self.deltas_total = 0
        self.bytes_total = 0
        self.last_bytes = 0
        self.last_slots = 0
        self.last_kind = ""
        self.last_save_s = 0.0

    # -- capture -------------------------------------------------------------

    def _aux_arrays(self) -> Dict[str, np.ndarray]:
        srv = self.server
        ab = srv.ab
        out = {"owner": ab.owner, "slot": ab.slot,
               "cache_slot": ab.cache_slot,
               "relocation_counter": ab.relocation_counter,
               "intent_end": srv.sync.intent_end,
               "clocks": srv._clocks}
        # streaming plane (ISSUE 20): the acked-event cursor rides the
        # chain so a restore lands on "events [0, cursor) applied
        # exactly once". Captured under the SAME lock hold as the row
        # bits, and — like the trainer's cursor bump — never torn
        # against a push: both sides bracket with the server RLock.
        # Optional: pre-v16 chains (and stream-off servers) simply
        # never carry it, so it is NOT in _AUX_KEYS' mandatory set.
        if getattr(srv, "stream", None) is not None:
            out["stream_cursor"] = srv.stream.cursor
        return out

    def _capture_locked(self, kind: str):
        """Assemble one link's arrays (caller holds the server lock).
        Returns (arrs, new_marks, new_aux, slots_captured); the caller
        commits marks/aux only after the link is durably written."""
        srv = self.server
        ab = srv.ab
        arrs: Dict[str, np.ndarray] = {
            "format_version": np.int64(FORMAT_VERSION),
            "kind": np.frombuffer(kind.encode(), dtype=np.uint8).copy(),
            "num_keys": np.int64(srv.num_keys),
            "num_shards": np.int64(srv.num_shards),
        }
        if kind == "base":
            # compat metadata rides the base only: an O(num_keys)
            # array on every delta would put a floor under the very
            # bytes the incremental chain exists to shrink
            arrs["value_lengths"] = srv.value_lengths
        slots = 0
        new_marks = list(self._marks)
        for cid, st in enumerate(srv.stores):
            if kind == "base":
                arrs[f"main_{cid}"] = st.main_host()
                slots += int(st.main_shape_full[0] *
                             st.main_shape_full[1])
            else:
                sh, sl = np.nonzero(st.main_epoch > self._marks[cid])
                arrs[f"dsh_{cid}"] = sh.astype(np.int32)
                arrs[f"dsl_{cid}"] = sl.astype(np.int32)
                arrs[f"drows_{cid}"] = (
                    st.read_rows("main", sh.astype(np.int32),
                                 sl.astype(np.int32))
                    if len(sh) else
                    np.empty((0, st.value_length), dtype=np.float32))
                slots += len(sh)
            # the readback above synchronized with every enqueued
            # program; under the lock nothing new can land, so the
            # store's CURRENT epoch is the watermark this link covers
            new_marks[cid] = st._epoch
        # currently-dirty replicas: the restore rebuilds clean ones
        # from the replayed mains (clean == bitwise cache==main,
        # delta==0 — the dirty-filter invariant), so only these need
        # their (cache, delta) rows shipped
        shards, keys = np.nonzero(ab.cache_slot >= 0)
        if len(keys):
            keys = keys.astype(np.int64)
            shards = shards.astype(np.int32)
            dirty = srv._dirty_replica_mask(keys, shards)
            dk, ds = keys[dirty], shards[dirty]
        else:
            dk = np.empty(0, dtype=np.int64)
            ds = np.empty(0, dtype=np.int32)
        for cid, st in enumerate(srv.stores):
            if len(dk):
                in_cls = ab.key_class[dk] == cid
                ck, cs_sh = dk[in_cls], ds[in_cls]
            else:
                ck = np.empty(0, dtype=np.int64)
                cs_sh = np.empty(0, dtype=np.int32)
            cs = ab.cache_slot[cs_sh, ck].astype(np.int32) if len(ck) \
                else np.empty(0, dtype=np.int32)
            arrs[f"rsh_{cid}"] = cs_sh
            arrs[f"rcs_{cid}"] = cs
            if len(ck):
                arrs[f"rcache_{cid}"] = st.read_rows("cache", cs_sh, cs)
                arrs[f"rdelta_{cid}"] = st.read_rows("delta", cs_sh, cs)
            else:
                empty = np.empty((0, st.value_length), dtype=np.float32)
                arrs[f"rcache_{cid}"] = empty
                arrs[f"rdelta_{cid}"] = empty
        # placement/meta tables, skipped when byte-identical to the
        # previous link (aux churn, not row churn, would otherwise
        # dominate a small-model delta). Serialize the COPY taken
        # under the lock, never the live table: serialization happens
        # after the lock releases, and a concurrent relocation mutates
        # these arrays in place — a live reference would let the link
        # record placement from mid-mutation, inconsistent with the
        # row bits read back above
        new_aux: Dict[str, np.ndarray] = {}
        for name, arr in self._aux_arrays().items():
            prev = self._aux_last.get(name)
            if prev is None or not np.array_equal(prev, arr):
                snap = arr.copy()
                arrs[f"aux_{name}"] = snap
                new_aux[name] = snap
        return arrs, new_marks, new_aux, slots

    # -- save ----------------------------------------------------------------

    def save(self) -> Dict:
        """Write the next chain link (base first, deltas after):
        capture under the server lock, serialize, write atomically,
        then extend the manifest. Returns the manifest entry. A
        failure anywhere leaves the previous chain fully restorable
        (the manifest still describes only durably-written links)."""
        srv = self.server
        f = srv.fault
        if f is not None:
            f.fire("ckpt.save")
        with self._save_lock:
            t0 = time.perf_counter()
            kind = "base" if not self._entries else "delta"
            with srv._lock:
                arrs, new_marks, new_aux, slots = \
                    self._capture_locked(kind)
            blob = _npz_bytes(arrs)
            fname = f"{kind}-{self._seq:06d}.npz"
            _write_atomic(os.path.join(self.path, fname), blob)
            entry = {
                "seq": self._seq,
                "kind": kind,
                "file": fname,
                "bytes": len(blob),
                "slots": int(slots),
                "sha256": hashlib.sha256(blob).hexdigest(),
                "prev_sha256": (self._entries[-1]["sha256"]
                                if self._entries else ""),
                "wall_time": time.time(),
            }
            self._entries.append(entry)
            manifest = {"format": MANIFEST_FORMAT,
                        "chain_id": self.chain_id,
                        "entries": self._entries}
            _write_atomic(os.path.join(self.path, MANIFEST_NAME),
                          json.dumps(manifest, indent=1).encode())
            # commit the watermarks only now: had the write failed, the
            # next save would re-capture these slots (never lose them)
            self._marks = new_marks
            self._aux_last.update(new_aux)
            self._seq += 1
            self.saves_total += 1
            if kind == "base":
                self.bases_total += 1
            else:
                self.deltas_total += 1
            self.bytes_total += len(blob)
            self.last_bytes = len(blob)
            self.last_slots = int(slots)
            self.last_kind = kind
            self.last_save_s = time.perf_counter() - t0
            return entry

    # -- periodic operation (the `ckpt` executor stream) ---------------------

    def start_periodic(self, every_s: float) -> None:
        """Schedule `save()` every `every_s` seconds as a
        self-rescheduling delayed program on the `ckpt` stream (no
        sleeping thread). A failed save is logged and the cadence
        continues — the chain stays restorable to its last good link."""
        assert every_s > 0
        self._every_s = float(every_s)
        token = object()
        self._token = token

        def tick():
            from ..utils import alog
            if self._stop or self._token is not token:
                return
            try:
                self.save()
            except Exception as e:  # noqa: BLE001 — cadence survives
                # one failed save (injected or real I/O); the manifest
                # still describes only durable links
                f = self.server.fault
                if f is not None:
                    f.c_loop_retries.inc()
                alog(f"[ckpt] periodic save failed: "
                     f"{type(e).__name__}: {e}")
            if not self._stop and self._token is token:
                self.server.exec.submit("ckpt", tick, label="ckpt.save",
                                        coalesce_key="ckpt.save",
                                        delay=self._every_s)

        self.server.exec.submit("ckpt", tick, label="ckpt.save",
                                coalesce_key="ckpt.save",
                                delay=self._every_s)

    def close(self) -> None:
        """Stop the periodic program and drain the `ckpt` stream
        (idempotent). A save still in flight reads through the pools,
        so Server.shutdown() calls this BEFORE pool teardown; a save
        that cannot drain is wedged and fail-stops loudly instead of
        letting teardown pull the pools out from under it."""
        if self._closed:
            return
        self._closed = True
        self._stop = True
        ex = self.server.exec
        if not ex.closed and not ex.drain("ckpt", timeout=60):
            from ..utils import alog
            alog("[ckpt] checkpoint program failed to drain within 60s "
                 "of close — wedged mid-save")
            raise RuntimeError(
                "checkpoint program wedged: did not drain within 60s "
                "of close; refusing to proceed into pool teardown "
                "under a live reader")

    def stats(self) -> Dict:
        return {"saves_total": self.saves_total,
                "bases_total": self.bases_total,
                "deltas_total": self.deltas_total,
                "bytes_total": self.bytes_total,
                "last_bytes": self.last_bytes,
                "last_slots": self.last_slots,
                "last_kind": self.last_kind,
                "last_save_s": self.last_save_s,
                "chain_len": len(self._entries),
                "periodic_every_s": self._every_s}


# ---------------------------------------------------------------------------
# restore
# ---------------------------------------------------------------------------


def _load_manifest(path: str) -> Dict:
    mp = os.path.join(path, MANIFEST_NAME)
    if not os.path.exists(mp):
        raise CheckpointChainError(
            f"no checkpoint chain manifest at {mp}")
    try:
        with open(mp, "rb") as f:
            m = json.loads(f.read().decode())
    except (OSError, ValueError) as e:
        raise CheckpointCorruptError(
            f"chain manifest {mp} is unreadable: {e}") from e
    if m.get("format") != MANIFEST_FORMAT:
        raise CheckpointChainError(
            f"chain manifest format {m.get('format')!r} is "
            f"incompatible (expects {MANIFEST_FORMAT})")
    entries = m.get("entries") or []
    if not entries:
        raise CheckpointChainError(
            f"chain manifest {mp} lists no checkpoints")
    if entries[0].get("kind") != "base":
        raise CheckpointChainError(
            "chain does not start with a base checkpoint")
    for i, e in enumerate(entries):
        if e.get("seq") != i:
            raise CheckpointChainError(
                f"chain sequence broken at position {i}: manifest "
                f"lists seq {e.get('seq')!r} (a link is missing or "
                f"the manifest was edited)")
        if i > 0 and e.get("kind") != "delta":
            raise CheckpointChainError(
                f"unexpected {e.get('kind')!r} link at seq {i} "
                f"(only link 0 may be a base)")
    return m


def _load_verified_chain(path: str) -> List[Tuple[Dict, Dict]]:
    """Verify and load the whole chain into host memory. Every failure
    mode raises a NAMED error here, before the caller touches any
    server state."""
    m = _load_manifest(path)
    out: List[Tuple[Dict, Dict]] = []
    prev_sha = ""
    for e in m["entries"]:
        fp = os.path.join(path, e["file"])
        if not os.path.exists(fp):
            raise CheckpointChainError(
                f"missing chain link {e['file']} (seq {e['seq']}): "
                f"the manifest names it but the file is gone")
        with open(fp, "rb") as f:
            data = f.read()
        sha = hashlib.sha256(data).hexdigest()
        if sha != e.get("sha256"):
            raise CheckpointCorruptError(
                f"chain link {e['file']} (seq {e['seq']}) failed its "
                f"checksum ({len(data)} bytes on disk): truncated or "
                f"corrupt — refusing a half-restore")
        if e.get("prev_sha256", "") != prev_sha:
            raise CheckpointChainError(
                f"chain link {e['file']} (seq {e['seq']}) does not "
                f"chain to its predecessor (manifest edited or links "
                f"spliced from different chains)")
        prev_sha = sha
        try:
            with np.load(io.BytesIO(data), allow_pickle=False) as z:
                arrs = {k: z[k] for k in z.files}
        except Exception as e2:  # noqa: BLE001 — checksum passed but
            # the archive is unreadable: still a corrupt link
            raise CheckpointCorruptError(
                f"chain link {e['file']} is not a readable archive: "
                f"{e2}") from e2
        if int(arrs["format_version"]) != FORMAT_VERSION:
            raise CheckpointChainError(
                f"chain link {e['file']} has format "
                f"v{int(arrs['format_version'])} (expects "
                f"v{FORMAT_VERSION})")
        out.append((e, arrs))
    return out


def _check_compat(server, chain: List[Tuple[Dict, Dict]]) -> None:
    _, base = chain[0]
    if int(base["num_keys"]) != server.num_keys:
        raise CheckpointChainError(
            f"key count mismatch: chain has {int(base['num_keys'])}, "
            f"server has {server.num_keys}")
    if int(base["num_shards"]) != server.num_shards:
        raise CheckpointChainError(
            f"shard count mismatch: chain has "
            f"{int(base['num_shards'])}, server has "
            f"{server.num_shards}")
    if not (base["value_lengths"] == server.value_lengths).all():
        raise CheckpointChainError("value-length layout mismatch")
    for cid, st in enumerate(server.stores):
        got = base[f"main_{cid}"].shape
        if got != st.main_shape_full:
            raise CheckpointChainError(
                f"pool main_{cid} geometry mismatch: chain "
                f"{got} vs server {st.main_shape_full}")


def restore_chain(server, path: str,
                  hold_degraded_s: float = 0.0) -> float:
    """Verify + replay a checkpoint chain into a compatibly-constructed
    single-process Server. Returns the recovery wall time (seconds;
    also recorded as `ckpt.recovery_s` in metrics_snapshot).

    Failure contract: every verification error (`CheckpointChainError`
    / `CheckpointCorruptError` / geometry mismatch) raises BEFORE any
    server mutation — the live server keeps serving its current state.
    During the apply the server is DEGRADED: serve lookups shed loudly
    with `ServeDegradedError` (never a torn or mixed read); on apply
    success the flag clears, on an apply failure it stays set (the
    server's state is indeterminate — fail-stop, never quietly serve).

    `hold_degraded_s` keeps the degraded state up that much longer
    after a successful apply — an operational knob for drills and for
    deployments that gate traffic on an external health probe's
    observation window (scripts/fault_drill_check.py uses it to pin
    the shed-while-degraded contract deterministically)."""
    if server.glob is not None:
        raise NotImplementedError(
            "restore_chain is single-process; multi-process jobs use "
            "utils.checkpoint.restore_server")
    f = server.fault
    if f is not None:
        f.fire("ckpt.restore")
    t0 = time.perf_counter()
    chain = _load_verified_chain(path)
    _check_compat(server, chain)
    server.begin_degraded(
        f"checkpoint restore in progress ({path}, "
        f"{len(chain)} links)")
    _apply_chain(server, chain)
    recovery_s = time.perf_counter() - t0
    server._last_recovery_s = recovery_s
    if hold_degraded_s > 0:
        time.sleep(hold_degraded_s)
    server.end_degraded()
    return recovery_s


def _apply_chain(server, chain: List[Tuple[Dict, Dict]]) -> None:
    from ..utils.checkpoint import _rebuild_alloc, _rebuild_cache_alloc
    # latest version of each aux table across the chain (links skip
    # unchanged tables)
    aux: Dict[str, np.ndarray] = {}
    for _, arrs in chain:
        for name in _AUX_KEYS:
            k = f"aux_{name}"
            if k in arrs:
                aux[name] = arrs[k]
        # optional stream cursor (ISSUE 20): collected when present,
        # never required — pre-v16 chains and stream-off servers have
        # no aux_stream_cursor and must keep restoring cleanly
        if "aux_stream_cursor" in arrs:
            aux["stream_cursor"] = arrs["aux_stream_cursor"]
    missing = [n for n in _AUX_KEYS if n not in aux]
    if missing:
        raise CheckpointChainError(
            f"chain never captured table(s) {missing} (base link "
            f"incomplete)")
    _, final = chain[-1]
    with server._lock, server._topology_mutation():
        # leading bump: any concurrently-planned optimistic route fails
        # revalidation instead of dispatching pre-restore coordinates
        # (the restore_server discipline, utils/checkpoint.py)
        server.topology_version += 1
        ab = server.ab
        ab.owner[:] = aux["owner"]
        ab.slot[:] = aux["slot"]
        ab.cache_slot[:] = aux["cache_slot"]
        ab.relocation_counter[:] = aux["relocation_counter"]
        ab.replica_count[:] = (ab.cache_slot >= 0).sum(axis=0)
        server.sync.intent_end[:] = aux["intent_end"]
        server._clocks[:] = aux["clocks"]
        for wid, w in server._workers.items():
            w._clock = int(server._clocks[wid])
        if "stream_cursor" in aux:
            # acked-event horizon (ISSUE 20): recorded on the server
            # regardless of plane state, and written into the live
            # plane when one exists — a resumed StreamTrainer starts
            # from here and replay_tail() re-applies only the tail
            # between this and the pre-kill ack watermark
            cur = int(np.asarray(aux["stream_cursor"]).reshape(-1)[0])
            server._restored_stream_cursor = cur
            if getattr(server, "stream", None) is not None:
                server.stream.cursor[0] = cur

        rep_sh, rep_k = np.nonzero(ab.cache_slot >= 0)
        for cid, st in enumerate(server.stores):
            # replay: base table, then cell-wise last-writer deltas
            full = np.array(chain[0][1][f"main_{cid}"])
            for _, arrs in chain[1:]:
                dsh, dsl = arrs[f"dsh_{cid}"], arrs[f"dsl_{cid}"]
                if len(dsh):
                    full[dsh, dsl] = arrs[f"drows_{cid}"]
            if st.res is not None:
                from ..tier.coldpath import install_main_full
                install_main_full(st, full)
            else:
                st.main = st.port.install_pool(full, st.ctx.shard0())
            # replicas: clean ones are bitwise cache==main, delta==0;
            # the final link's captured dirty rows overlay that
            S = st.ctx.num_shards
            cache_host = np.zeros((S, st.cache_slots, st.value_length),
                                  dtype=full.dtype)
            delta_host = np.zeros_like(cache_host)
            if len(rep_k):
                in_cls = ab.key_class[rep_k] == cid
                ck, csh = rep_k[in_cls], rep_sh[in_cls]
                if len(ck):
                    cs = ab.cache_slot[csh, ck]
                    cache_host[csh, cs] = full[ab.owner[ck],
                                               ab.slot[ck]]
            rsh, rcs = final[f"rsh_{cid}"], final[f"rcs_{cid}"]
            if len(rsh):
                cache_host[rsh, rcs] = final[f"rcache_{cid}"]
                delta_host[rsh, rcs] = final[f"rdelta_{cid}"]
            sh0 = st.ctx.shard0()
            st.cache = st.port.install_pool(cache_host, sh0)
            st.delta = st.port.install_pool(delta_host, sh0)

        for cid in range(len(server.stores)):
            class_keys = np.nonzero(ab.key_class == cid)[0]
            _rebuild_alloc(ab.main_alloc[cid],
                           ab.owner[class_keys], ab.slot[class_keys])
            used_by_shard = [
                ab.cache_slot[s, class_keys]
                for s in range(server.num_shards)]
            _rebuild_cache_alloc(ab.cache_alloc[cid], used_by_shard)

        server.sync.replica_clear()
        shards, keys = np.nonzero(ab.cache_slot >= 0)
        server.sync.replica_add(keys.astype(np.int64),
                                shards.astype(np.int32))
        for st in server.stores:
            st.reset_write_tracking()
    if server.prefetch is not None:
        server.prefetch.invalidate_all()
    server.block()
