"""Fault-injection plane + robustness layers (ISSUE 10 tentpole).

Three coupled layers over one seeded injection substrate
(docs/failure_handling.md has the operator guide):

  - `inject`  — `FaultPlane`: deterministic, seeded, named injection
    points threaded through the executor, sync rounds, tier promotion
    commits, serve drains, and checkpoint I/O. Off by default with
    zero hot-path cost (`Server.fault` is None; one `is None` check
    per instrumented site, zero `fault.*` registry names).
  - `policy`  — `RetryPolicy`: transient-vs-fatal classification with
    bounded retry + exponential backoff for executor programs; the
    watchdog half (`AsyncExecutor.wedged_streams`) marks a stream
    wedged past `--sys.fault.watchdog_s` and escalates into serve
    readiness.
  - `ckpt`    — incremental dirty-slot checkpoint chains
    (`IncrementalCheckpointer` / `restore_chain`): base + deltas of
    only the slots whose write epoch advanced, atomic writes,
    per-link sha256 and a chained manifest; restore verifies the
    whole chain before touching the server and serves DEGRADED
    (`ServeDegradedError` sheds) while it applies — never a torn or
    half-restored read.

Drilled end to end by scripts/fault_drill_check.py (run_tests.sh) and
measured by bench.py's `fault` phase (recovery_s, incremental-vs-full
bytes).
"""
from .ckpt import (CheckpointChainError,  # noqa: F401
                   CheckpointCorruptError, IncrementalCheckpointer,
                   restore_chain)
from .inject import (FatalInjectedFault, FaultPlane,  # noqa: F401
                     InjectedFault, TransientFaultError,
                     parse_fault_spec)
from .policy import RetryPolicy  # noqa: F401
