"""Deterministic, seeded fault injection (ISSUE 10 tentpole).

The plane is a registry of NAMED injection points threaded through the
hot control paths — executor program dispatch/completion
(exec/executor.py), background sync rounds (core/kv.py tick), tier
promotion commits (tier/promote.py), serve drains (serve/batcher.py),
and checkpoint I/O (utils/checkpoint.py + fault/ckpt.py). Each point
fires with a configured probability and raises `InjectedFault` (a
`TransientFaultError` — the classification the executor's RetryPolicy
retries) or `FatalInjectedFault` (never retried: the
completion-side point, where the program's side effects already
happened and a retry would double-execute them).

Off by default with ZERO hot-path cost (the r7 skip-wrapper
discipline): `Server.fault` is None unless `--sys.fault.spec` is set,
every instrumented site is `if srv.fault is not None: srv.fault.fire(
"point")` — one attribute + `is None` check — and the registry holds
zero `fault.*` metric names (pinned by scripts/metrics_overhead_check).

Determinism: each point owns its own `random.Random` seeded from
(`--sys.fault.seed`, crc32(point name)), so the Nth evaluation of a
given point draws the same number regardless of how OTHER points
interleave across threads — a seeded drill (scripts/
fault_drill_check.py) fires the same faults run over run as long as
each point is evaluated the same number of times.

Spec grammar (`--sys.fault.spec`): comma/semicolon-separated
`point=probability` pairs, e.g.

    --sys.fault.spec "sync.round=0.2,serve.drain=0.1,tier.promote=0.05"

Probabilities are in [0, 1]; unknown point names are allowed (points
are registered by the sites that fire them, so a spec may name a point
the current configuration never reaches — it simply never fires).

Injection points wired in this tree:

    exec.dispatch   before an executor program runs (retry-safe)
    exec.complete   after a program ran, before completion (FATAL —
                    the work happened; only the completion is lost)
    sync.round      background sync tick, before run_round
    serve.drain     serve dispatcher drain, before any request is
                    claimed (retry-safe: no waiter is failed)
    tier.promote    tier promotion commit, before ensure_hot_rows
    ckpt.save       checkpoint save entry (atomic tmp+rename writes
                    make a failed save invisible)
    ckpt.restore    checkpoint restore entry, before any server
                    mutation (a failed restore leaves the live server
                    untouched)
    net.send        NetPort outbound frame dropped at the sender
                    (non-raising `draw`: the drop IS the fault; the
                    port's retransmit machinery absorbs it)
    net.recv        inbound frame dropped at the receiver (draw)
    net.delay       outbound frame delayed ~5 ms (draw)
    net.dup         outbound frame delivered twice — exercises the
                    receiver's at-most-once rid dedup cache (draw)
    net.partition   the (src, dst) link eats this frame (draw)
"""
from __future__ import annotations

import threading
import zlib
from typing import Dict, Tuple


class TransientFaultError(RuntimeError):
    """Base classification for failures the executor's RetryPolicy may
    retry (fault/policy.py): the operation performed no durable side
    effects before raising, so re-running it is safe. Injected faults
    subclass this; deployments may raise it from their own transient
    paths (a flaky remote read, a lease that expired mid-acquire)."""


class InjectedFault(TransientFaultError):
    """A seeded injection fired at a named point (retryable)."""


class FatalInjectedFault(RuntimeError):
    """A seeded injection at a point where the guarded work ALREADY
    happened (e.g. `exec.complete`) — retrying would double-execute,
    so this is deliberately NOT a TransientFaultError."""


def parse_fault_spec(spec: str) -> Dict[str, float]:
    """`point=prob` pairs, comma/semicolon separated. Raises ValueError
    on malformed entries or probabilities outside [0, 1]."""
    out: Dict[str, float] = {}
    for raw in spec.replace(";", ",").split(","):
        item = raw.strip()
        if not item:
            continue
        name, sep, val = item.partition("=")
        name = name.strip()
        if not sep or not name:
            raise ValueError(
                f"--sys.fault.spec entry {item!r} is not "
                f"'point=probability'")
        try:
            p = float(val)
        except ValueError:
            raise ValueError(
                f"--sys.fault.spec probability {val!r} for point "
                f"{name!r} is not a number") from None
        if not 0.0 <= p <= 1.0:
            raise ValueError(
                f"--sys.fault.spec probability {p!r} for point "
                f"{name!r} must be in [0, 1]")
        out[name] = p
    return out


class _Point:
    """One injection point's seeded RNG + accounting (own lock so
    firing threads of different points never contend)."""

    __slots__ = ("name", "prob", "rng", "lock", "evals", "fired")

    def __init__(self, name: str, prob: float, seed: int):
        import random
        self.name = name
        self.prob = prob
        # per-point stream: the Nth draw of THIS point is deterministic
        # regardless of how other points interleave across threads
        # (crc32, not hash(): str hashes are salted per process)
        self.rng = random.Random(
            (int(seed) << 32) ^ zlib.crc32(name.encode()))
        self.lock = threading.Lock()
        self.evals = 0
        self.fired = 0


class FaultPlane:
    """Seeded probability-per-point injection (see module docstring).
    Constructed by Server only when `--sys.fault.spec` is non-empty;
    every instrumented site guards with `if fault is not None`."""

    def __init__(self, spec: str, seed: int = 0, registry=None):
        self.seed = int(seed)
        self._points: Dict[str, _Point] = {
            name: _Point(name, p, seed)
            for name, p in parse_fault_spec(spec).items()}
        # registry metrics exist ONLY when a plane exists: with
        # injection off the registry must hold zero fault.* names
        # (metrics_overhead_check.py pins this)
        from ..obs.metrics import Counter
        if registry is not None and registry.enabled:
            self._c_fired = registry.counter("fault.injections_total")
            self._c_by_point = {
                name: registry.counter(f"fault.injections.{name}")
                for name in self._points}
            # retries performed by SELF-HEALING loops (the background
            # sync tick, the periodic checkpointer) that catch their
            # own failures instead of riding the executor policy
            self.c_loop_retries = registry.counter(
                "fault.loop_retries_total")
        else:
            self._c_fired = Counter("fault.injections_total")
            self._c_by_point = {name: Counter(f"fault.injections.{name}")
                                for name in self._points}
            self.c_loop_retries = Counter("fault.loop_retries_total")

    def fire(self, point: str, transient: bool = True) -> None:
        """Evaluate `point`: raise with its configured probability,
        no-op otherwise (or when the point is not in the spec —
        a dict get, so unconfigured points cost nothing measurable).
        `transient=False` raises `FatalInjectedFault` instead (the
        completion-side points, where a retry would double-execute)."""
        pt = self._points.get(point)
        if pt is None or pt.prob <= 0.0:
            return
        with pt.lock:
            pt.evals += 1
            hit = pt.rng.random() < pt.prob
            if hit:
                pt.fired += 1
                n = pt.fired
        if hit:
            self._c_fired.inc()
            self._c_by_point[point].inc()
            cls = InjectedFault if transient else FatalInjectedFault
            raise cls(
                f"injected fault #{n} at {point!r} "
                f"(--sys.fault.spec p={pt.prob:g}, seed={self.seed})")

    def draw(self, point: str) -> bool:
        """Non-raising evaluation for points where the fault is an
        ACTION the caller performs (drop/duplicate/delay a network
        frame, net/loopback.py) rather than an exception to unwind.
        Same seeded per-point stream and accounting as fire()."""
        pt = self._points.get(point)
        if pt is None or pt.prob <= 0.0:
            return False
        with pt.lock:
            pt.evals += 1
            hit = pt.rng.random() < pt.prob
            if hit:
                pt.fired += 1
        if hit:
            self._c_fired.inc()
            self._c_by_point[point].inc()
        return hit

    def counts(self, point: str) -> Tuple[int, int]:
        """(evaluations, fired) for one point — 0s when unconfigured."""
        pt = self._points.get(point)
        return (pt.evals, pt.fired) if pt is not None else (0, 0)

    def stats(self) -> Dict:
        """The `fault` snapshot section's injection half (the executor
        contributes retries / backoff / wedge flips)."""
        out: Dict = {"seed": self.seed,
                     "injections_fired": int(self._c_fired.value),
                     "loop_retries": int(self.c_loop_retries.value)}
        out["points"] = {
            name: {"prob": pt.prob, "evals": pt.evals,
                   "fired": pt.fired}
            for name, pt in self._points.items()}
        return out
