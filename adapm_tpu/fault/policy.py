"""Executor error policy (ISSUE 10 tentpole, layer 2): transient-vs-
fatal classification with bounded retry + exponential backoff.

A failed executor program used to have exactly one outcome: the error
reached the completion's waiters and the program was gone — a transient
hiccup in a self-rescheduling background program (the sync tick, a
serve drain, a tier commit) silently killed that subsystem's loop. The
RetryPolicy gives every stream a second chance with a bound:

  - **classification**: `classify(exc)` decides transient vs fatal.
    The default classifies exactly `TransientFaultError` (and its
    `InjectedFault` subclass) as transient — everything else is fatal
    and surfaces unchanged, so with no injection configured and no
    caller raising TransientFaultError the policy is INERT and the
    executor behaves byte-for-byte as before.
  - **bounded retry + backoff**: a transient failure re-queues the SAME
    program at the head of its stream (FIFO order preserved — the
    stream stays ordered) with `not_before = now + backoff`, where
    backoff doubles per attempt from `--sys.fault.backoff_ms`, capped.
    The completion stays open until the final outcome, so waiters see
    one result, never an intermediate failure.
  - **budget**: after `--sys.fault.retries` retries the error surfaces
    exactly as an unpolicied failure would (logged, completion error).

The watchdog half of the error policy lives in the executor itself
(`AsyncExecutor.wedged_streams`): a program busy past
`--sys.fault.watchdog_s` marks its stream WEDGED — readiness
(serve/health.py) folds that in, so a stuck program flips the traffic
signal instead of hanging probes behind it.
"""
from __future__ import annotations

from typing import Callable, Optional

from ..obs.metrics import Counter
from .inject import TransientFaultError


def _default_classify(exc: BaseException) -> bool:
    return isinstance(exc, TransientFaultError)


class RetryPolicy:
    """Bounded-retry/backoff policy for executor programs (one per
    executor, applied to every stream; see module docstring). Counters
    are standalone (not registry names): they surface through the
    `fault` snapshot section only when a FaultPlane is attached, and
    `scripts/metrics_overhead_check.py` pins that the registry holds
    zero fault.* names by default."""

    def __init__(self, max_retries: int = 3,
                 backoff_base_s: float = 0.01,
                 backoff_max_s: float = 2.0,
                 classify: Optional[Callable[[BaseException], bool]]
                 = None):
        if max_retries < 0:
            raise ValueError(f"max_retries must be >= 0 "
                             f"(got {max_retries})")
        if backoff_base_s < 0 or backoff_max_s < 0:
            raise ValueError("backoff bounds must be >= 0")
        self.max_retries = int(max_retries)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.classify = classify or _default_classify
        self.c_retries = Counter("fault.retries_total")
        self.c_backoff_s = Counter("fault.backoff_s_total", unit="s")

    def backoff_s(self, attempt: int) -> float:
        """Delay before retry number `attempt` (1-based): exponential
        from the base, capped."""
        return min(self.backoff_max_s,
                   self.backoff_base_s * (2.0 ** (attempt - 1)))

    def stats(self) -> dict:
        return {"retries": int(self.c_retries.value),
                "backoff_s": float(self.c_backoff_s.value),
                "max_retries": self.max_retries}
