"""System configuration.

One dataclass replaces the reference's three config tiers (env vars + boost
program_options `--sys.*` + compile-time defines; SURVEY.md §5 "Config / flag
system"). `SystemOptions.add_arguments`/`from_args` provide the `--sys.*` CLI
surface so apps keep the reference's flag names.
"""
from __future__ import annotations

import argparse
import dataclasses
from typing import Optional

from .base import MgmtTechniques


def parse_class_targets(base_ms: float, spec: str,
                        flag: str = "--sys.serve.slo_ms"):
    """Parse a per-priority-class target spec — comma-separated
    "prio=ms" pairs, e.g. "1=10,0=50" — into {priority: target_ms}.
    Empty spec -> {} (the byte-identical no-override path). Raises
    ValueError on a malformed pair, a negative priority, a non-positive
    target, a duplicate class, or overrides without a base target
    (ISSUE 20 satellite; the flag itself carries "base,prio=ms,...",
    split by `from_args`)."""
    out = {}
    if not spec:
        return out
    if base_ms <= 0:
        raise ValueError(
            f"{flag}: per-class overrides ({spec!r}) require a base "
            f"target > 0 — classes without an override fall back to "
            f"the base, which must therefore exist")
    for part in spec.split(","):
        part = part.strip()
        cls_s, eq, val_s = part.partition("=")
        if not eq or not cls_s or not val_s:
            raise ValueError(
                f"{flag}: malformed per-class override {part!r} "
                f"(expected 'priority=target_ms', e.g. '1=10')")
        try:
            cls = int(cls_s)
            val = float(val_s)
        except ValueError:
            raise ValueError(
                f"{flag}: malformed per-class override {part!r} "
                f"(priority must be an int, target a float)") from None
        if cls < 0:
            raise ValueError(
                f"{flag}: priority class must be >= 0 (got {cls})")
        if val <= 0:
            raise ValueError(
                f"{flag}: per-class target must be > 0 ms "
                f"(got {val:g} for class {cls})")
        if cls in out:
            raise ValueError(
                f"{flag}: duplicate override for class {cls}")
        out[cls] = val
    return out


def _slo_spec(text: str) -> str:
    """argparse type for SLO flags that accept "base_ms" or
    "base_ms,prio=ms,...": syntax-checks at parse time (range and
    consistency checks live in validate_serve) and returns the raw
    string for from_args to split."""
    head, _, rest = text.partition(",")
    try:
        float(head)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"expected 'target_ms' or 'target_ms,prio=ms,...' "
            f"(got {text!r})") from None
    for part in rest.split(",") if rest else ():
        cls_s, eq, val_s = part.strip().partition("=")
        ok = bool(eq)
        if ok:
            try:
                int(cls_s)
                float(val_s)
            except ValueError:
                ok = False
        if not ok:
            raise argparse.ArgumentTypeError(
                f"malformed per-class override {part!r} in {text!r} "
                f"(expected 'priority=target_ms')")
    return text


def _split_slo_spec(text: str):
    """"25,1=10" -> (25.0, "1=10"); "25" -> (25.0, "")."""
    head, _, rest = str(text).partition(",")
    return float(head), rest


@dataclasses.dataclass
class SystemOptions:
    """Knobs for the parameter manager (reference coloc_kv_server.h:205-222,
    sync_manager.h:805-814, sampling.h:163-172)."""

    # -- management techniques (sys.techniques)
    techniques: MgmtTechniques = MgmtTechniques.ALL
    # -- channels (sys.channels): number of independent sync streams.
    #    On TPU the sync program is a single fused collective per round;
    #    channels partition
    #    keys so each round can sync a subset (bounding per-round payload).
    channels: int = 4
    # -- location caches (sys.location_caches): keep per-host stale owner hints
    location_caches: bool = True
    # -- intent action timing (sys.time_intent_actions): ActionTimer on/off
    time_intent_actions: bool = True

    # -- heartbeat (reference PS_HEARTBEAT_INTERVAL, src/van.cc:515-527;
    #    0 = off, matching the reference's default)
    heartbeat_s: float = 0.0

    # -- cross-process channel concurrency (reference --sys.zmq_threads,
    #    coloc_kv_server.h:208): read-executor width of the GlobalPM;
    #    write executors get half, floored at 2 (a write task may wait on
    #    an earlier write future, so one thread could self-block)
    dcn_threads: int = 8

    # -- transport plane (sys.net.*; adapm_tpu/net, docs/NETWORK.md):
    #    backend selects the wire under GlobalPM — "auto" = the legacy
    #    DCN channel (byte-identical pre-NetPort behavior), "tcp" = the
    #    framed TcpNetPort, "loopback" = the in-process fabric (tests/
    #    storms; normally injected via Server(net_node=...)); queue
    #    bounds the loopback per-peer inbox; timeout_ms is the per-
    #    attempt request timeout; heartbeat_ms paces membership beats
    net_backend: str = "auto"
    net_queue: int = 64
    net_timeout_ms: float = 5000.0
    net_heartbeat_ms: float = 100.0

    # -- sync throttling (sys.sync.*)
    sync_max_per_sec: float = 1000.0
    sync_pause_ms: float = 0.0
    sync_threshold: float = 0.0      # drop deltas with max-abs below threshold
    # dirty-delta filtering (core/sync.py sync_channel): rounds ship only
    # replicas with an unshipped write or a stale base (store.py write
    # epochs) — exact, so a filtered round reads bit-identically to a
    # full one. Default on; 0 is the kill switch (re-sync every
    # intent-live replica every round, the pre-PR-3 behavior).
    sync_dirty_only: bool = True
    # delta compression for sync rounds (ISSUE 8; store.py
    # _sync_replicas_compressed, docs/MEMORY.md contract): periodic
    # rounds ship deltas in fp16 (half the bytes) or int8 + per-key
    # fp16 scale (~quarter) with per-key error feedback — the
    # quantization remainder parks in the replica's delta row and
    # rides the next round, keeping the main copy's long-run sum
    # unbiased; drop/quiesce flushes stay exact. "off" (default) is
    # bit-identical to pre-compression behavior. Requires the dirty
    # filter: compression marks synced replicas clean with a sub-grid
    # residual parked, a bookkeeping step the full-resync path has no
    # epoch state for (validate_serve rejects the combination).
    sync_compress: str = "off"

    # -- collective sync data plane (parallel/collective.py): replica
    #    delta ship + fresh-value refresh ride device all-to-all exchanges
    #    at WaitSync/quiesce points instead of per-destination DCN RPC
    #    (SURVEY's ICI mapping; off = the reference-parity host channel)
    collective_sync: bool = False
    collective_bucket: int = 1024    # rows per peer per exchange iteration
    # bounded staleness for collective mode: every process joins a BSP
    # exchange each time its workers' min clock crosses a multiple of K
    # (checked in run_round), so a replica observes remote pushes within
    # K clocks — the reference's continuously-running sync loop analog
    # (sync_manager.h:452-520). 0 = exchanges only at WaitSync/quiesce.
    # Requires clock-advancing training loops on EVERY process (the
    # co-located worker+server model); skewed per-process batch counts
    # are absorbed by the quiesce-time flag loop.
    collective_cadence: int = 0

    # -- optimistic routing (reference per-key lock array,
    #    handle.h:1069-1083): worker Pull/Push route + stage OUTSIDE the
    #    server lock against a topology_version snapshot, then revalidate
    #    under the lock and re-plan on a miss. Shrinks the serialized
    #    critical section to the device dispatch itself so N worker
    #    threads scale on multi-core hosts; off = route under the lock.
    optimistic_routing: bool = True

    # -- prefetch pipeline (sys.prefetch.*; core/intent.py
    #    PrefetchScheduler): consume Worker.intent declarations on a
    #    background thread — delegated planner rounds, staged device
    #    table mirrors, and pre-gathered pull buffers — so the training
    #    thread's per-step critical path is the device dispatch alone.
    #    Default on; --sys.prefetch 0 is the kill switch (everything
    #    then runs inline, the pre-r6 behavior).
    prefetch: bool = True
    # staged pull batches kept per worker (oldest evicted beyond this)
    prefetch_max_batches: int = 4
    # device rows the staging pool may hold per length class (bounds the
    # HBM the pipeline can pin; 65536 rows of 512 f32 = 128 MiB)
    prefetch_staging_rows: int = 65536
    # when to pre-gather pull buffers: "auto" stages only for workers
    # that use the Pull API (fused-runner loops never pull — staging
    # gathers for them is wasted device work), "always"/"off" force it
    prefetch_pull: str = "auto"
    # routing-plan cache entries (core/intent.py PlanCache; 0 = off)
    plan_cache_entries: int = 64

    # -- ActionTimer (sys.timing.*; reference sync_manager.h:62-158)
    timing_alpha: float = 0.1
    timing_quantile: float = 0.9999
    timing_rounds_lookahead: float = 2.0

    # -- tiered parameter storage (sys.tier.*; adapm_tpu/tier,
    #    docs/MEMORY.md): split each server's owned keys between a
    #    capacity-bounded device-hot main pool and a host-resident cold
    #    store, with intent-driven promotion and a background demotion
    #    worker. Decouples model size from HBM: the device main pool
    #    holds --sys.tier.hot_rows rows per shard per length class
    #    instead of the whole table. Reads/writes of cold rows are
    #    served correctly-but-slowly through the cold path and remain
    #    bit-identical to the untiered store. Default off.
    tier: bool = False
    # device-resident main rows per shard per length class
    tier_hot_rows: int = 65536
    # cold-store at-rest format (ISSUE 8; tier/quant.py): fp32 keeps
    # the bit-identity pin; fp16 halves host bytes/row (exact where
    # the value is fp16-representable); int8 + per-row scale quarters
    # them (exact on the row's int grid) — both otherwise follow the
    # error-compensated contract in docs/MEMORY.md (demote parks the
    # sub-grid remainder host-side; the next promote folds it back)
    tier_cold_dtype: str = "fp32"
    # pin keys inside an active Intent window hot for the window
    tier_pin_intent: bool = True
    # demotion batch size / per-shard free-row headroom the maintenance
    # worker maintains (a promotion that finds headroom never pays a
    # victim readback on the caller's path)
    tier_demote_batch: int = 1024

    # -- unified async executor (sys.exec.*; adapm_tpu/exec,
    #    docs/EXECUTOR.md): the one ordered-stream dispatch plane under
    #    sync rounds, prefetch staging, tier maintenance, serve
    #    batching, and fused steps. Worker-pool width bounds how many
    #    streams make progress concurrently (background subsystems
    #    share it; the training thread dispatches inline).
    exec_workers: int = 4
    # serialized fallback: one worker thread, so background programs
    # execute strictly one at a time (oldest submission first) with
    # zero cross-stream overlap; streams keep their identity, so
    # per-subsystem drains and delayed programs still behave. The
    # baseline the bench `exec` phase and scripts/exec_overlap_check.py
    # compare the overlapped default against, and the conservative
    # escape hatch.
    exec_single_stream: bool = False

    # -- episodic execution (sys.episode.*; adapm_tpu/device/episode.py,
    #    ISSUE 14): default step-batches per episode for EpisodicRunner
    #    — the window whose union working set is pinned device-hot as a
    #    unit while the next window's samples/gathers/wire rows stage on
    #    the `episode` stream. Larger episodes amortize prep over more
    #    steps but need hot capacity for two windows to overlap fully.
    episode_batches: int = 8

    # -- store geometry
    cache_slots_per_shard: int = 0   # 0 = auto (num_keys // num_shards)
    remote_bucket_min: int = 8       # min padded size of the remote op bucket
    # main-pool headroom factor for relocations (slots per shard =
    # keys_per_shard * over_alloc); at memory-bound scale (e.g. a
    # Wikidata5M-sized table filling most of HBM) set close to 1.0
    main_over_alloc: float = 1.25

    # -- observability (sys.stats.*, sys.trace.*, sys.metrics*; obs/)
    stats_out: Optional[str] = None
    trace_keys: Optional[str] = None
    # per-key access counters (PS_LOCALITY_STATS)
    locality_stats: bool = False
    sync_report_s: float = 10.0      # periodic sync-thread report (0 = off)
    # unified metrics registry (docs/OBSERVABILITY.md): counters/gauges/
    # histograms behind Server.metrics_snapshot(). Default ON (<2%
    # overhead budget on the bench probe phase; guarded by
    # scripts/metrics_overhead_check.py); --sys.metrics 0 disables the
    # registry entirely (null metrics, empty snapshot, no reporter import)
    metrics: bool = True
    # periodic one-line metrics report every N seconds (0 = off; the
    # reporter module is only imported when > 0 AND metrics is on)
    metrics_report_s: float = 0.0
    # span tracing: begin/end events for named phases, exported as
    # Chrome trace-event JSON (Perfetto-loadable) at shutdown. Default
    # off — spans bracket the hot Pull/Push path.
    trace_spans: bool = False
    # trace output path (default: <stats_out or cwd>/spans.<rank>.trace.json)
    trace_spans_out: Optional[str] = None
    # faulthandler crash dumps with a per-rank file (+ last-open-span
    # breadcrumb when trace_spans is on, + the executor flight-recorder
    # ring file) — attributes this image's intermittent XLA-CPU hard
    # aborts (CHANGES.md r6). Default on.
    crash_dumps: bool = True
    # request-flight tracing (obs/flight.py, docs/OBSERVABILITY.md):
    # per-request trace ids minted at ServeSession.lookup /
    # Worker.pull|push, carried through admission -> batch -> executor
    # program -> reply and exported as Perfetto FLOW events, plus the
    # queue/batch_wait/dispatch/device breakdown histograms and the
    # push-to-servable freshness probe. Default off — same skip-wrapper
    # discipline as trace_spans: off costs one `is None` check per op
    # and registers zero flight.* metrics.
    trace_flight: bool = False
    # flight trace output path
    # (default: <stats_out or cwd>/flight.<rank>.trace.json)
    trace_flight_out: Optional[str] = None
    # freshness-probe table bound (ISSUE 20 satellite): how many
    # in-flight push-to-servable probes the FreshnessProbe may hold
    # before evicting the oldest unresolved one. The pre-r22 hardcoded
    # bound (256) was fine for a spot gauge but too noisy as an SLO
    # input — at-bound eviction silently drops the probes a controller
    # steers by. >= 8; raise further for high-fanout streams.
    flight_freshness_samples: int = 1024
    # workload trace capture (ISSUE 15; obs/wtrace.py, docs/REPLAY.md):
    # record the semantic op stream — pull/push/set key batches, intent
    # windows, clock advances, serve lookups with tenant/priority/
    # deadline, PrepareSample/PullSample, and relocation/sync/promotion
    # decisions as they landed — into a versioned, checksummed .wtrace
    # file at this path, replayable offline by adapm_tpu/replay/.
    # Default off (None): Server.wtrace is None, every instrumented
    # site pays one `is None` check, zero wtrace.* registry names (the
    # r7 skip-wrapper discipline; scripts/metrics_overhead_check.py).
    trace_workload: Optional[str] = None
    # per-event exact-key budget: batches up to this record their exact
    # keys; larger batches record an evenly-strided sample + the true
    # count, loudly (wtrace.sampled_batches_total)
    trace_workload_keys: int = 4096
    # decision telemetry capture (ISSUE 17; obs/decisions.py,
    # docs/OBSERVABILITY.md "Explain a decision"): record every
    # adaptive decision — relocate-vs-replicate, tier promote/demote
    # with the anti-thrash verdict, dirty-sync ship/hold, SLO window
    # moves, prefetch stage/skip, cost-table overrides — with the
    # feature vector visible at decision time and a bounded follow-up
    # outcome window, into a versioned, checksummed .dtrace file at
    # this path (replay/dataset.py exports the labeled join). Default
    # off (None): Server.decisions is None, every instrumented site
    # pays one `is None` check, zero decision.* registry names (the r7
    # skip-wrapper discipline; scripts/metrics_overhead_check.py).
    trace_decisions: Optional[str] = None
    # outcome-attribution follow-up window: a decision's outcome probe
    # resolves after this many same-plane decisions (or 8x any-plane
    # events, or the recorder's wall deadline, whichever first); >= 1
    trace_decisions_window: int = 8
    # span-event buffer bound (obs/spans.py; ISSUE 17 satellite): spans
    # beyond it are counted loudly in spans.dropped instead of stored.
    # Validated >= 1000 — a tiny bound would silently gut every trace
    trace_spans_max_events: int = 1_000_000

    # -- online serving plane (sys.serve.*; adapm_tpu/serve,
    #    docs/SERVING.md). Knob ranges are validated by validate_serve()
    #    at parse time AND at ServePlane construction — bad combinations
    #    fail loudly instead of mis-serving.
    # requests coalesced into one fused lookup gather (>= 1)
    serve_max_batch: int = 64
    # micro-batch window: how long the dispatcher lingers after the
    # first request to coalesce more (>= 0; 0 = dispatch immediately
    # with whatever is already queued)
    serve_max_wait_us: int = 200
    # admission queue bound (> 0): submissions beyond this are rejected
    # with ServeOverloadError (backpressure, never an unbounded queue)
    serve_queue: int = 1024
    # default per-lookup deadline in ms (0 = none); expired requests
    # are shed loudly (DeadlineExceededError), never parked
    serve_deadline_ms: float = 0.0
    # tail-latency SLO target in ms (0 = off, the default). When set, a
    # closed-loop controller (obs/slo.py) observes the serve P99 from
    # the latency histogram and adapts the effective max_wait_us —
    # bounded, with hysteresis — so tails track the target instead of
    # the hand-tuned static window. When unset, serve behavior is
    # IDENTICAL to the static-knob path (no controller exists).
    # Requires --sys.metrics (the controller reads the histogram).
    # The CLI flag also accepts per-priority-class overrides:
    # "25,1=10,0=50" sets the base target to 25 ms, class 1 (gold) to
    # 10 ms, class 0 (bronze) to 50 ms — parsed into serve_slo_class
    # below.
    serve_slo_ms: float = 0.0
    # per-priority-class SLO overrides (ISSUE 20 satellite; first
    # slice of ROADMAP item 4): "prio=ms" pairs, comma-separated
    # ("1=10,0=50"). With any override set the SLO controller keeps a
    # per-class effective batch window (batcher.class_wait_us) and
    # walks each class's window against ITS target from per-class
    # windowed P99s; empty (the default) leaves the single-window path
    # byte-identical to pre-r22. Requires serve_slo_ms > 0.
    serve_slo_class: str = ""
    # dispatcher drains (ISSUE 9 tentpole b; serve/batcher.py): N
    # admission lanes, each drained by its own executor stream
    # (`serve`, `serve.1`, ...), so a long-row length class's gather no
    # longer head-of-line-blocks short ones. Lanes are keyed by length
    # class on multi-class servers, round-robin otherwise. 1 (the
    # default) is the pre-PR single-consumer path, bit-identical.
    serve_dispatchers: int = 1
    # read-only serve replica (ISSUE 9 tentpole a; serve/replica.py):
    # rows in the epoch-versioned snapshot of the hottest locally-owned
    # rows. A lookup fully covered by a snapshot whose per-slot write
    # epochs (and topology_version) are unchanged gathers WITHOUT the
    # server lock — bit-identical to the locked path by construction;
    # any staleness signal falls back to the exact path. 0 (default) =
    # off: every lookup takes the pre-PR locked path.
    serve_replica_rows: int = 0
    # min interval between snapshot refreshes (the coalesced
    # `serve_refresh` executor program's throttle), in ms
    serve_replica_refresh_ms: float = 50.0
    # fused embedding-bag reads (ISSUE 16; serve/bags.py): serve
    # `ServeSession.lookup_bags` through ONE gather+pool device program
    # per (length class, pooling) — only the pooled vectors cross the
    # device boundary. Off = pool on the host after the flat union
    # gather; bit-identical either way (the knob moves WHERE the
    # reduction runs, never what it returns).
    serve_bags: bool = True

    # -- streaming plane (sys.stream.*; adapm_tpu/stream,
    #    docs/STREAMING.md): the PM as a continuously-trained online
    #    service — a micro-batching StreamTrainer turning click events
    #    into fused Push steps while ServeSessions read, plus a
    #    FreshnessSLO controller closing the loop on event-to-servable
    #    staleness. With NO stream knob set the Server holds no stream
    #    plane object and the registry holds zero stream.* names (the
    #    r7 skip-wrapper discipline; scripts/metrics_overhead_check.py
    #    pins it).
    # events per fused push micro-batch (the trainer's unit of work AND
    # its ack/checkpoint granularity — the acked-event cursor only
    # advances at batch boundaries). 0 (default) = no trainer support;
    # > 0 turns the stream plane on.
    stream_batch: int = 0
    # target ingest rate in events/s for the executor pump (0 =
    # unthrottled: each micro-batch is pushed as soon as the previous
    # one finishes). Requires stream_batch > 0.
    stream_rate: float = 0.0
    # event-to-servable freshness SLO target in ms (0 = off). When set,
    # a FreshnessSLO controller (stream/freshness.py) observes the
    # windowed P99 of flight.freshness_s and walks TWO levers — the
    # effective sync rate (sync.effective_max_per_sec above the static
    # --sys.sync.max_per_sec throttle) and the effective serve-replica
    # refresh window (ServeReplica.refresh_s below the static
    # --sys.serve.replica_refresh_ms) — with the obs/slo.py law:
    # multiplicative shrink/grow, deadband hysteresis, hard bounds,
    # bounded move log. Requires --sys.trace.flight (the freshness
    # probe is the sensor) and --sys.metrics. The CLI flag accepts the
    # same per-class override syntax as --sys.serve.slo_ms
    # ("400,1=200"): the controller steers to the TIGHTEST class
    # target (freshness is a write-path property shared by all
    # classes; docs/STREAMING.md).
    stream_freshness_slo_ms: float = 0.0
    # per-priority-class freshness overrides ("prio=ms" pairs; parsed
    # from the flag above). Requires stream_freshness_slo_ms > 0.
    stream_freshness_slo_class: str = ""

    # -- measured kernel cost table (sys.costs.*; adapm_tpu/ops/
    #    costs.py, docs/PERF.md "Kernel cost table"): per-(variant,
    #    length class, batch bucket, dtype, pooling) measured dispatch
    #    costs, persisted as versioned JSON at costs_table. The serve
    #    batcher consults it to pick fused vs host-pool bag dispatch;
    #    the episodic planner sizes prep windows from the per-class
    #    entries. No table (the default) = built-in preference order,
    #    no file I/O anywhere.
    costs_table: Optional[str] = None
    # measure-and-write at server construction (one-time calibration
    # pass over the cost probes; requires costs_table for the output)
    costs_calibrate: bool = False

    # -- fault injection + error policy (sys.fault.*; adapm_tpu/fault,
    #    docs/failure_handling.md). The spec is `point=prob` pairs
    #    (comma-separated), e.g. "sync.round=0.2,serve.drain=0.1" —
    #    empty (the default) means NO FaultPlane exists: every
    #    instrumented site pays one `is None` check and the registry
    #    holds zero fault.* names (scripts/metrics_overhead_check.py).
    fault_spec: str = ""
    # seed for the per-point injection RNGs (deterministic drills)
    fault_seed: int = 0
    # executor error policy: bounded retries for TRANSIENT program
    # failures (TransientFaultError classification — inert unless
    # something raises it), exponential backoff from backoff_ms capped
    # at backoff_max_ms
    fault_retries: int = 3
    fault_backoff_ms: float = 10.0
    fault_backoff_max_ms: float = 2000.0
    # per-program watchdog: an executor program busy past this marks
    # its stream WEDGED (readiness escalation; never an interrupt —
    # the waiters' own bounds fail-stop)
    fault_watchdog_s: float = 30.0

    # -- incremental checkpoints (sys.checkpoint.*; adapm_tpu/fault/
    #    ckpt.py): every N seconds a `ckpt`-stream executor program
    #    appends a dirty-slot delta (base first) to the chain at
    #    checkpoint.path. 0 (default) = no periodic checkpointing;
    #    explicit IncrementalCheckpointer use needs no knobs.
    ckpt_every_s: float = 0.0
    ckpt_path: Optional[str] = None

    # -- learned adaptive-policy plane (sys.policy.*; adapm_tpu/
    #    policy, docs/POLICY.md). policy_file names a trained artifact
    #    (`python -m adapm_tpu.policy.train`); each per-plane mode
    #    knob picks `heuristic` (default — the hand-tuned law, exactly
    #    as before) or `learned` (the trained regret scorer may VETO
    #    the heuristic's action through a value-preservation guard —
    #    a policy changes what/when, never values). policy_shadow
    #    scores the learned policy live WITHOUT applying it
    #    (policy.shadow_agree/disagree — the promotion runbook's A/B).
    #    No file (the default) means NO PolicyPlane exists: every hook
    #    site pays one `is None` check and the registry holds zero
    #    policy.* names (the r7 skip-wrapper discipline;
    #    scripts/metrics_overhead_check.py).
    policy_reloc: str = "heuristic"
    policy_tier: str = "heuristic"
    policy_sync: str = "heuristic"
    policy_serve: str = "heuristic"
    policy_file: Optional[str] = None
    policy_shadow: bool = False

    # -- runtime lock-order sentinel (sys.lint.*; adapm_tpu/lint/
    #    lockorder.py, docs/INVARIANTS.md): wrap the server lock, the
    #    dispatch gate, and the admission/registry locks in a recorder
    #    that raises LockOrderError on an acquisition-graph cycle or a
    #    gate-leaf violation (any lock taken while the gate is held).
    #    Default off — the Server then builds plain RLocks and the
    #    gate proxy pays one `is None` check per acquire (the r7
    #    skip-wrapper discipline). The tier-1 storm tests run with it
    #    on, so the dynamic checker validates exactly what the static
    #    adapm-lint rules (APM001/APM002) claim.
    lint_lockorder: bool = False

    # -- sampling (--sampling.*)
    sampling_scheme: str = "local"   # naive | preloc | pool | local
    sampling_reuse_factor: int = 32  # pool scheme
    sampling_pool_size: int = 0      # pool scheme; 0 = auto
    sampling_batch_size: int = 1024  # RNG batching
    sampling_with_replacement: bool = True

    def validate_serve(self) -> None:
        """Range/consistency checks for the --sys.serve.* surface
        (ISSUE 4 satellite). Raises ValueError; called by `from_args`
        (parse-time) and by `ServePlane.__init__` (hand-built options),
        so a bad knob fails loudly before it can mis-serve."""
        if self.serve_max_batch < 1:
            raise ValueError(
                f"--sys.serve.max_batch must be >= 1 "
                f"(got {self.serve_max_batch}): a coalescer that can "
                f"never form a batch serves nothing")
        if self.serve_max_wait_us < 0:
            raise ValueError(
                f"--sys.serve.max_wait_us must be >= 0 "
                f"(got {self.serve_max_wait_us})")
        if self.serve_queue < 1:
            raise ValueError(
                f"--sys.serve.queue must be > 0 (got {self.serve_queue}): "
                f"a zero-bound admission queue rejects every request")
        if self.serve_deadline_ms < 0:
            raise ValueError(
                f"--sys.serve.deadline_ms must be >= 0 "
                f"(got {self.serve_deadline_ms}; 0 = no deadline)")
        if self.serve_slo_ms < 0:
            raise ValueError(
                f"--sys.serve.slo_ms must be >= 0 "
                f"(got {self.serve_slo_ms}; 0 = no SLO controller)")
        if self.serve_slo_ms > 0 and not self.metrics:
            raise ValueError(
                "--sys.serve.slo_ms requires --sys.metrics: the SLO "
                "controller observes the serve P99 from the "
                "serve.latency_s histogram and is blind without it")
        # per-class override specs (ISSUE 20 satellite): parse loudly
        # here so a malformed "prio=ms" pair fails at parse time / plane
        # construction, never inside a controller tick
        parse_class_targets(self.serve_slo_ms, self.serve_slo_class,
                            flag="--sys.serve.slo_ms")
        parse_class_targets(self.stream_freshness_slo_ms,
                            self.stream_freshness_slo_class,
                            flag="--sys.stream.freshness_slo_ms")
        if self.flight_freshness_samples < 8:
            raise ValueError(
                f"--sys.flight.freshness_samples must be >= 8 "
                f"(got {self.flight_freshness_samples}): a smaller "
                f"probe table evicts nearly every probe at the bound — "
                f"a freshness gauge with no samples behind it")
        if self.stream_batch < 0:
            raise ValueError(
                f"--sys.stream.batch must be >= 0 "
                f"(got {self.stream_batch}; 0 = no stream trainer)")
        if self.stream_rate < 0:
            raise ValueError(
                f"--sys.stream.rate must be >= 0 "
                f"(got {self.stream_rate}; 0 = unthrottled)")
        if self.stream_rate > 0 and self.stream_batch < 1:
            raise ValueError(
                "--sys.stream.rate requires --sys.stream.batch >= 1: "
                "the rate throttles the trainer pump, which does not "
                "exist without a micro-batch size")
        if self.stream_freshness_slo_ms < 0:
            raise ValueError(
                f"--sys.stream.freshness_slo_ms must be >= 0 "
                f"(got {self.stream_freshness_slo_ms}; 0 = no "
                f"freshness controller)")
        if self.stream_freshness_slo_ms > 0 and not self.trace_flight:
            raise ValueError(
                "--sys.stream.freshness_slo_ms requires "
                "--sys.trace.flight: the freshness controller's sensor "
                "is the flight plane's push-to-servable probe "
                "(flight.freshness_s) and is blind without it")
        if self.stream_freshness_slo_ms > 0 and not self.metrics:
            raise ValueError(
                "--sys.stream.freshness_slo_ms requires --sys.metrics: "
                "the freshness controller reads the flight.freshness_s "
                "histogram through the registry")
        if self.net_backend not in ("auto", "dcn", "tcp", "loopback"):
            raise ValueError(
                f"--sys.net.backend must be one of auto/dcn/tcp/"
                f"loopback (got {self.net_backend!r})")
        if self.net_queue < 1:
            raise ValueError(
                f"--sys.net.queue must be >= 1 (got {self.net_queue}): "
                f"a zero-bound peer inbox delivers nothing")
        if self.net_timeout_ms <= 0:
            raise ValueError(
                f"--sys.net.timeout_ms must be > 0 "
                f"(got {self.net_timeout_ms})")
        if self.net_heartbeat_ms <= 0:
            raise ValueError(
                f"--sys.net.heartbeat_ms must be > 0 "
                f"(got {self.net_heartbeat_ms})")
        from .tier.quant import COLD_DTYPES, SYNC_COMPRESS_MODES
        if self.tier_cold_dtype not in COLD_DTYPES:
            raise ValueError(
                f"--sys.tier.cold_dtype must be one of "
                f"{'/'.join(COLD_DTYPES)} (got "
                f"{self.tier_cold_dtype!r})")
        if self.sync_compress not in SYNC_COMPRESS_MODES:
            raise ValueError(
                f"--sys.sync.compress must be one of "
                f"{'/'.join(SYNC_COMPRESS_MODES)} (got "
                f"{self.sync_compress!r})")
        if self.sync_compress != "off" and not self.sync_dirty_only:
            raise ValueError(
                "--sys.sync.compress requires --sys.sync.dirty_only 1: "
                "compressed rounds mark shipped replicas clean with a "
                "sub-grid residual parked in the delta row — the "
                "full-resync path re-ships every replica every round, "
                "re-quantizing residuals that can never clear (bytes "
                "and convergence both regress); turn the dirty filter "
                "back on or turn compression off")
        if self.sync_compress == "int8" and not self.metrics:
            raise ValueError(
                "--sys.sync.compress int8 requires --sys.metrics: the "
                "int8 error-feedback loop is only auditable through "
                "the sync.ef_residual_norm gauge — running a lossy "
                "grid a quarter of fp32 wide with no metrics-visible "
                "residual is a silent-quality-loss trap")
        if self.tier and self.tier_hot_rows < 8:
            raise ValueError(
                f"--sys.tier.hot_rows must be >= 8 (got "
                f"{self.tier_hot_rows}): a hot pool smaller than one "
                f"padded bucket cannot serve any gather from device")
        if self.tier and self.tier_demote_batch < 1:
            raise ValueError(
                f"--sys.tier.demote_batch must be >= 1 "
                f"(got {self.tier_demote_batch})")
        if self.episode_batches < 1:
            raise ValueError(
                f"--sys.episode.batches must be >= 1 "
                f"(got {self.episode_batches}): an episode must hold "
                f"at least one step batch")
        if self.exec_workers < 1:
            raise ValueError(
                f"--sys.exec.workers must be >= 1 "
                f"(got {self.exec_workers}): the executor's streams "
                f"need at least one worker to make progress")
        if self.serve_dispatchers < 1:
            raise ValueError(
                f"--sys.serve.dispatchers must be >= 1 "
                f"(got {self.serve_dispatchers}): the serve plane needs "
                f"at least one dispatcher drain")
        if self.serve_replica_rows < 0:
            raise ValueError(
                f"--sys.serve.replica_rows must be >= 0 "
                f"(got {self.serve_replica_rows}; 0 = no read-only "
                f"serve replica)")
        if self.serve_replica_refresh_ms <= 0:
            raise ValueError(
                f"--sys.serve.replica_refresh_ms must be > 0 "
                f"(got {self.serve_replica_refresh_ms}): a zero "
                f"refresh throttle would let every snapshot miss queue "
                f"an immediate refresh program")
        if self.costs_table is not None and not self.costs_table:
            raise ValueError(
                "--sys.costs.table needs a non-empty path for the "
                "cost-table JSON (omit the flag to run without a "
                "measured table)")
        if self.costs_calibrate and not self.costs_table:
            raise ValueError(
                "--sys.costs.calibrate requires --sys.costs.table: a "
                "calibration pass measures kernel costs and must have "
                "somewhere to persist them")
        if self.trace_workload_keys < 1:
            raise ValueError(
                f"--sys.trace.workload_keys must be >= 1 "
                f"(got {self.trace_workload_keys}): a zero key budget "
                f"would record no keys at all — an unreplayable trace")
        if self.trace_workload is not None and not self.trace_workload:
            raise ValueError(
                "--sys.trace.workload needs a non-empty path for the "
                ".wtrace file (omit the flag to disable capture)")
        if self.trace_decisions is not None and not self.trace_decisions:
            raise ValueError(
                "--sys.trace.decisions needs a non-empty path for the "
                ".dtrace file (omit the flag to disable capture)")
        if self.trace_decisions_window < 1:
            raise ValueError(
                f"--sys.trace.decisions_window must be >= 1 "
                f"(got {self.trace_decisions_window}): a zero window "
                f"would close every outcome probe before any follow-up "
                f"could land — attribution without evidence")
        if self.trace_spans_max_events < 1000:
            raise ValueError(
                f"--sys.trace.spans.max_events must be >= 1000 "
                f"(got {self.trace_spans_max_events}): a smaller bound "
                f"would drop nearly every span — an unreadable trace "
                f"masquerading as a cheap one")
        _policy_planes = (("reloc", self.policy_reloc),
                          ("tier", self.policy_tier),
                          ("sync", self.policy_sync),
                          ("serve", self.policy_serve))
        for _plane, _mode in _policy_planes:
            if _mode not in ("heuristic", "learned"):
                raise ValueError(
                    f"--sys.policy.{_plane} must be heuristic or "
                    f"learned (got {_mode!r})")
        if self.policy_file is not None and not self.policy_file:
            raise ValueError(
                "--sys.policy.file needs a non-empty path for the "
                "policy artifact (omit the flag to run pure "
                "heuristics)")
        if not self.policy_file:
            _learned = [p for p, m in _policy_planes if m == "learned"]
            if _learned:
                raise ValueError(
                    f"--sys.policy.{_learned[0]} learned requires "
                    f"--sys.policy.file: a learned mode without a "
                    f"trained artifact has nothing to consult")
            if self.policy_shadow:
                raise ValueError(
                    "--sys.policy.shadow requires --sys.policy.file: "
                    "shadow mode scores the TRAINED policy against "
                    "the live heuristic and is meaningless without "
                    "an artifact")
        if self.fault_spec:
            from .fault.inject import parse_fault_spec
            parse_fault_spec(self.fault_spec)  # raises ValueError on a
            # malformed point=prob entry or a probability outside [0,1]
        if self.fault_seed < 0:
            raise ValueError(
                f"--sys.fault.seed must be >= 0 (got {self.fault_seed})")
        if self.fault_retries < 0:
            raise ValueError(
                f"--sys.fault.retries must be >= 0 "
                f"(got {self.fault_retries}; 0 = no retries, failures "
                f"surface immediately)")
        if self.fault_backoff_ms < 0 or self.fault_backoff_max_ms < 0:
            raise ValueError(
                f"--sys.fault.backoff_ms bounds must be >= 0 (got "
                f"{self.fault_backoff_ms}/{self.fault_backoff_max_ms})")
        if self.fault_watchdog_s <= 0:
            raise ValueError(
                f"--sys.fault.watchdog_s must be > 0 "
                f"(got {self.fault_watchdog_s}): a zero watchdog would "
                f"flag every program wedged the instant it starts")
        if self.ckpt_every_s < 0:
            raise ValueError(
                f"--sys.checkpoint.every must be >= 0 "
                f"(got {self.ckpt_every_s}; 0 = no periodic "
                f"checkpointing)")
        if self.ckpt_every_s > 0 and not self.ckpt_path:
            raise ValueError(
                "--sys.checkpoint.every requires --sys.checkpoint.path: "
                "periodic incremental checkpoints need a chain "
                "directory to append to")
        if self.serve_queue < self.serve_max_batch:
            raise ValueError(
                f"inconsistent serve knobs: --sys.serve.queue "
                f"({self.serve_queue}) < --sys.serve.max_batch "
                f"({self.serve_max_batch}) — the admission queue could "
                f"never hold a full micro-batch, so the configured batch "
                f"size is unreachable; raise the queue bound or lower "
                f"max_batch")

    @staticmethod
    def add_arguments(parser: argparse.ArgumentParser) -> None:
        g = parser.add_argument_group("system")
        g.add_argument("--sys.techniques", dest="sys_techniques",
                       default="all",
                       choices=[t.value for t in MgmtTechniques])
        g.add_argument("--sys.channels", dest="sys_channels", type=int,
                       default=4)
        g.add_argument("--sys.location_caches", dest="sys_location_caches",
                       type=int, default=1)
        g.add_argument("--sys.time_intent_actions",
                       dest="sys_time_intent_actions",
                       type=int, default=1)
        g.add_argument("--sys.heartbeat", dest="sys_heartbeat",
                       type=float, default=0.0)
        g.add_argument("--sys.dcn_threads", dest="sys_dcn_threads",
                       type=int, default=8)
        g.add_argument("--sys.net.backend", dest="sys_net_backend",
                       type=str, default="auto")
        g.add_argument("--sys.net.queue", dest="sys_net_queue",
                       type=int, default=64)
        g.add_argument("--sys.net.timeout_ms", dest="sys_net_timeout_ms",
                       type=float, default=5000.0)
        g.add_argument("--sys.net.heartbeat_ms",
                       dest="sys_net_heartbeat_ms",
                       type=float, default=100.0)
        g.add_argument("--sys.sync.max_per_sec", dest="sys_sync_max_per_sec",
                       type=float, default=1000.0)
        g.add_argument("--sys.sync.pause", dest="sys_sync_pause", type=float,
                       default=0.0)
        g.add_argument("--sys.sync.threshold", dest="sys_sync_threshold",
                       type=float, default=0.0)
        g.add_argument("--sys.sync.dirty_only", dest="sys_sync_dirty_only",
                       type=int, default=1)
        g.add_argument("--sys.sync.compress", dest="sys_sync_compress",
                       default="off", choices=["off", "fp16", "int8"])
        g.add_argument("--sys.collective_sync", dest="sys_collective_sync",
                       type=int, default=0)
        g.add_argument("--sys.collective_bucket",
                       dest="sys_collective_bucket", type=int, default=1024)
        g.add_argument("--sys.collective_cadence",
                       dest="sys_collective_cadence", type=int, default=0)
        g.add_argument("--sys.main_over_alloc", dest="sys_main_over_alloc",
                       type=float, default=1.25)
        g.add_argument("--sys.optimistic_routing",
                       dest="sys_optimistic_routing", type=int, default=1)
        g.add_argument("--sys.prefetch", dest="sys_prefetch", type=int,
                       default=1)
        g.add_argument("--sys.prefetch.max_batches",
                       dest="sys_prefetch_max_batches", type=int, default=4)
        g.add_argument("--sys.prefetch.staging_rows",
                       dest="sys_prefetch_staging_rows", type=int,
                       default=65536)
        g.add_argument("--sys.prefetch.pull", dest="sys_prefetch_pull",
                       default="auto", choices=["auto", "always", "off"])
        g.add_argument("--sys.plan_cache", dest="sys_plan_cache", type=int,
                       default=64)
        g.add_argument("--sys.tier", dest="sys_tier", type=int, default=0)
        g.add_argument("--sys.tier.hot_rows", dest="sys_tier_hot_rows",
                       type=int, default=65536)
        g.add_argument("--sys.tier.cold_dtype",
                       dest="sys_tier_cold_dtype", default="fp32",
                       choices=["fp32", "fp16", "int8"])
        g.add_argument("--sys.tier.pin_intent",
                       dest="sys_tier_pin_intent", type=int, default=1)
        g.add_argument("--sys.tier.demote_batch",
                       dest="sys_tier_demote_batch", type=int,
                       default=1024)
        g.add_argument("--sys.exec.workers", dest="sys_exec_workers",
                       type=int, default=4)
        g.add_argument("--sys.exec.single_stream",
                       dest="sys_exec_single_stream", type=int,
                       default=0)
        g.add_argument("--sys.episode.batches",
                       dest="sys_episode_batches", type=int, default=8)
        g.add_argument("--sys.stats.out", dest="sys_stats_out", default=None)
        g.add_argument("--sys.trace.keys", dest="sys_trace_keys", default=None)
        g.add_argument("--sys.stats.locality", dest="sys_stats_locality",
                       action="store_true")
        g.add_argument("--sys.sync.report", dest="sys_sync_report",
                       type=float, default=10.0)
        g.add_argument("--sys.metrics", dest="sys_metrics", type=int,
                       default=1)
        g.add_argument("--sys.metrics.report", dest="sys_metrics_report",
                       type=float, default=0.0)
        g.add_argument("--sys.trace.spans", dest="sys_trace_spans",
                       type=int, default=0)
        g.add_argument("--sys.trace.spans_out",
                       dest="sys_trace_spans_out", default=None)
        g.add_argument("--sys.crash_dumps", dest="sys_crash_dumps",
                       type=int, default=1)
        g.add_argument("--sys.trace.flight", dest="sys_trace_flight",
                       type=int, default=0)
        g.add_argument("--sys.trace.flight_out",
                       dest="sys_trace_flight_out", default=None)
        g.add_argument("--sys.flight.freshness_samples",
                       dest="sys_flight_freshness_samples", type=int,
                       default=1024)
        g.add_argument("--sys.trace.workload",
                       dest="sys_trace_workload", default=None)
        g.add_argument("--sys.trace.workload_keys",
                       dest="sys_trace_workload_keys", type=int,
                       default=4096)
        g.add_argument("--sys.trace.decisions",
                       dest="sys_trace_decisions", default=None)
        g.add_argument("--sys.trace.decisions_window",
                       dest="sys_trace_decisions_window", type=int,
                       default=8)
        g.add_argument("--sys.trace.spans.max_events",
                       dest="sys_trace_spans_max_events", type=int,
                       default=1_000_000)
        g.add_argument("--sys.serve.max_batch", dest="sys_serve_max_batch",
                       type=int, default=64)
        g.add_argument("--sys.serve.max_wait_us",
                       dest="sys_serve_max_wait_us", type=int, default=200)
        g.add_argument("--sys.serve.queue", dest="sys_serve_queue",
                       type=int, default=1024)
        g.add_argument("--sys.serve.deadline_ms",
                       dest="sys_serve_deadline_ms", type=float,
                       default=0.0)
        g.add_argument("--sys.serve.slo_ms", dest="sys_serve_slo_ms",
                       type=_slo_spec, default="0")
        g.add_argument("--sys.serve.dispatchers",
                       dest="sys_serve_dispatchers", type=int, default=1)
        g.add_argument("--sys.serve.replica_rows",
                       dest="sys_serve_replica_rows", type=int, default=0)
        g.add_argument("--sys.serve.replica_refresh_ms",
                       dest="sys_serve_replica_refresh_ms", type=float,
                       default=50.0)
        g.add_argument("--sys.serve.bags", dest="sys_serve_bags",
                       type=int, default=1)
        g.add_argument("--sys.stream.batch", dest="sys_stream_batch",
                       type=int, default=0)
        g.add_argument("--sys.stream.rate", dest="sys_stream_rate",
                       type=float, default=0.0)
        g.add_argument("--sys.stream.freshness_slo_ms",
                       dest="sys_stream_freshness_slo_ms",
                       type=_slo_spec, default="0")
        g.add_argument("--sys.costs.table", dest="sys_costs_table",
                       default=None)
        g.add_argument("--sys.costs.calibrate",
                       dest="sys_costs_calibrate", type=int, default=0)
        g.add_argument("--sys.fault.spec", dest="sys_fault_spec",
                       default="")
        g.add_argument("--sys.fault.seed", dest="sys_fault_seed",
                       type=int, default=0)
        g.add_argument("--sys.fault.retries", dest="sys_fault_retries",
                       type=int, default=3)
        g.add_argument("--sys.fault.backoff_ms",
                       dest="sys_fault_backoff_ms", type=float,
                       default=10.0)
        g.add_argument("--sys.fault.backoff_max_ms",
                       dest="sys_fault_backoff_max_ms", type=float,
                       default=2000.0)
        g.add_argument("--sys.fault.watchdog_s",
                       dest="sys_fault_watchdog_s", type=float,
                       default=30.0)
        g.add_argument("--sys.checkpoint.every",
                       dest="sys_ckpt_every", type=float, default=0.0)
        g.add_argument("--sys.checkpoint.path",
                       dest="sys_ckpt_path", default=None)
        g.add_argument("--sys.policy.reloc", dest="sys_policy_reloc",
                       default="heuristic",
                       choices=["heuristic", "learned"])
        g.add_argument("--sys.policy.tier", dest="sys_policy_tier",
                       default="heuristic",
                       choices=["heuristic", "learned"])
        g.add_argument("--sys.policy.sync", dest="sys_policy_sync",
                       default="heuristic",
                       choices=["heuristic", "learned"])
        g.add_argument("--sys.policy.serve", dest="sys_policy_serve",
                       default="heuristic",
                       choices=["heuristic", "learned"])
        g.add_argument("--sys.policy.file", dest="sys_policy_file",
                       default=None)
        g.add_argument("--sys.policy.shadow",
                       dest="sys_policy_shadow", type=int, default=0)
        g.add_argument("--sys.lint.lockorder",
                       dest="sys_lint_lockorder", type=int, default=0)
        s = parser.add_argument_group("sampling")
        s.add_argument("--sampling.scheme", dest="sampling_scheme",
                       default="local",
                       choices=["naive", "preloc", "pool", "local"])
        s.add_argument("--sampling.reuse", dest="sampling_reuse", type=int,
                       default=32)
        s.add_argument("--sampling.pool_size", dest="sampling_pool_size",
                       type=int,
                       default=0)
        s.add_argument("--sampling.batch_size", dest="sampling_batch_size",
                       type=int, default=1024)
        s.add_argument("--sampling.without_replacement",
                       dest="sampling_without_replacement",
                       action="store_true")

    @classmethod
    def from_args(cls, args: argparse.Namespace) -> "SystemOptions":
        serve_slo_ms, serve_slo_class = \
            _split_slo_spec(args.sys_serve_slo_ms)
        stream_slo_ms, stream_slo_class = \
            _split_slo_spec(args.sys_stream_freshness_slo_ms)
        opts = cls(
            techniques=MgmtTechniques(args.sys_techniques),
            channels=args.sys_channels,
            location_caches=bool(args.sys_location_caches),
            time_intent_actions=bool(args.sys_time_intent_actions),
            heartbeat_s=args.sys_heartbeat,
            dcn_threads=args.sys_dcn_threads,
            net_backend=args.sys_net_backend,
            net_queue=args.sys_net_queue,
            net_timeout_ms=args.sys_net_timeout_ms,
            net_heartbeat_ms=args.sys_net_heartbeat_ms,
            sync_max_per_sec=args.sys_sync_max_per_sec,
            sync_pause_ms=args.sys_sync_pause,
            sync_threshold=args.sys_sync_threshold,
            sync_dirty_only=bool(args.sys_sync_dirty_only),
            sync_compress=args.sys_sync_compress,
            collective_sync=bool(args.sys_collective_sync),
            collective_bucket=args.sys_collective_bucket,
            collective_cadence=args.sys_collective_cadence,
            main_over_alloc=args.sys_main_over_alloc,
            optimistic_routing=bool(args.sys_optimistic_routing),
            prefetch=bool(args.sys_prefetch),
            prefetch_max_batches=args.sys_prefetch_max_batches,
            prefetch_staging_rows=args.sys_prefetch_staging_rows,
            prefetch_pull=args.sys_prefetch_pull,
            plan_cache_entries=args.sys_plan_cache,
            tier=bool(args.sys_tier),
            tier_hot_rows=args.sys_tier_hot_rows,
            tier_cold_dtype=args.sys_tier_cold_dtype,
            tier_pin_intent=bool(args.sys_tier_pin_intent),
            tier_demote_batch=args.sys_tier_demote_batch,
            exec_workers=args.sys_exec_workers,
            exec_single_stream=bool(args.sys_exec_single_stream),
            episode_batches=args.sys_episode_batches,
            stats_out=args.sys_stats_out,
            trace_keys=args.sys_trace_keys,
            locality_stats=args.sys_stats_locality,
            sync_report_s=args.sys_sync_report,
            metrics=bool(args.sys_metrics),
            metrics_report_s=args.sys_metrics_report,
            trace_spans=bool(args.sys_trace_spans),
            trace_spans_out=args.sys_trace_spans_out,
            crash_dumps=bool(args.sys_crash_dumps),
            trace_flight=bool(args.sys_trace_flight),
            trace_flight_out=args.sys_trace_flight_out,
            trace_workload=args.sys_trace_workload,
            trace_workload_keys=args.sys_trace_workload_keys,
            trace_decisions=args.sys_trace_decisions,
            trace_decisions_window=args.sys_trace_decisions_window,
            trace_spans_max_events=args.sys_trace_spans_max_events,
            serve_max_batch=args.sys_serve_max_batch,
            serve_max_wait_us=args.sys_serve_max_wait_us,
            serve_queue=args.sys_serve_queue,
            serve_deadline_ms=args.sys_serve_deadline_ms,
            serve_slo_ms=serve_slo_ms,
            serve_slo_class=serve_slo_class,
            serve_dispatchers=args.sys_serve_dispatchers,
            serve_replica_rows=args.sys_serve_replica_rows,
            serve_replica_refresh_ms=args.sys_serve_replica_refresh_ms,
            serve_bags=bool(args.sys_serve_bags),
            stream_batch=args.sys_stream_batch,
            stream_rate=args.sys_stream_rate,
            stream_freshness_slo_ms=stream_slo_ms,
            stream_freshness_slo_class=stream_slo_class,
            flight_freshness_samples=args.sys_flight_freshness_samples,
            costs_table=args.sys_costs_table,
            costs_calibrate=bool(args.sys_costs_calibrate),
            fault_spec=args.sys_fault_spec,
            fault_seed=args.sys_fault_seed,
            fault_retries=args.sys_fault_retries,
            fault_backoff_ms=args.sys_fault_backoff_ms,
            fault_backoff_max_ms=args.sys_fault_backoff_max_ms,
            fault_watchdog_s=args.sys_fault_watchdog_s,
            ckpt_every_s=args.sys_ckpt_every,
            ckpt_path=args.sys_ckpt_path,
            policy_reloc=args.sys_policy_reloc,
            policy_tier=args.sys_policy_tier,
            policy_sync=args.sys_policy_sync,
            policy_serve=args.sys_policy_serve,
            policy_file=args.sys_policy_file,
            policy_shadow=bool(args.sys_policy_shadow),
            lint_lockorder=bool(args.sys_lint_lockorder),
            sampling_scheme=args.sampling_scheme,
            sampling_reuse_factor=args.sampling_reuse,
            sampling_pool_size=args.sampling_pool_size,
            sampling_batch_size=args.sampling_batch_size,
            sampling_with_replacement=not args.sampling_without_replacement,
        )
        opts.validate_serve()  # parse-time rejection of bad serve knobs
        return opts
