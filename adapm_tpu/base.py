"""Core type aliases, sentinels and enums.

TPU-native analog of the reference's include/ps/base.h (Key/Clock/sentinels,
MgmtTechniques) — see SURVEY.md §2.2.
"""
from __future__ import annotations

import enum

import numpy as np

# Keys are int64 (reference base.h: Key = uint64_t by default; bindings require
# int64_t). numpy/JAX index arrays use int32 on device where key counts permit.
Key = np.int64
Clock = int

# Sentinels (reference include/ps/base.h)
CLOCK_MAX: Clock = 2**31 - 1          # "forever" intent end
WORKER_FINISHED: Clock = CLOCK_MAX    # worker clock value after Finalize
LOCAL = -1                            # op timestamp: answered entirely locally

# Addressbook sentinels
NOT_CACHED = -2                       # location cache: no cached location
NO_SLOT = -1                          # key has no slot in a pool


class MgmtTechniques(enum.Enum):
    """Which adaptive management actions the planner may take.

    Mirrors the reference `--sys.techniques {all,replication_only,relocation_only}`
    (coloc_kv_server.h:209, sync_manager.h:624-644).
    """

    ALL = "all"
    REPLICATION_ONLY = "replication_only"
    RELOCATION_ONLY = "relocation_only"


class OpType(enum.Enum):
    PULL = "pull"
    PUSH = "push"
    SET = "set"
