"""Core type aliases, sentinels and enums.

TPU-native analog of the reference's include/ps/base.h (Key/Clock/sentinels,
MgmtTechniques) — see SURVEY.md §2.2.
"""
from __future__ import annotations

import enum

import numpy as np

# Keys are int64 (reference base.h: Key = uint64_t by default; bindings require
# int64_t). numpy/JAX index arrays use int32 on device where key counts permit.
Key = np.int64
Clock = int

# Sentinels (reference include/ps/base.h)
CLOCK_MAX: Clock = 2**31 - 1          # "forever" intent end
WORKER_FINISHED: Clock = CLOCK_MAX    # worker clock value after Finalize
LOCAL = -1                            # op timestamp: answered entirely locally

# Addressbook sentinels
NOT_CACHED = -2                       # location cache: no cached location
NO_SLOT = -1                          # key has no slot in a pool
# owner sentinel: main copy lives on another process
REMOTE = -1


def check_key_range(keys, num_keys: int, what: str = "key") -> None:
    """Raise IndexError if any key is outside [0, num_keys). One shared
    guard so every host path (routing, intents, stats, fused runners)
    reports the same way — negative keys would otherwise silently wrap via
    numpy indexing, and XLA clamps them on device."""
    keys = np.asarray(keys)
    if keys.size and (int(keys.min()) < 0 or int(keys.max()) >= num_keys):
        bad = keys[(keys < 0) | (keys >= num_keys)].ravel()[0]
        raise IndexError(
            f"{what} {bad} is outside the key range [0, {num_keys})")


class MgmtTechniques(enum.Enum):
    """Which adaptive management actions the planner may take.

    Mirrors the reference `--sys.techniques
    {all,replication_only,relocation_only}`
    (coloc_kv_server.h:209, sync_manager.h:624-644).
    """

    ALL = "all"
    REPLICATION_ONLY = "replication_only"
    RELOCATION_ONLY = "relocation_only"
