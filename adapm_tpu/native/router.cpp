// Native host-side runtime core.
//
// The reference's hot host path is C++ (per-key routing through the
// Addressbook + handle locks, addressbook.h:50-70, coloc_kv_worker.h:120-186).
// Here the device data plane is XLA, but the *host* still resolves every
// key batch to pool coordinates before each fused step — that loop is this
// library. Compiled with g++ (no external deps), loaded via ctypes
// (adapm_tpu/native/__init__.py); a numpy fallback keeps pure-Python
// environments working.
//
// Contract notes:
//  - tables are the Addressbook's numpy arrays, accessed zero-copy.
//  - `oob` is the store's OOB sentinel: padding/masked entries are dropped
//    by device scatters and zero-filled by gathers.
//  - write_through mirrors Server._route: a Set must reach the owner, so a
//    local replica does not make the op local.

#include <cstdint>

extern "C" {

// Resolve routing for n keys (prefer a local replica, else the owner row).
// Outputs: o_sh/o_sl (owner shard + raw slot — callers mask the gather path
// themselves, since Set writes through to the owner even past a replica),
// c_sh/c_sl (replica coordinates; c_sl=oob where none), use_c mask.
// Returns the number of remote keys (not owned here, no local replica;
// write_through: replicas don't count as local).
// Returns the remote-key count, or -(i+1) if keys[i] is the first key
// outside [0, num_keys) (the caller raises; the numpy fallback would have
// raised IndexError, and unchecked table reads here would corrupt memory).
int64_t adapm_route(const int64_t* keys, int64_t n, int64_t num_keys,
                    const int32_t* owner, const int32_t* slot,
                    const int32_t* cache_slot_row,  // cache_slot[shard, :]
                    int32_t shard, int32_t oob, int32_t write_through,
                    int32_t* o_sh, int32_t* o_sl,
                    int32_t* c_sh, int32_t* c_sl, uint8_t* use_c,
                    uint8_t* local_mask /* out: for locality stats */) {
  int64_t n_remote = 0;
  for (int64_t i = 0; i < n; ++i) {
    const int64_t k = keys[i];
    if (k < 0 || k >= num_keys) return -(i + 1);
    const int32_t ow = owner[k];
    const int32_t cs = cache_slot_row[k];
    const bool replica = cs >= 0;
    o_sh[i] = ow;
    c_sh[i] = shard;
    use_c[i] = replica ? 1 : 0;
    o_sl[i] = slot[k];
    c_sl[i] = replica ? cs : oob;
    const bool on_owner = ow == shard;
    const bool local = write_through ? on_owner : (on_owner || replica);
    local_mask[i] = local ? 1 : 0;
    n_remote += local ? 0 : 1;
  }
  return n_remote;
}

// Locality counters: accesses[k] += 1; local_acc[k] += local[i]
// (the vectorized replacement for np.add.at, which is slow for large
// batches of duplicate keys). Out-of-range keys are skipped; returns the
// number skipped so the caller can raise.
int64_t adapm_count(const int64_t* keys, const uint8_t* local, int64_t n,
                    int64_t num_keys, int64_t* accesses,
                    int64_t* local_acc) {
  int64_t bad = 0;
  for (int64_t i = 0; i < n; ++i) {
    const int64_t k = keys[i];
    if (k < 0 || k >= num_keys) { ++bad; continue; }
    accesses[k] += 1;
    local_acc[k] += local[i];
  }
  return bad;
}

// Intent bookkeeping: intent_end[k] = max(intent_end[k], end) for a key
// batch (SyncManager._register's np.maximum.at). Returns skipped count.
// intent_end is int32 ([S, K] at 5M+ keys — int64 would double the
// footprint; clocks are bounded by CLOCK_MAX = 2^31-1).
int64_t adapm_intent_max(const int64_t* keys, int64_t n, int64_t num_keys,
                         int64_t end, int32_t* intent_end) {
  const int32_t e = end > 2147483647LL ? 2147483647 : (int32_t)end;
  int64_t bad = 0;
  for (int64_t i = 0; i < n; ++i) {
    const int64_t k = keys[i];
    if (k < 0 || k >= num_keys) { ++bad; continue; }
    if (intent_end[k] < e) intent_end[k] = e;
  }
  return bad;
}

// Replica expiry scan (legacy single-mask variant; superseded by
// adapm_replica_scan2 on the planner hot path but kept for tooling):
// for replica i at (key[i], shard[i]), keep iff
// intent_end[shard[i]*num_keys + key[i]] >= min_clock[shard[i]].
// Writes 1/0 into keep; returns number kept.
int64_t adapm_replica_scan(const int64_t* keys, const int32_t* shards,
                           int64_t n, const int32_t* intent_end,
                           const int64_t* min_clock, int64_t num_keys,
                           uint8_t* keep) {
  int64_t kept = 0;
  for (int64_t i = 0; i < n; ++i) {
    const bool k =
        intent_end[(int64_t)shards[i] * num_keys + keys[i]] >=
        min_clock[shards[i]];
    keep[i] = k ? 1 : 0;
    kept += k ? 1 : 0;
  }
  return kept;
}

// Partitioned replica scan (SyncManager.sync_channel): one pass over a
// channel's (key, shard) snapshot emitting the four index partitions
// (keep/drop x local/cross) directly, instead of a keep-mask that
// Python re-walks. `cross` is the caller's owner-is-remote mask
// (snapshotted under the server lock; all-zero in a single process).
// Row indices land in the four caller-sized-n buffers; counts[4] =
// {keep_local, keep_cross, drop_local, drop_cross}.
void adapm_replica_scan2(const int64_t* keys, const int32_t* shards,
                         int64_t n, const int32_t* intent_end,
                         const int64_t* min_clock, int64_t num_keys,
                         const uint8_t* cross,
                         int64_t* keep_local, int64_t* keep_cross,
                         int64_t* drop_local, int64_t* drop_cross,
                         int64_t* counts) {
  int64_t nkl = 0, nkx = 0, ndl = 0, ndx = 0;
  for (int64_t i = 0; i < n; ++i) {
    const bool keep =
        intent_end[(int64_t)shards[i] * num_keys + keys[i]] >=
        min_clock[shards[i]];
    if (keep) {
      if (cross[i]) keep_cross[nkx++] = i; else keep_local[nkl++] = i;
    } else {
      if (cross[i]) drop_cross[ndx++] = i; else drop_local[ndl++] = i;
    }
  }
  counts[0] = nkl; counts[1] = nkx; counts[2] = ndl; counts[3] = ndx;
}

}  // extern "C"
