"""Native runtime loader: compiles router.cpp once (g++ -O3 -shared) into a
cache directory and binds it with ctypes. Falls back to None when no
compiler is available — callers keep a numpy path.

The reference ships its host runtime as C++ (libadapm.a); here the host-side
hot loops (route resolution per fused step, stat counters, intent/replica
scans) are the native surface, while the device data plane is XLA.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_SRC = os.path.join(os.path.dirname(__file__), "router.cpp")


def _cache_dir() -> str:
    d = os.environ.get("ADAPM_NATIVE_CACHE") or os.path.join(
        os.path.expanduser("~"), ".cache", "adapm_tpu")
    os.makedirs(d, exist_ok=True)
    return d


def _host_tag() -> str:
    """Cache-key component for the build host's ISA: -march=native output is
    only valid on CPUs with the same feature set (shared cache dirs on NFS
    homes would otherwise serve SIGILL-ing binaries to older machines)."""
    import platform
    parts = [platform.machine()]
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    parts.append(line.split(":", 1)[1].strip())
                    break
    except OSError:
        pass
    return hashlib.sha256(" ".join(parts).encode()).hexdigest()[:8]


def _build() -> Optional[str]:
    with open(_SRC, "rb") as f:
        tag = hashlib.sha256(f.read()).hexdigest()[:16]
    out = os.path.join(_cache_dir(),
                       f"libadapm_router_{tag}_{_host_tag()}.so")
    if os.path.exists(out):
        return out
    tmp = out + f".tmp{os.getpid()}"
    cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
           _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except Exception:
        try:  # -march=native can be unsupported in exotic environments
            cmd.remove("-march=native")
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        except Exception:
            return None
    os.replace(tmp, out)  # atomic vs concurrent builders
    return out


def get_lib() -> Optional[ctypes.CDLL]:
    """The compiled router library, or None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("ADAPM_NO_NATIVE"):
            return None
        path = _build()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            # stale/incompatible cached binary: fall back to numpy
            try:
                os.remove(path)
            except OSError:
                pass
            return None
        i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        lib.adapm_route.restype = ctypes.c_int64
        lib.adapm_route.argtypes = [
            i64p, ctypes.c_int64, ctypes.c_int64, i32p, i32p, i32p,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, i32p, i32p,
            i32p, i32p, u8p, u8p]
        lib.adapm_count.restype = ctypes.c_int64
        lib.adapm_count.argtypes = [i64p, u8p, ctypes.c_int64,
                                    ctypes.c_int64, i64p, i64p]
        lib.adapm_intent_max.restype = ctypes.c_int64
        lib.adapm_intent_max.argtypes = [i64p, ctypes.c_int64,
                                         ctypes.c_int64, ctypes.c_int64,
                                         i32p]
        lib.adapm_replica_scan.restype = ctypes.c_int64
        lib.adapm_replica_scan.argtypes = [
            i64p, i32p, ctypes.c_int64, i32p, i64p, ctypes.c_int64, u8p]
        lib.adapm_replica_scan2.restype = None
        lib.adapm_replica_scan2.argtypes = [
            i64p, i32p, ctypes.c_int64, i32p, i64p, ctypes.c_int64, u8p,
            i64p, i64p, i64p, i64p, i64p]
        _lib = lib
        return _lib


def route(lib, keys: np.ndarray, owner: np.ndarray, slot: np.ndarray,
          cache_slot_row: np.ndarray, shard: int, oob: int,
          write_through: bool):
    """ctypes wrapper for adapm_route; returns Server._route's tuple layout
    plus the per-key local mask (for locality stats)."""
    n = len(keys)
    num_keys = len(owner)
    o_sh = np.empty(n, np.int32)
    o_sl = np.empty(n, np.int32)
    c_sh = np.empty(n, np.int32)
    c_sl = np.empty(n, np.int32)
    use_c = np.empty(n, np.uint8)
    local = np.empty(n, np.uint8)
    keys = np.ascontiguousarray(keys, np.int64)
    n_remote = lib.adapm_route(
        keys, n, num_keys, owner, slot, cache_slot_row, shard, oob,
        int(write_through), o_sh, o_sl, c_sh, c_sl, use_c, local)
    if n_remote < 0:
        bad = keys[-(n_remote + 1)]
        raise IndexError(
            f"key {bad} is outside the key range [0, {num_keys})")
    return o_sh, o_sl, c_sh, c_sl, use_c.astype(bool), int(n_remote), local


def replica_scan_partition(lib, keys: np.ndarray, shards: np.ndarray,
                           intent_end: np.ndarray, min_clock: np.ndarray,
                           num_keys: int, cross):
    """ctypes wrapper for adapm_replica_scan2: partition a channel
    snapshot into (keep_local, keep_cross, drop_local, drop_cross)
    index arrays in one native pass. `cross` is a uint8 owner-is-remote
    mask or None (single process)."""
    n = len(keys)
    keys = np.ascontiguousarray(keys, np.int64)
    shards = np.ascontiguousarray(shards, np.int32)
    cross = np.zeros(n, np.uint8) if cross is None \
        else np.ascontiguousarray(cross, np.uint8)
    keep_l = np.empty(n, np.int64)
    keep_x = np.empty(n, np.int64)
    drop_l = np.empty(n, np.int64)
    drop_x = np.empty(n, np.int64)
    counts = np.zeros(4, np.int64)
    lib.adapm_replica_scan2(
        keys, shards, n, np.ascontiguousarray(intent_end.ravel(), np.int32),
        min_clock, num_keys, cross, keep_l, keep_x, drop_l, drop_x, counts)
    return (keep_l[: counts[0]], keep_x[: counts[1]],
            drop_l[: counts[2]], drop_x[: counts[3]])
