"""The correct-but-slow cold path: serving main-row operations whose
rows live in the host cold store.

Every function here is the tiered twin of a `ShardedStore` device
program and preserves its BIT-EXACT semantics (the tentpole contract):

  - reads select the cold row's bits verbatim (`jnp.where` merge, never
    `+ 0` — addition maps -0.0 to +0.0, the checkpoint-launder lesson);
  - additive writes are single f32 adds on either side (IEEE f32
    addition is deterministic; in-batch duplicates accumulate in batch
    order on both the XLA scatter and `np.add.at`);
  - a replica sync against a cold owner extracts the delta (device
    readback), merges on host, and installs the post-merge value as the
    new base with a zeroed delta — the same extract → merge-all →
    refresh-all ordering as the fused device program.

Callers hold the server lock (the residency discipline, residency.py);
the readbacks these paths pay ARE the cold tier's cost — misses are
served correctly and queued for promotion so repeated access turns hot.

Since ISSUE 8 the cold store may be QUANTIZED (--sys.tier.cold_dtype;
tier/quant.py): every access below goes through the `store.coldq`
surface, whose fp32 mode is a bit-identical raw-array passthrough (the
pre-PR pin) and whose fp16/int8 modes follow the error-compensated
contract in docs/MEMORY.md — the visible value of a cold row is its
dequantized stored value, identical through the dequant-fused device
gather (ops/dequant.py) and the host read paths here.

Since ISSUE 14 every device program below dispatches through the
store's DevicePort (adapm_tpu/device) — the cold-override gather, the
dequant-fused wire gathers, and the refresh installs are port methods;
this module is device-API-free (adapm-lint APM008) and pays only the
host-side residency work.
"""
from __future__ import annotations

import time

import numpy as np

from ..core.store import OOB, pad_bucket, pad_to

# ---------------------------------------------------------------------------
# residency resolution
# ---------------------------------------------------------------------------


def split_owner(store, o_sh: np.ndarray, o_sl: np.ndarray):
    """Resolve owner (shard, slot) coordinates against the residency
    map. Returns (g_row, cold, valid): the device hot-pool row per entry
    (OOB where the entry is padding/replica-served or cold), the cold
    mask, and the valid-entry mask."""
    o_sh = np.asarray(o_sh, dtype=np.int64).ravel()
    o_sl = np.asarray(o_sl, dtype=np.int64).ravel()
    valid = (o_sl >= 0) & (o_sl != OOB)
    g_row = np.full(o_sl.shape, OOB, dtype=np.int32)
    cold = np.zeros(o_sl.shape, dtype=bool)
    if valid.any():
        rows = store.res.dev_row[o_sh[valid], o_sl[valid]]
        g_row[valid] = np.where(rows >= 0, rows, OOB)
        cold[valid] = rows < 0
    return g_row, cold, valid


def _note_access(store, o_sh, o_sl, cold, valid) -> None:
    """Score the touched rows, count hot/cold serves, and queue cold
    rows for promotion (waking the maintenance worker — the miss path
    must drive adaptation even in workloads that never signal intent
    or serve lookups)."""
    res = store.res
    if valid.any():
        res.touch(o_sh[valid], o_sl[valid])
    nc = int(cold.sum())
    store.tier_hot_hits += int(valid.sum()) - nc
    store.tier_cold_hits += nc
    if nc:
        res.request_promote(o_sh[cold], o_sl[cold])
        res.kick()


# ---------------------------------------------------------------------------
# tiered store ops (called by ShardedStore when residency is enabled;
# caller holds the server lock)
# ---------------------------------------------------------------------------


def gather_tiered(store, o_shard, o_slot, c_shard, c_slot, use_cache):
    o_sh = np.asarray(o_shard, dtype=np.int64).ravel()
    o_sl = np.asarray(o_slot, dtype=np.int64).ravel()
    g_row, cold, valid = split_owner(store, o_sh, o_sl)
    _note_access(store, o_sh, o_sl, cold, valid)
    n = len(o_sh)
    a = pad_bucket(n, (o_sh.astype(np.int32), 0), (g_row, OOB),
                   (c_shard, 0), (c_slot, OOB), (use_cache, False),
                   minimum=store.bucket_min)
    if not cold.any():
        return store.port.gather(store.main, store.cache,
                                 store.delta, *a)
    t0 = time.perf_counter()
    b = a[0].shape[0]
    use_cold = np.zeros(b, dtype=bool)
    use_cold[:n] = cold
    mode = store.coldq.mode
    if mode == "fp32":
        cold_vals = np.zeros((b, store.value_length),
                             dtype=np.dtype(store.dtype))
        cold_vals[:n][cold] = store.coldq.read(o_sh[cold], o_sl[cold])
        out = store.port.gather_cold(store.main, store.cache,
                                     store.delta, *a, cold_vals,
                                     use_cold)
    else:
        # dequant-fused cold-miss gather (the port's wire ingest): ship
        # the WIRE rows — half/quarter the host->device bytes — and
        # invert the format inside the gather program itself
        q, s = store.coldq.wire(o_sh[cold], o_sl[cold])
        qbuf = np.zeros((b, store.value_length), dtype=q.dtype)
        qbuf[:n][cold] = q
        sbuf = None
        if mode != "fp16":
            sbuf = np.zeros(b, dtype=np.float32)
            sbuf[:n][cold] = s
        out = store.port.gather_cold_wire(
            mode, store.main, store.cache, store.delta, *a,
            qbuf, sbuf, use_cold)
    if store.tier_hist is not None:
        store.tier_hist.observe(time.perf_counter() - t0)
    return out


def gather_pool_tiered(store, o_shard, o_slot, c_shard, c_slot,
                       use_cache, seg, out, pooling):
    """`gather_tiered`'s fused-bag twin (ISSUE 16): identical residency
    resolution and cold-row staging, but the member rows reduce into
    `out` inside the port program (`gather_pool_cold[_wire]`) instead
    of coming back raw. The pooled result is bit-identical to host-
    pooling `gather_tiered`'s rows — the gather half is the same
    program body, and the segment sum accumulates in batch order on
    both sides."""
    o_sh = np.asarray(o_shard, dtype=np.int64).ravel()
    o_sl = np.asarray(o_slot, dtype=np.int64).ravel()
    g_row, cold, valid = split_owner(store, o_sh, o_sl)
    _note_access(store, o_sh, o_sl, cold, valid)
    n = len(o_sh)
    a = pad_bucket(n, (o_sh.astype(np.int32), 0), (g_row, OOB),
                   (c_shard, 0), (c_slot, OOB), (use_cache, False),
                   minimum=store.bucket_min)
    b = a[0].shape[0]
    segb = pad_to(np.asarray(seg, dtype=np.int32), b, OOB)
    if not cold.any():
        return store.port.gather_pool(store.main, store.cache,
                                      store.delta, *a, segb, out,
                                      pooling=pooling)
    t0 = time.perf_counter()
    use_cold = np.zeros(b, dtype=bool)
    use_cold[:n] = cold
    mode = store.coldq.mode
    if mode == "fp32":
        cold_vals = np.zeros((b, store.value_length),
                             dtype=np.dtype(store.dtype))
        cold_vals[:n][cold] = store.coldq.read(o_sh[cold], o_sl[cold])
        pooled = store.port.gather_pool_cold(
            store.main, store.cache, store.delta, *a, cold_vals,
            use_cold, segb, out, pooling=pooling)
    else:
        q, s = store.coldq.wire(o_sh[cold], o_sl[cold])
        qbuf = np.zeros((b, store.value_length), dtype=q.dtype)
        qbuf[:n][cold] = q
        sbuf = None
        if mode != "fp16":
            sbuf = np.zeros(b, dtype=np.float32)
            sbuf[:n][cold] = s
        pooled = store.port.gather_pool_cold_wire(
            mode, store.main, store.cache, store.delta, *a,
            qbuf, sbuf, use_cold, segb, out, pooling=pooling)
    if store.tier_hist is not None:
        store.tier_hist.observe(time.perf_counter() - t0)
    return pooled


def scatter_add_tiered(store, o_shard, o_slot, d_shard, d_slot, vals):
    o_sh = np.asarray(o_shard, dtype=np.int64).ravel()
    o_sl = np.asarray(o_slot, dtype=np.int64).ravel()
    g_row, cold, valid = split_owner(store, o_sh, o_sl)
    _note_access(store, o_sh, o_sl, cold, valid)
    rows = np.asarray(vals, dtype=np.dtype(store.dtype)).reshape(
        len(o_sh), store.value_length)
    if cold.any():
        # additive merge on the authoritative host row (in-batch
        # duplicates accumulate in batch order, like the device
        # scatter; quantized modes fold through the EF residual)
        store.coldq.add_at(o_sh[cold], o_sl[cold], rows[cold])
    n = len(o_sh)
    a = pad_bucket(n, (o_sh.astype(np.int32), 0), (g_row, OOB),
                   (d_shard, 0), (d_slot, OOB), minimum=store.bucket_min)
    v = store._vals_bucket(rows, a[0].shape[0])
    store.main, store.delta = store.port.scatter_add(
        store.main, store.delta, *a, v)


def set_rows_tiered(store, o_shard, o_slot, vals, c_shard, c_slot):
    o_sh = np.asarray(o_shard, dtype=np.int64).ravel()
    o_sl = np.asarray(o_slot, dtype=np.int64).ravel()
    g_row, cold, valid = split_owner(store, o_sh, o_sl)
    _note_access(store, o_sh, o_sl, cold, valid)
    rows = np.asarray(vals, dtype=np.dtype(store.dtype)).reshape(
        len(o_sh), store.value_length)
    if cold.any():
        store.coldq.set_at(o_sh[cold], o_sl[cold], rows[cold])
    n = len(o_sh)
    a = pad_bucket(n, (o_sh.astype(np.int32), 0), (g_row, OOB),
                   (c_shard, 0), (c_slot, OOB), minimum=store.bucket_min)
    v = store._vals_bucket(rows, a[0].shape[0])
    store.main, store.cache, store.delta = store.port.set_rows(
        store.main, store.cache, store.delta, a[0], a[1], v,
        a[2], a[3])


def replica_create_tiered(store, o_shard, o_slot, c_shard, c_slot):
    """Materialize replicas: hot owners through the device program (with
    remapped rows), cold owners via host read + base install."""
    o_sh = np.asarray(o_shard, dtype=np.int64).ravel()
    o_sl = np.asarray(o_slot, dtype=np.int64).ravel()
    c_sh = np.asarray(c_shard, dtype=np.int32).ravel()
    c_sl = np.asarray(c_slot, dtype=np.int32).ravel()
    g_row, cold, valid = split_owner(store, o_sh, o_sl)
    hot = valid & ~cold
    if hot.any():
        a = pad_bucket(int(hot.sum()),
                       (o_sh[hot].astype(np.int32), 0), (g_row[hot], OOB),
                       (c_sh[hot], 0), (c_sl[hot], OOB),
                       minimum=store.bucket_min)
        store.cache, store.delta = store.port.replica_create(
            store.main, store.cache, store.delta, *a)
    if cold.any():
        # a fresh replica copies the VISIBLE cold value (deq only —
        # the parked residual stays with the owner row)
        vals = store.coldq.read(o_sh[cold], o_sl[cold])
        a = pad_bucket(int(cold.sum()), (c_sh[cold], 0), (c_sl[cold], OOB),
                       minimum=store.bucket_min)
        v = store._vals_bucket(vals, a[0].shape[0])
        store.cache, store.delta = store.port.install_cache_rows(
            store.cache, store.delta, *a, v)


def sync_replicas_tiered(store, r_shard, r_cslot, o_shard, o_slot,
                         threshold: float = 0.0, compress: str = "off"):
    """One sync batch with tier-aware owners: replicas of hot owners
    ride the fused device program; replicas of cold owners sync through
    the cold path — delta readback → host merge → base install (the
    tentpole's "replicas of cold keys sync through the cold path").
    `compress` applies the --sys.sync.compress wire transform on both
    halves: the device program for hot owners, the host twin
    (quant.compress_delta) for cold owners — with the residual parked
    in the replica's delta row either way."""
    r_sh = np.asarray(r_shard, dtype=np.int32).ravel()
    r_cs = np.asarray(r_cslot, dtype=np.int32).ravel()
    o_sh = np.asarray(o_shard, dtype=np.int64).ravel()
    o_sl = np.asarray(o_slot, dtype=np.int64).ravel()
    g_row, cold, valid = split_owner(store, o_sh, o_sl)
    hot = ~cold  # invalid (padding) entries ride the device program: OOB
    if hot.any():
        a = pad_bucket(int(hot.sum()), (r_sh[hot], 0), (r_cs[hot], OOB),
                       (o_sh[hot].astype(np.int32), 0), (g_row[hot], OOB),
                       minimum=store.bucket_min)
        out = store.port.sync_replicas(
            store.main, store.cache, store.delta, *a,
            threshold=threshold, compress=compress)
        if compress != "off":
            (store.main, store.cache, store.delta,
             store._ef_resid_dev) = out
        else:
            store.main, store.cache, store.delta = out
    if not cold.any():
        return
    t0 = time.perf_counter()
    ci = np.nonzero(cold)[0]
    # extract: the pending deltas of the cold-owner replicas (the
    # readback serializes behind every enqueued delta write — exact)
    dvals = store.read_rows("delta", r_sh[ci], r_cs[ci])
    ship = np.ones(len(ci), dtype=bool)
    if threshold > 0.0:
        # the reference's sync threshold, decided on host for cold rows
        # (the device program decides on device for hot rows)
        ship = np.max(np.abs(dvals), axis=1) >= threshold
    if ship.any():
        si = ci[ship]
        merged = dvals[ship]
        resid = None
        if compress != "off":
            # host twin of _sync_replicas_compressed: the owner merges
            # what the wire format reconstructs; the remainder parks in
            # the replica's delta row below
            from .quant import compress_delta
            merged, resid = compress_delta(compress, merged)
            if len(resid):
                store._ef_resid_host = float(np.max(np.abs(resid)))
        # merge-all THEN refresh-all, like the device program: all
        # shipped deltas land before any fresh value is read, so every
        # replica of a key sees the post-merge value
        store.coldq.add_at(o_sh[si], o_sl[si], merged)
        fresh = store.coldq.read(o_sh[si], o_sl[si])
        a = pad_bucket(len(si), (r_sh[si], 0), (r_cs[si], OOB),
                       minimum=store.bucket_min)
        v = store._vals_bucket(fresh, a[0].shape[0])
        rv = None if resid is None else \
            store._vals_bucket(resid, a[0].shape[0])
        store.cache, store.delta = store.port.install_cache_rows(
            store.cache, store.delta, *a, v, resid=rv)
    if store.tier_hist is not None:
        store.tier_hist.observe(time.perf_counter() - t0)


def relocate_tiered(store, old_shard, old_slot, new_shard, new_slot,
                    rc_shard, rc_slot):
    """Relocation on the tiered store runs through the host: read the
    authoritative old rows (device readback where hot, cold store
    otherwise), merge the destination replica's pending delta, land the
    moved rows COLD at the destination (relocation is intent-driven, so
    the pin/promote path makes them hot right after), and free the old
    residency. All reads happen before all writes — the device
    program's intra-batch slot-reuse discipline."""
    from .promote import release_rows
    old_sh = np.asarray(old_shard, dtype=np.int64).ravel()
    old_sl = np.asarray(old_slot, dtype=np.int64).ravel()
    new_sh = np.asarray(new_shard, dtype=np.int64).ravel()
    new_sl = np.asarray(new_slot, dtype=np.int64).ravel()
    rc_sh = np.asarray(rc_shard, dtype=np.int32).ravel()
    rc_sl = np.asarray(rc_slot, dtype=np.int32).ravel()
    n = len(old_sh)
    g_row, cold, valid = split_owner(store, old_sh, old_sl)
    rows = np.zeros((n, store.value_length), dtype=np.dtype(store.dtype))
    hot = valid & ~cold
    if hot.any():
        rows[hot] = store.read_hot_rows_at(old_sh[hot].astype(np.int32),
                                           g_row[hot])
    if cold.any():
        # a relocation MOVES the authoritative value: take the full-
        # precision row (deq + parked residual, consuming it) so the
        # error-feedback state travels with the key
        rows[cold] = store.coldq.take_true(old_sh[cold], old_sl[cold])
    has_rc = (rc_sl != OOB) & (rc_sl >= 0)
    if has_rc.any():
        d = store.read_rows("delta", rc_sh[has_rc], rc_sl[has_rc])
        rows[has_rc] += d
        a = pad_bucket(int(has_rc.sum()), (rc_sh[has_rc], 0),
                       (rc_sl[has_rc], OOB), minimum=store.bucket_min)
        store.delta = store.port.clear_rows(store.delta, *a)
    # free the old residency (value already extracted), land cold
    release_rows(store, old_sh[valid], old_sl[valid])
    dst_ok = (new_sl >= 0) & (new_sl != OOB)
    if dst_ok.any():
        store.coldq.set_at(new_sh[dst_ok], new_sl[dst_ok], rows[dst_ok])
        # defensively clear any stale mapping at the destination slot
        # (a correctly-released slot is already -1)
        store.res.dev_row[new_sh[dst_ok], new_sl[dst_ok]] = -1


def read_main_rows_tiered(store, sh, sl) -> np.ndarray:
    """Host readback of main rows on the tiered store (read_rows'
    "main" pool): hot rows via a device gather, cold rows from the cold
    store."""
    sh = np.asarray(sh, dtype=np.int64).ravel()
    sl = np.asarray(sl, dtype=np.int64).ravel()
    g_row, cold, valid = split_owner(store, sh, sl)
    out = np.zeros((len(sh), store.value_length),
                   dtype=np.dtype(store.dtype))
    hot = valid & ~cold
    if hot.any():
        out[hot] = store.read_hot_rows_at(sh[hot].astype(np.int32),
                                          g_row[hot])
    if cold.any():
        out[cold] = store.coldq.read(sh[cold], sl[cold])
    return out


def read_main_rows_bulk(store, sh: np.ndarray,
                        sl: np.ndarray) -> np.ndarray:
    """Bulk-scale host read of main rows (checkpoint/eval/export path):
    fancy-index the REQUESTED rows out of the cold store (no full-table
    copy — at beyond-HBM model sizes a whole-table copy would
    transiently double host RAM) and overlay the hot subset via one
    hot-pool-sized readback (bounded by hot_rows, not model size)."""
    sh = np.asarray(sh, dtype=np.int64).ravel()
    sl = np.asarray(sl, dtype=np.int64).ravel()
    # fancy index -> copy of the REQUESTED rows only; quantized modes
    # dequantize that same bounded slice (wire copy + f32 result), so
    # the dequant path keeps the no-second-full-table-copy contract
    out = store.coldq.read(sh, sl)
    rows = store.res.dev_row[sh, sl]
    m = rows >= 0
    if m.any():
        hot = np.asarray(store.main)  # [S, hot_rows, L]
        out[m] = hot[sh[m], rows[m]]
    return out


def main_full_host(store) -> np.ndarray:
    """Assemble the full authoritative main table [S, main_slots, L] on
    host (checkpoint save, bulk reads): the cold store overlaid with the
    hot pool's rows. One device readback of the whole hot pool."""
    full = store.coldq.full()
    res = store.res
    sh_idx, row_idx = np.nonzero(res.row_slot >= 0)
    if len(sh_idx):
        hot_host = np.asarray(store.main)
        full[sh_idx, res.row_slot[sh_idx, row_idx]] = \
            hot_host[sh_idx, row_idx]
    return full


def install_main_full(store, arr: np.ndarray) -> None:
    """Checkpoint restore into a tiered store: the full main table
    becomes the cold store and residency resets — everything cold,
    re-promoted lazily by access/intent (the restore contract,
    tests/test_tier.py)."""
    store.coldq.install_full(np.asarray(arr, dtype=np.dtype(store.dtype)))
    store.res.reset()
