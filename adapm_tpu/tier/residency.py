"""Per-row residency tracking for the tiered parameter store.

The tiering plane splits each server's owned keys (main rows) between a
capacity-bounded DEVICE-HOT pool and a HOST-COLD store (ISSUE 5
tentpole; the hot/cold split of DLRM-scale embedding systems —
"Dissecting Embedding Bag Performance in DLRM Inference" — and
GraphVite's hybrid host/accelerator residency, PAPERS.md). Replica
cache/delta rows stay fully device-resident: only MAIN rows tier.

`Residency` is one length class's host-side map:

    dev_row[S, main_slots]  slot -> device row in the hot pool (-1 = cold)
    row_slot[S, hot_rows]   reverse map (device row -> slot, -1 = free)
    score[S, main_slots]    clock/frequency access score (periodically
                            halved — a decayed-counter CLOCK variant)
    pin_until[S, main_slots] intent-liveness pin: rows pinned hot while
                            any Intent window covering them is active

The replacement signal FUSES frequency with the explicit `Intent`
windows the PM already collects (the paper's lookahead advantage over
frequency-only caches): a pinned row is never a demotion victim while
its window is live, regardless of score.

Locking discipline (the residency-epoch contract, docs/MEMORY.md):
every mutation of `dev_row`/`row_slot` — promotion, demotion, slot
release — happens under the SERVER lock and bumps `epoch`. Every store
op that consults residency (all of core/store.py's tiered dispatches)
also runs under the server lock, so a dispatched program can never see
a torn map. Plans computed OUTSIDE the lock (the demotion worker's
victim scans, the fused runners' composed slot mirrors) carry the epoch
they were computed under and revalidate it under the lock before
acting — the `topology_version` discipline, applied to residency.

Score bumps and pin writes are advisory (racy int writes are at worst a
slightly-wrong replacement decision, never a wrong value) and may run
lock-free.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core.addressbook import SlotAllocator

# pin sentinel: no pin
NO_PIN = np.int64(-1)


class Residency:
    """Host-side residency map for one ShardedStore (see module doc)."""

    def __init__(self, num_shards: int, main_slots: int, hot_rows: int):
        self.num_shards = num_shards
        self.main_slots = main_slots
        self.hot_rows = hot_rows
        self.dev_row = np.full((num_shards, main_slots), -1, dtype=np.int32)
        self.row_slot = np.full((num_shards, hot_rows), -1, dtype=np.int32)
        self.alloc = SlotAllocator(num_shards, hot_rows)
        self.score = np.zeros((num_shards, main_slots), dtype=np.int64)
        self.pin_until = np.full((num_shards, main_slots), NO_PIN,
                                 dtype=np.int64)
        # bumped on every promote/demote/release batch (under the server
        # lock); consumers revalidate like topology_version
        self.epoch = 0
        # cold-miss promotion wants, appended by the serve/gather paths
        # and drained by the maintenance worker: [(shards, slots)]
        self.want: List[Tuple[np.ndarray, np.ndarray]] = []
        # wakes the maintenance worker; bound by TierManager to
        # PromotionEngine.kick so the MISS path (gather/scatter on cold
        # rows) drains its promotion wants even in pure pull/push
        # workloads that never signal intent or serve lookups
        self.kick = lambda: None

    def hot_count(self, shard: int) -> int:
        return self.hot_rows - self.alloc.num_free(shard)

    def touch(self, shards: np.ndarray, slots: np.ndarray) -> None:
        """Bump access scores (advisory; may run lock-free)."""
        np.add.at(self.score, (shards, slots), 1)

    def decay(self) -> None:
        """Halve all scores (the CLOCK hand sweep, amortized)."""
        self.score >>= 1

    def pin(self, shards: np.ndarray, slots: np.ndarray, end: int) -> None:
        """Pin rows hot until clock `end` (advisory write)."""
        np.maximum.at(self.pin_until, (shards, slots), np.int64(end))

    def pinned_mask(self, shard: int, slots: np.ndarray,
                    min_clock: int) -> np.ndarray:
        """True where the row's pin window is still active."""
        return self.pin_until[shard, slots] >= min_clock

    def reset(self) -> None:
        """Everything cold (checkpoint restore): drop all mappings, pins
        and scores; keys re-promote lazily on access/intent."""
        self.dev_row.fill(-1)
        self.row_slot.fill(-1)
        self.alloc = SlotAllocator(self.num_shards, self.hot_rows)
        self.score.fill(0)
        self.pin_until.fill(NO_PIN)
        self.want.clear()
        self.epoch += 1

    def request_promote(self, shards: np.ndarray,
                        slots: np.ndarray) -> None:
        """Queue cold rows for background promotion (the miss path and
        the serving plane call this; the maintenance worker drains it
        under the server lock, revalidating coordinates there). Bounded:
        a producer outrunning the worker keeps only a fresh window."""
        self.want.append((np.asarray(shards, dtype=np.int32).copy(),
                          np.asarray(slots, dtype=np.int32).copy()))
        if len(self.want) > 64:
            del self.want[: len(self.want) - 64]


class TierManager:
    """Server-level coordinator of the tiering plane: owns the
    maintenance worker (adapm_tpu/tier/promote.py), the intent-pin and
    serve-feedback entry points, the residency-composed device slot
    mirror, and the `tier.*` metrics section (docs/OBSERVABILITY.md;
    schema_version 4)."""

    def __init__(self, server, opts):
        from .promote import PromotionEngine
        self.server = server
        self.opts = opts
        for st in server.stores:
            assert st.res is not None, \
                "TierManager requires tier-enabled stores"
        self.engine = PromotionEngine(server, opts, self)
        # composed key->device-row mirror cache (ops/fused.py
        # DeviceRouter): rebuilt when topology_version or the residency
        # epoch moves
        self._slot_mirror = None
        self._slot_mirror_key = None
        self._mirror_lock = threading.Lock()
        reg = server.obs
        self.c_promotions = reg.counter("tier.promotions")
        self.c_demotions = reg.counter("tier.demotions")
        self.c_serve_cold = reg.counter("tier.serve_cold_keys")
        self.h_cold_serve = reg.histogram("tier.cold_serve_s")
        if reg.enabled:
            reg.gauge("tier.epoch", fn=lambda: self.epoch)
            reg.gauge("tier.hot_hits",
                      fn=lambda: sum(st.tier_hot_hits
                                     for st in server.stores))
            reg.gauge("tier.cold_hits",
                      fn=lambda: sum(st.tier_cold_hits
                                     for st in server.stores))
            reg.gauge("tier.hot_hit_rate", fn=self.hot_hit_rate)
            reg.gauge("tier.hot_rows_used",
                      fn=lambda: sum(st.res.hot_count(s)
                                     for st in server.stores
                                     for s in range(st.res.num_shards)))
            reg.gauge("tier.hot_rows_capacity",
                      fn=lambda: sum(st.res.hot_rows * st.res.num_shards
                                     for st in server.stores))
            # compression plane (ISSUE 8; schema v7): actual host bytes
            # per cold row — dense store + scale column + parked EF
            # residuals, averaged over classes weighted by rows — plus
            # the residual-map health pair (rows parked / evicted at
            # the cap; evictions inject bounded error, never silent)
            reg.gauge("tier.cold_bytes_per_row",
                      fn=lambda: self.cold_bytes_per_row())
            reg.gauge("tier.ef_resid_rows",
                      fn=lambda: sum(st.coldq.resid_rows()
                                     for st in server.stores))
            reg.gauge("tier.ef_evicted",
                      fn=lambda: sum(st.coldq.ef_evicted
                                     for st in server.stores))
        # the cold-serve latency histogram is observed from inside the
        # store's gather path — hand the stores the handle; the wake
        # hook lets the miss path kick the maintenance worker
        for st in server.stores:
            st.tier_hist = self.h_cold_serve
            # late-bound on purpose: tests that must not run the worker
            # thread replace engine.kick on the instance
            st.res.kick = lambda e=self.engine: e.kick()

    # -- epoch ---------------------------------------------------------------

    @property
    def epoch(self) -> int:
        """Server-wide residency epoch (sum over class stores): bumped —
        under the server lock — by every promotion/demotion/release
        batch. In-flight residency-dependent plans revalidate against
        it, exactly like topology_version."""
        return sum(st.res.epoch for st in self.server.stores)

    def cold_bytes_per_row(self) -> float:
        """Host bytes one cold-tier row actually costs (fp32 = 4L; the
        quantized modes' savings INCLUDING scale columns and parked
        residuals — the honest number the bench compress phase and
        docs/MEMORY.md quote)."""
        total_bytes = sum(st.coldq.nbytes() for st in self.server.stores)
        total_rows = sum(st.coldq.num_shards * st.coldq.main_slots
                         for st in self.server.stores)
        return total_bytes / total_rows if total_rows else 0.0

    def hot_hit_rate(self) -> float:
        """Fraction of owner-served gather entries served from the
        device-hot pool (cumulative)."""
        hot = sum(st.tier_hot_hits for st in self.server.stores)
        cold = sum(st.tier_cold_hits for st in self.server.stores)
        return hot / (hot + cold) if (hot + cold) else 1.0

    # -- intent / serve feedback --------------------------------------------

    def note_intent(self, keys: np.ndarray, end: int) -> None:
        """Pin the owner rows of `keys` hot for the intent window and
        queue their promotion (called from the planner's intent drain —
        the same hook point the PrefetchScheduler rides,
        core/sync.py drain_intents). Advisory pin writes; the promotion
        itself happens in the maintenance worker under the server lock.
        Gated by --sys.tier.pin_intent."""
        if not self.opts.tier_pin_intent or len(keys) == 0:
            return
        srv = self.server
        ab = srv.ab
        keys = np.asarray(keys, dtype=np.int64).ravel()
        for cid, pos in srv._group_by_class(keys):
            ks = keys[pos]
            o_sh = ab.owner[ks]
            o_sl = ab.slot[ks]
            m = o_sl >= 0  # process-local owners only
            if not m.any():
                continue
            res = srv.stores[cid].res
            res.pin(o_sh[m], o_sl[m], int(end))
            cold = res.dev_row[o_sh[m], o_sl[m]] < 0
            if cold.any():
                res.request_promote(o_sh[m][cold], o_sl[m][cold])
        self.engine.kick()

    def note_serve(self, keys: np.ndarray) -> None:
        """Serving-plane feedback (serve/batcher.py consults residency
        before planning): bump scores for the looked-up keys and queue
        promotion of the cold ones, so the hot set adapts to serve load
        as well as training intent. Advisory — runs without the server
        lock; the worker revalidates coordinates."""
        srv = self.server
        ab = srv.ab
        keys = np.asarray(keys, dtype=np.int64).ravel()
        kicked = False
        for cid, pos in srv._group_by_class(keys):
            ks = keys[pos]
            o_sh = ab.owner[ks]
            o_sl = ab.slot[ks]
            m = o_sl >= 0
            if not m.any():
                continue
            res = srv.stores[cid].res
            res.touch(o_sh[m], o_sl[m])
            cold = res.dev_row[o_sh[m], o_sl[m]] < 0
            if cold.any():
                self.c_serve_cold.inc(int(cold.sum()))
                res.request_promote(o_sh[m][cold], o_sl[m][cold])
                kicked = True
        if kicked:
            self.engine.kick()

    def export_serve_scores(self) -> np.ndarray:
        """Per-KEY residency access scores (ISSUE 9; serve/replica.py
        seeds its hot-row selection from these fused with its own
        `note_serve`-style load counters). Locally-owned keys map to
        their owner row's decayed CLOCK score; process-remote keys read
        0. Advisory host read — scores are racy by design (module
        docstring), and a slightly stale score only shifts the
        selection, never a served value. O(num_keys); refresh-frequency
        only."""
        srv = self.server
        ab = srv.ab
        out = np.zeros(srv.num_keys, dtype=np.int64)
        single = len(srv.stores) == 1
        for cid, st in enumerate(srv.stores):
            owned = ab.owner >= 0
            if not single:
                owned = owned & (ab.key_class == cid)
            k = np.nonzero(owned)[0]
            if len(k):
                out[k] = st.res.score[ab.owner[k], ab.slot[k]]
        return out

    # -- synchronous promotion (fused runners; caller holds server lock) ----

    def pin_step_keys(self, role_class: Dict[str, int],
                      role_keys: Dict[str, np.ndarray]) -> None:
        """Make a fused step's host-known key batch device-hot and pin
        it for a short clock window (ops/fused.py runners call this
        under the server lock before building their pools snapshot): the
        step program reads main rows through the composed slot mirror,
        so a cold row would read as zeros — promotion here is a
        CORRECTNESS requirement for the fused path, not a heuristic."""
        srv = self.server
        end = self.step_pin_end()
        # union the roles per length class BEFORE ensuring: forced
        # eviction protects the batch being promoted, and ensuring the
        # roles one at a time would let a later role's eviction
        # victimize an earlier role's just-pinned rows
        by_cid: Dict[int, list] = {}
        for r, keys in role_keys.items():
            k = np.asarray(keys, dtype=np.int64).ravel()
            if len(k):
                by_cid.setdefault(role_class[r], []).append(k)
        for cid, parts in by_cid.items():
            k = np.concatenate(parts)
            self.ensure_hot(cid, srv.ab.owner[k], srv.ab.slot[k],
                            pin_end=end, force=True)

    def step_pin_end(self) -> int:
        """Pin horizon for a fused step's key batch: a couple of clocks
        past the fastest active worker — long enough that the demotion
        worker cannot thrash a step's rows between consecutive steps,
        short enough that a retired batch unpins by itself."""
        from ..base import WORKER_FINISHED
        clocks = self.server._clocks
        act = clocks[clocks != WORKER_FINISHED]
        return (int(act.max()) if len(act) else 0) + 2

    def ensure_hot(self, cid: int, shards: np.ndarray, slots: np.ndarray,
                   pin_end: Optional[int] = None,
                   force: bool = False) -> int:
        """Promote any cold rows among (shards, slots) of class `cid`,
        demoting low-score unpinned victims when the hot pool is full
        (caller holds the server lock). `force=True` (fused steps, whose
        programs index the hot pool directly) may also evict pinned
        victims and raises if the batch itself cannot fit. Entries with
        slot < 0 (process-remote keys) are skipped. Returns rows
        promoted."""
        from .promote import ensure_hot_rows
        res = self.server.stores[cid].res
        shards = np.asarray(shards, dtype=np.int32).ravel()
        slots = np.asarray(slots, dtype=np.int32).ravel()
        m = slots >= 0
        shards, slots = shards[m], slots[m]
        if len(slots) == 0:
            return 0
        if pin_end is not None:
            res.pin(shards, slots, pin_end)
        n = ensure_hot_rows(self.server, self.server.stores[cid],
                            shards, slots,
                            min_clock=self._min_active_clock(),
                            force=force)
        if n:
            self.c_promotions.inc(n)
        return n

    # -- test/tooling helpers (resolve keys -> coords, take the lock) --------

    def promote_keys(self, keys: np.ndarray) -> int:
        """Promote `keys`' owner rows (blocking; takes the server lock).
        Test/tooling surface — production promotion is intent/miss
        driven through the worker."""
        srv = self.server
        keys = np.asarray(keys, dtype=np.int64).ravel()
        n = 0
        with srv._lock:
            for cid, pos in srv._group_by_class(keys):
                ks = keys[pos]
                n += self.ensure_hot(cid, srv.ab.owner[ks],
                                     srv.ab.slot[ks])
        return n

    def demote_keys(self, keys: np.ndarray) -> int:
        """Demote `keys`' owner rows to the cold store (blocking; takes
        the server lock). Pinned rows demote too — this is the explicit
        tooling surface, not the worker's pin-respecting policy."""
        from .promote import demote_rows
        srv = self.server
        keys = np.asarray(keys, dtype=np.int64).ravel()
        n = 0
        with srv._lock:
            for cid, pos in srv._group_by_class(keys):
                ks = keys[pos]
                o_sl = srv.ab.slot[ks]
                o_sh = srv.ab.owner[ks]
                m = o_sl >= 0
                for s in np.unique(o_sh[m]):
                    sm = m & (o_sh == s)
                    n += demote_rows(srv.stores[cid], int(s),
                                     np.unique(o_sl[sm]).astype(np.int32))
        if n:
            self.c_demotions.inc(n)
        return n

    def _min_active_clock(self) -> int:
        """Min clock over active workers — the pin-expiry horizon (a pin
        whose end clock is behind every active worker can never matter
        again)."""
        from ..base import WORKER_FINISHED
        clocks = self.server._clocks
        act = clocks[clocks != WORKER_FINISHED]
        return int(act.min()) if len(act) else 0

    # -- composed device slot mirror (ops/fused.py DeviceRouter) -------------

    def compose_slot_table(self) -> np.ndarray:
        """key -> DEVICE ROW table for the device-routed fused step:
        `ab.slot` with each locally-owned key's slot replaced by its hot
        row, and OOB while cold. OOB, NOT -1: JAX's `.at[]` modes drop/
        fill only LARGE positive out-of-bounds indices — a negative
        index WRAPS to the last row, so a -1 sentinel would make any
        stray cold access read (and scatter into) the wrong hot row.
        With OOB, an unpinned cold read fills zeros and a cold scatter
        drops — detectable, never corrupting; runners pin their batches
        hot so neither happens. Cached per (topology_version, residency
        epoch); shared by every runner so N runners pay one O(num_keys)
        composition per residency change, not N."""
        from ..core.store import OOB
        srv = self.server
        key = (srv.topology_version, self.epoch)
        with self._mirror_lock:
            if self._slot_mirror_key == key and \
                    self._slot_mirror is not None:
                return self._slot_mirror
            ab = srv.ab
            eff = ab.slot.astype(np.int32).copy()
            single = len(srv.stores) == 1
            for cid, st in enumerate(srv.stores):
                owned = ab.owner >= 0
                if not single:
                    owned = owned & (ab.key_class == cid)
                k = np.nonzero(owned)[0]
                if len(k):
                    rows = st.res.dev_row[ab.owner[k], ab.slot[k]]
                    eff[k] = np.where(rows >= 0, rows, OOB)
            self._slot_mirror = eff
            self._slot_mirror_key = key
            return eff

    # -- lifecycle -----------------------------------------------------------

    def maintain(self) -> None:
        """One synchronous maintenance pass (drain promotion wants,
        pressure-demote, decay) — what the background worker runs;
        exposed for tests and the residency check script so adaptation
        is deterministic without thread timing."""
        self.engine.run_once()

    def reset_residency(self) -> None:
        """Everything cold (checkpoint restore path; caller holds the
        server lock)."""
        for st in self.server.stores:
            st.res.reset()
        with self._mirror_lock:
            self._slot_mirror = None
            self._slot_mirror_key = None

    def close(self) -> None:
        """Stop the maintenance worker (idempotent; Server.shutdown
        closes the tier plane after the prefetch pipeline and before the
        sync thread — the demotion worker reads through the pools, so it
        must be down before pool teardown)."""
        self.engine.close()

    def report(self) -> Dict[str, float]:
        return {"hot_hit_rate": round(self.hot_hit_rate(), 4),
                "promotions": int(self.c_promotions.snap()),
                "demotions": int(self.c_demotions.snap())}
