"""Tiered parameter storage (ISSUE 5 tentpole): device-hot / host-cold
main-row residency with intent-driven promotion.

    residency.py — per-row tier + clock/frequency score fused with
                   intent liveness; the TierManager coordinator
    promote.py   — batched promotion/demotion programs + the
                   maintenance (demotion) worker
    coldpath.py  — the correct-but-slow cold path: tier-aware store
                   operations (host gather → staged upload → merge)

Enable with --sys.tier (plus --sys.tier.{hot_rows,pin_intent,
demote_batch}); docs/MEMORY.md is the design doc. Every Pull/Push/serve
lookup on the tiered store is bit-identical to the untiered store —
residency moves values, never changes them.
"""
from __future__ import annotations

from .promote import (PromotionEngine, demote_rows, ensure_hot_rows,  # noqa: F401
                      promote_rows, release_rows)
from .residency import Residency, TierManager  # noqa: F401
