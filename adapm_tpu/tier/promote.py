"""Batched promotion/demotion between the device-hot pool and the host
cold store.

Promotion is one fused scatter program per (class, shard) batch: the
cold rows' authoritative host values upload into freshly-allocated hot
rows (`_write_main_rows`, donated — the StagingPool-style bounded-
device-buffer discipline: the hot pool IS the bound). Demotion is the
reverse: a device gather readback lands the rows in the cold store and
frees the device rows. Both are BIT-EXACT moves — a float32 row is the
same bits on either side — so residency changes can never change what a
Pull/Push/serve lookup returns (the tentpole's bit-identity contract,
pinned by tests/test_tier.py's storm).

Discipline: mutations run under the server lock and bump the store's
residency epoch (see residency.py). The maintenance worker computes its
victim plans OUTSIDE the lock against an epoch snapshot and revalidates
under the lock before acting — stale plans are recomputed, never
dispatched (the topology_version discipline applied to residency).
"""
from __future__ import annotations

from functools import partial

import numpy as np

from ..core.store import OOB, pad_bucket

# the promotion upload programs (_write_main_rows and its wire twins)
# live on the DevicePort since ISSUE 14 (device/jaxport.py) — this
# module stays device-API-free (adapm-lint APM008)


def promote_rows(store, shard: int, slots: np.ndarray) -> int:
    """Promote cold `slots` of `shard` into the hot pool (caller holds
    the server lock). Capacity-bounded: only as many rows as the free
    list covers promote; the surplus stays cold — slower, never wrong.
    Returns the number promoted."""
    res = store.res
    slots = np.unique(np.asarray(slots, dtype=np.int64))
    slots = slots[res.dev_row[shard, slots] < 0]
    if len(slots) == 0:
        return 0
    rows = res.alloc.alloc_batch(shard, len(slots))
    take = slots[: len(rows)]
    if len(take) == 0:
        return 0
    a = pad_bucket(len(take),
                   (np.full(len(take), shard, np.int32), 0),
                   (rows.astype(np.int32), OOB),
                   minimum=store.bucket_min)
    b = a[0].shape[0]
    mode = store.coldq.mode
    if mode == "fp32":
        v = store._vals_bucket(store.coldq.read(
            np.full(len(take), shard), take), b)
        store.main = store.port.write_main_rows(store.main, a[0],
                                                a[1], v)
    else:
        # dequant-fused upload (the port's wire ingest): ship the WIRE
        # rows — half/quarter the host->device bytes — and invert the
        # format inside the donated scatter. Rows with a parked EF
        # residual (few) get their full-precision value re-set exactly
        # right after: the residual folds into the promote, so the hot
        # row carries the true long-run sum (docs/MEMORY.md contract).
        q, s, fix_pos, fix_vals = store.coldq.promote_wire(shard, take)
        qb = np.zeros((b, store.value_length), dtype=q.dtype)
        qb[: len(take)] = q
        sb = None
        if mode != "fp16":
            sb = np.zeros(b, dtype=np.float32)
            sb[: len(take)] = s
        store.main = store.port.write_main_rows_wire(
            mode, store.main, a[0], a[1], qb, sb)
        if len(fix_pos):
            f = pad_bucket(len(fix_pos),
                           (np.full(len(fix_pos), shard, np.int32), 0),
                           (rows[fix_pos].astype(np.int32), OOB),
                           minimum=store.bucket_min)
            fv = store._vals_bucket(fix_vals, f[0].shape[0])
            store.main = store.port.write_main_rows(store.main, f[0],
                                                    f[1], fv)
    res.dev_row[shard, take] = rows
    res.row_slot[shard, rows] = take
    res.epoch += 1
    return len(take)


def demote_rows(store, shard: int, slots: np.ndarray) -> int:
    """Demote hot `slots` of `shard` back to the cold store (caller
    holds the server lock). The readback synchronizes with every
    enqueued program on the pool (dispatch order), so the landed bits
    are the row's current authoritative value. Returns rows demoted."""
    res = store.res
    slots = np.unique(np.asarray(slots, dtype=np.int64))
    rows = res.dev_row[shard, slots]
    m = rows >= 0
    slots, rows = slots[m], rows[m]
    if len(slots) == 0:
        return 0
    vals = store.read_hot_rows_at(
        np.full(len(rows), shard, dtype=np.int32), rows.astype(np.int32))
    # land the readback in the cold tier's at-rest format; quantized
    # modes park the sub-grid remainder as the demote's EF residual
    # (folded back in at the next promote — docs/MEMORY.md contract)
    store.coldq.set_at(np.full(len(slots), shard), slots, vals)
    res.dev_row[shard, slots] = -1
    res.row_slot[shard, rows] = -1
    res.alloc.free_batch(shard, rows)
    res.epoch += 1
    return len(slots)


def release_rows(store, shards: np.ndarray, slots: np.ndarray) -> None:
    """Free the residency of slots leaving the store entirely (slot
    free on relocation/abandonment): the hot rows are returned WITHOUT a
    copy-back — the caller has already read the authoritative value out.
    Caller holds the server lock."""
    res = store.res
    if res is None or len(slots) == 0:
        return
    shards = np.asarray(shards, dtype=np.int64).ravel()
    slots = np.asarray(slots, dtype=np.int64).ravel()
    changed = False
    for s in np.unique(shards):
        sl = slots[shards == s]
        rows = res.dev_row[s, sl]
        hot = rows >= 0
        if hot.any():
            res.row_slot[s, rows[hot]] = -1
            res.alloc.free_batch(int(s), rows[hot])
            res.dev_row[s, sl[hot]] = -1
            changed = True
        res.score[s, sl] = 0
        res.pin_until[s, sl] = -1
        # the slot's value has left the store: its parked EF residual
        # must not leak onto whatever key reuses the slot
        store.coldq.drop_resid(np.full(len(sl), int(s)), sl)
    if changed:
        res.epoch += 1


def _count_demotions(server, n: int) -> None:
    """Fold victim demotions into tier.demotions (the promotions/
    demotions pair must balance occupancy, so EVERY demote_rows path
    counts — eviction victims included, not just the pressure worker
    and the tooling surface)."""
    if n and getattr(server, "tier", None) is not None:
        server.tier.c_demotions.inc(n)


def _pick_victims(store, shard: int, need: int, min_clock: int,
                  protect: np.ndarray,
                  force: bool = False) -> np.ndarray:
    """Lowest-score, unpinned hot slots of `shard` (up to `need`), never
    from `protect` (the batch being made hot right now). `force=True`
    falls back to PINNED rows (still never `protect`) when unpinned
    victims alone cannot cover `need` — the fused-step path, where the
    current batch being hot is a correctness requirement and an older
    pin is only a performance hint."""
    res = store.res
    rows = np.nonzero(res.row_slot[shard] >= 0)[0]
    if len(rows) == 0:
        return np.empty(0, dtype=np.int64)
    slots = res.row_slot[shard, rows].astype(np.int64)
    if len(protect):
        slots = slots[~np.isin(slots, protect)]
    unpinned = slots[~res.pinned_mask(shard, slots, min_clock)]
    cand = unpinned
    if force and len(unpinned) < need:
        pinned = slots[res.pinned_mask(shard, slots, min_clock)]
        cand = np.concatenate([unpinned, pinned])
        # prefer unpinned victims; overflow into pinned by score
        if len(cand) > need:
            extra = need - len(unpinned)
            sc = res.score[shard, pinned]
            pick = pinned[np.argpartition(sc, extra - 1)[:extra]] \
                if extra < len(pinned) else pinned
            return np.concatenate([unpinned, pick])
        return cand
    if len(cand) <= need:
        return cand
    sc = res.score[shard, cand]
    idx = np.argpartition(sc, need - 1)[:need]
    return cand[idx]


def ensure_hot_rows(server, store, shards: np.ndarray, slots: np.ndarray,
                    min_clock: int = 0, force: bool = False) -> int:
    """Promote any cold rows among (shards, slots), demoting low-score
    unpinned victims when a shard's hot pool is full (caller holds the
    server lock). `force=True` (the fused-step path) additionally evicts
    PINNED victims — never the batch itself — and raises when even that
    cannot fit the batch (the batch's own unique rows exceed the hot
    pool: a configuration error, like a full cache pool). Returns rows
    promoted."""
    res = store.res
    n = 0
    for s in np.unique(shards):
        s = int(s)
        sl = np.unique(slots[shards == s]).astype(np.int64)
        cold = sl[res.dev_row[s, sl] < 0]
        if len(cold) == 0:
            continue
        if force:
            short = len(cold) - res.alloc.num_free(s)
            if short > 0:
                victims = _pick_victims(store, s, short, min_clock, sl,
                                        force=True)
                if len(victims):
                    _count_demotions(server,
                                     demote_rows(store, s, victims))
            got = promote_rows(store, s, cold)
            if got < len(cold):
                raise RuntimeError(
                    f"tier hot pool exhausted on shard {s}: a fused "
                    f"step needs {len(cold)} cold rows hot but only "
                    f"{got} fit (hot_rows={res.hot_rows}); raise "
                    f"--sys.tier.hot_rows above the step's per-shard "
                    f"unique-key working set")
            n += got
            continue
        # background (non-forced) policy — anti-thrash: PINNED cold
        # candidates (live intent windows) outrank unpinned residents
        # and may demote them; unpinned candidates fill free capacity
        # and beyond that evict only STRICTLY lower-scored unpinned
        # residents (equal scores never churn)
        is_pin = res.pinned_mask(s, cold, min_clock)
        pc, uc = cold[is_pin], cold[~is_pin]
        n_pinned, n_unpinned = len(pc), len(uc)
        n_victims = n_beat = 0
        if len(pc):
            short = len(pc) - res.alloc.num_free(s)
            if short > 0:
                victims = _pick_victims(store, s, short, min_clock, sl)
                n_victims += len(victims)
                if len(victims):
                    _count_demotions(server,
                                     demote_rows(store, s, victims))
            n += promote_rows(store, s, pc)
        if len(uc):
            pol = server.policy
            if pol is not None and pol.active("tier"):
                # ISSUE 18 learned tier law: predicted
                # promoted-never-hit regret HOLDS this shard's
                # UNPINNED background promotions (the rows stay cold —
                # served exactly from the cold pool, slower, never
                # wrong, so no value-preservation guard is needed).
                # Pinned candidates above and the force=True fused-step
                # path are NEVER policy-gated: those promotions are
                # intent/correctness driven, not speculative.
                if pol.consult("tier", {"n_pinned": n_pinned,
                                        "n_unpinned": n_unpinned},
                               n_pinned + n_unpinned):
                    pol.applied("tier")
                    uc = uc[:0]
        if len(uc):
            over = len(uc) - res.alloc.num_free(s)
            if over > 0:
                uc = uc[np.argsort(-res.score[s, uc], kind="stable")]
                victims = _pick_victims(store, s, over, min_clock, sl)
                n_victims += len(victims)
                if len(victims):
                    victims = victims[np.argsort(
                        res.score[s, victims], kind="stable")]
                    k = min(len(victims), len(uc))
                    beat = res.score[s, victims[:k]] < \
                        res.score[s, uc[:k]]
                    n_beat = int(beat.sum())
                    if beat.any():
                        _count_demotions(
                            server,
                            demote_rows(store, s, victims[:k][beat]))
                uc = uc[: res.alloc.num_free(s)]
            if len(uc):
                n += promote_rows(store, s, uc)
        dc = server.decisions
        if dc is not None and (n_pinned or n_unpinned):
            # ISSUE 17: this shard's promotion batch with the
            # anti-thrash verdict (pin split, victims scanned, victims
            # strictly beaten); the promoted rows open an outcome
            # window probing re-touch-while-hot
            dc.record_tier(store, s, np.concatenate((pc, uc)),
                           n_pinned, n_unpinned, n_victims, n_beat,
                           min_clock)
    return n


class PromotionEngine:
    """The tier maintenance worker, as a self-rescheduling executor
    task on the `tier` stream (adapm_tpu/exec; the dedicated thread +
    condvar this class owned before PR 6 is subsumed by the executor's
    worker pool). Each pass:

      1. drains the residency `want` queues (cold-miss and intent
         promotion requests) into batched `ensure_hot_rows` calls —
         DOUBLE-BUFFERED: the host-side prep of chunk N+1 (dedup,
         coordinate split) runs on the `tier` stream while chunk N's
         device scatter — committed on the `tier_commit` stream — is
         still in flight (GraphVite's episodic transfer/compute
         overlap; the exec.overlap_fraction gauge measures it);
      2. pressure-demotes: keeps a bounded free-row headroom per shard
         so hot-path promotions rarely wait on a victim readback;
      3. decays the access scores periodically (the CLOCK sweep).

    Every mutating batch takes the server lock for revalidation +
    ENQUEUE only (dispatch never — the lock-narrowing rule,
    docs/EXECUTOR.md); candidate scans run outside it and revalidate
    via the residency epoch. `run_once()` exposes one synchronous pass
    for deterministic tests/tooling. A pass that moved rows reschedules
    itself; an idle pass parks (no queued task — the executor worker
    parks on its condvar, pinned by scripts/exec_overlap_check.py)."""

    _INTERVAL_S = 0.02
    _DECAY_EVERY = 64

    def __init__(self, server, opts, manager):
        self.server = server
        self.opts = opts
        self.manager = manager
        self._stop = False
        self._passes = 0

    # -- producer ------------------------------------------------------------

    def kick(self) -> None:
        """Queue one maintenance pass (coalesced: a pass already queued
        absorbs the kick; a running pass reschedules itself while it
        finds work)."""
        if self._stop:
            return
        self.server.exec.submit("tier", self._pass,
                                label="tier.maintain",
                                coalesce_key="tier.maintain")

    # -- worker --------------------------------------------------------------

    def _pass(self) -> None:
        from ..utils import alog
        if self._stop:
            return
        delay = self._INTERVAL_S
        try:
            moved = self.run_once()
        except Exception as e:  # noqa: BLE001 — keep the worker up
            # retry after a backoff (the pre-PR thread loop's behavior):
            # a transient failure must not strand queued wants, pressure
            # demotion, and the CLOCK decay until the next external kick
            moved = 1
            delay = self._INTERVAL_S * 5
            alog(f"[tier] maintenance pass failed: "
                 f"{type(e).__name__}: {e}")
        if moved and not self._stop:
            # work found (or a failed pass retrying): keep draining at
            # the maintenance cadence
            self.server.exec.submit("tier", self._pass,
                                    label="tier.maintain",
                                    coalesce_key="tier.maintain",
                                    delay=delay)

    def run_once(self) -> int:
        """One maintenance pass (see class doc). Safe to call from any
        thread; takes the server lock internally per batch. Returns the
        number of rows moved (0 = the pass was a no-op)."""
        srv = self.server
        mgr = self.manager
        moved = 0
        min_clock = mgr._min_active_clock()
        batch = max(1, self.opts.tier_demote_batch)
        ex = srv.exec
        # double-buffering needs a second worker to run the commit
        # while this pass preps the next chunk; the serialized fallback
        # (--sys.exec.single_stream) and a closing executor commit
        # inline — same results, no overlap
        pipelined = (not ex.single_stream and not ex.closed
                     and ex.max_workers >= 2)
        for st in srv.stores:
            res = st.res
            # 1. drain promotion wants — deduplicated, then processed in
            # bounded chunks so no single lock hold scans an unbounded
            # batch (the whole drained set IS processed this pass; a
            # capped-and-dropped remainder would silently starve
            # intent-pinned promotions behind access-driven noise).
            # Capture the list OBJECT, then rebind: a lock-free
            # request_promote racing the swap lands its append either in
            # the captured list (processed now) or the fresh one
            # (processed next pass) — a copy-then-clear would drop it.
            wants = res.want
            res.want = []
            if wants:
                sh = np.concatenate([w[0] for w in wants]).astype(np.int64)
                sl = np.concatenate([w[1] for w in wants]).astype(np.int64)
                pair = np.unique(sh * np.int64(res.main_slots) + sl)
                # DOUBLE-BUFFERED drain: chunk N commits (server lock ->
                # revalidate -> cold-row copy -> device scatter enqueue)
                # on the `tier_commit` stream while this pass preps
                # chunk N+1's coordinates on the `tier` stream — at most
                # one commit in flight, so host prep of batch N+1
                # overlaps the device scatter of batch N and nothing
                # runs unboundedly ahead
                prev = None
                for lo in range(0, len(pair), 4 * batch):
                    p = pair[lo: lo + 4 * batch]
                    csh = (p // res.main_slots).astype(np.int32)
                    csl = (p % res.main_slots).astype(np.int32)
                    commit = partial(self._commit_chunk, st, csh, csl,
                                     min_clock)
                    if pipelined:
                        cur = ex.submit("tier_commit", commit,
                                        label="tier.promote_commit")
                    else:
                        cur = None
                        moved += commit()
                    if prev is not None:
                        moved += self._commit_result(prev)
                    prev = cur
                if prev is not None:
                    moved += self._commit_result(prev)
            # 2. pressure demotion: keep a MODEST free-row headroom per
            # shard so hot-path promotions rarely pay a victim readback
            # — bounded by a fraction of the pool, NOT the raw batch
            # knob (a target above the pool size would demote every
            # unpinned row every pass, a permanent demote/promote storm)
            target = min(batch, max(1, res.hot_rows // 8))
            for s in range(res.num_shards):
                free = res.alloc.num_free(s)
                if free >= target:
                    continue
                # plan outside the lock; revalidate epoch under it
                epoch = res.epoch
                victims = _pick_victims(st, s, target - free, min_clock,
                                        np.empty(0, dtype=np.int64))
                if len(victims) == 0:
                    continue
                with srv._lock:
                    if res.epoch != epoch:
                        # residency moved underneath the scan: replan
                        victims = _pick_victims(
                            st, s, target - res.alloc.num_free(s),
                            min_clock, np.empty(0, dtype=np.int64))
                    n = demote_rows(st, s, victims) if len(victims) else 0
                if n:
                    moved += n
                    mgr.c_demotions.inc(n)
                    dc = srv.decisions
                    if dc is not None:
                        # ISSUE 17: headroom-reclaim demotion (outcome
                        # immediate — its cost surfaces as later
                        # promotions' regret, not its own)
                        dc.record_tier_demote(s, n, free, target)
        # 3. score decay
        self._passes += 1
        if self._passes % self._DECAY_EVERY == 0:
            for st in srv.stores:
                st.res.decay()
        return moved

    def _commit_chunk(self, st, sh: np.ndarray, sl: np.ndarray,
                      min_clock: int) -> int:
        """Commit one promotion chunk: server lock -> coordinate
        revalidation -> program enqueue (the lock-narrowing rule —
        dispatch itself is async under the gate)."""
        srv = self.server
        if srv.fault is not None:
            # ISSUE 10 injection point: fires BEFORE the commit takes
            # the lock or moves any row, so a retried commit (executor
            # policy on `tier_commit`, or _pass's own backoff retry
            # when inline) re-runs cleanly; the wanted rows stay cold
            # until a commit succeeds — slower, never wrong
            srv.fault.fire("tier.promote")
        with srv._lock:
            n = ensure_hot_rows(srv, st, sh, sl, min_clock=min_clock)
        if n:
            self.manager.c_promotions.inc(n)
            wt = srv.wtrace
            if wt is not None:
                # promotion decision as it landed (ISSUE 15):
                # observational — replay's candidate tier policy
                # re-decides; the recorded stream is the baseline
                wt.record_decision("promote", n)
        return n

    @staticmethod
    def _commit_result(completion) -> int:
        """Join one in-flight commit; a commit cancelled by executor
        close counts zero (teardown path)."""
        n = completion.result(timeout=60)
        return int(n or 0)

    def close(self) -> None:
        """Stop the worker (idempotent; drains the tier streams so no
        maintenance pass can outlive the server into pool teardown)."""
        self._stop = True
        ex = self.server.exec
        if not ex.closed:
            if not ex.drain("tier", timeout=30) or \
                    not ex.drain("tier_commit", timeout=30):
                from ..utils import alog
                alog("[tier] maintenance pass failed to drain within "
                     "30s of close")
