"""Quantized cold-tier storage + the shared wire-format transforms
(ISSUE 8 tentpole, half a: the compression plane's at-rest side).

The cold store holds the authoritative value of every non-device-hot
main row. At beyond-HBM scale its host bytes ARE the scaling wall
(ROADMAP item 3), so `--sys.tier.cold_dtype` trades precision for
bytes/row with an EXPLICIT numeric contract (docs/MEMORY.md "Cold-row
numeric contract") instead of a silent quality loss:

  fp32   4L bytes/row — bit-identical, the pre-PR pin (default).
  fp16   2L bytes/row — exact where the value is fp16-representable;
         otherwise round-to-nearest-even with per-row error feedback.
  int8   L + 4 bytes/row — symmetric per-row scale (max-abs / 127,
         itself rounded through fp16 so the wire scale matches the
         2-byte scale column a real transport would ship); exact on
         the row's int grid, error-compensated otherwise.

Error feedback (the EF-SGD residual loop, applied to storage): every
lossy write folds the row's true fp32 value — stored quantized value
plus any parked residual plus the incoming update — and re-quantizes;
the new sub-grid remainder is parked host-side in a bounded residual
map and folded into the NEXT promote / write / relocation. The visible
value of a cold row is always the DEQUANTIZED stored value (device
gathers, host reads, and checkpoints agree bit-for-bit — residuals are
private state, never read), so per-element error is bounded by half a
grid step at all times and repeated promote/demote/write cycles cannot
drift unboundedly: the long-run sum is preserved up to fp32 rounding.

The residual map is BOUNDED (`resid_cap` rows). Overflow evicts the
oldest entry, injecting at most one half grid step of error once —
counted in `tier.ef_evicted` so a workload outrunning the cap is
visible, never silent. Rows whose quantization is exact never hold an
entry, so the fp16-representable / int-grid cases cost zero residual
bytes (the "exact" half of the contract).

Host and device MUST dequantize identically: the jitted dequant-fused
programs (device/jaxport.py) use the same IEEE f32 ops —
f16<->f32 converts are exact/RTNE on both, and `round` is
half-to-even in both numpy and XLA — so a cold row reads the same bits
through the fused device gather and the host bulk-read path.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

COLD_DTYPES = ("fp32", "fp16", "int8")
SYNC_COMPRESS_MODES = ("off", "fp16", "int8")

# bytes of the per-row scale column on the wire (int8 modes): the f32
# scale is rounded through fp16, so a transport ships 2 bytes
_SCALE_WIRE_BYTES = 2

# largest finite fp16 value. Every f16 cast below clips to it first: a
# value (or int8 scale) beyond the range would cast to inf, and an inf
# stored/shipped value poisons the EF loop with inf - inf = NaN. The
# clipped excess rides the residual like any other remainder — for
# at-rest rows the visible value SATURATES at the format max until a
# promote folds the residual back (an inherent fp16-format limit; the
# two-grid-step bound applies to in-range values). Must stay equal to
# core/store.py F16_MAX: device and host transforms agree bitwise.
F16_MAX = np.float32(65504.0)


def grid_step(mode: str, rows: np.ndarray) -> np.ndarray:
    """Per-row quantization grid step of `mode` for f32 `rows` of shape
    [..., L]: the unit the numeric contract (docs/MEMORY.md "Cold-row
    numeric contract") is stated in — visible error is bounded by TWO
    of these (one at-rest rounding + one parked residual's slack). The
    single source the drift storm tests, the CI guard
    (scripts/compress_drift_check.py), and the bench drift curve all
    import."""
    m = np.max(np.abs(rows), axis=-1)
    if mode == "fp16":
        return m * np.float32(2.0 ** -11)
    if mode == "int8":
        return m / np.float32(127.0)
    raise ValueError(f"no grid step for mode {mode!r}")


def wire_bytes_per_row(mode: str, value_length: int) -> int:
    """Bytes one row (or one shipped delta) of `value_length` f32
    elements costs in wire/at-rest format `mode` ("off"/"fp32" = full
    width)."""
    if mode in ("off", "fp32"):
        return 4 * value_length
    if mode == "fp16":
        return 2 * value_length
    if mode == "int8":
        return value_length + _SCALE_WIRE_BYTES
    raise ValueError(f"unknown compression mode {mode!r}")


def int8_scale(rows: np.ndarray) -> np.ndarray:
    """Symmetric per-row int8 scale: max-abs / 127, rounded through
    fp16 (the 2-byte wire scale; clipped to the f16 range — see
    F16_MAX). f32 in, f32 out."""
    s = (np.max(np.abs(rows), axis=-1) / np.float32(127.0))
    return np.clip(s, 0.0, F16_MAX).astype(np.float16).astype(np.float32)


def quantize_rows(mode: str, rows: np.ndarray
                  ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """f32 rows -> (wire rows, per-row scale or None). The transform
    the device programs invert; see module doc for the exactness
    contract."""
    rows = np.ascontiguousarray(rows, dtype=np.float32)
    if mode == "fp32":
        return rows, None
    if mode == "fp16":
        return np.clip(rows, -F16_MAX, F16_MAX).astype(np.float16), None
    if mode == "int8":
        s = int8_scale(rows)
        safe = np.where(s > 0, s, np.float32(1.0)).astype(np.float32)
        q = np.clip(np.round(rows / safe[..., None]), -127, 127)
        return q.astype(np.int8), s
    raise ValueError(f"unknown cold dtype {mode!r}")


def dequantize_rows(mode: str, q: np.ndarray,
                    scale: Optional[np.ndarray]) -> np.ndarray:
    """Invert quantize_rows (the VISIBLE value of a stored row)."""
    if mode == "fp32":
        return np.asarray(q, dtype=np.float32).copy()
    if mode == "fp16":
        return q.astype(np.float32)
    if mode == "int8":
        return q.astype(np.float32) * scale[..., None]
    raise ValueError(f"unknown cold dtype {mode!r}")


def compress_delta(mode: str, dvals: np.ndarray
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """One sync round's wire transform on host: f32 deltas ->
    (shipped f32 values as the receiver reconstructs them, EF
    residual). Bit-for-bit the same result as the jitted
    `_sync_replicas_compressed` program (core/store.py) — the tiered
    cold-owner sync path (tier/coldpath.py) runs this on host and must
    agree with the device rounds."""
    dvals = np.ascontiguousarray(dvals, dtype=np.float32)
    q, s = quantize_rows(mode, dvals)
    shipped = dequantize_rows(mode, q, s)
    return shipped, dvals - shipped


class QuantCold:
    """One length class's cold store in `mode` format (see module doc).

    API mirrors the raw ndarray ops tier/coldpath.py used against the
    fp32 array, so fp32 mode is a bit-identical passthrough:

      read(sh, sl)          visible f32 rows (deq; fancy-index copy)
      add_at(sh, sl, rows)  additive merge, in-batch duplicates
                            accumulating in batch order (np.add.at)
      set_at(sh, sl, rows)  overwrite (duplicate coords: last wins)
      take_true(sh, sl)     full-precision rows (deq + residual),
                            CONSUMING the residuals — the move/promote
                            read
      promote_wire(...)     wire rows for the dequant-fused promotion
                            scatter + the residual fixups, consumed

    Mutating calls run under the server lock (the cold store is part
    of the residency-guarded state); gauges read lock-free.
    """

    def __init__(self, num_shards: int, main_slots: int,
                 value_length: int, mode: str = "fp32",
                 resid_cap: int = 65536):
        if mode not in COLD_DTYPES:
            raise ValueError(
                f"--sys.tier.cold_dtype must be one of {COLD_DTYPES} "
                f"(got {mode!r})")
        self.mode = mode
        self.value_length = value_length
        self.num_shards = num_shards
        self.main_slots = main_slots
        np_dtype = {"fp32": np.float32, "fp16": np.float16,
                    "int8": np.int8}[mode]
        self.q = np.zeros((num_shards, main_slots, value_length),
                          dtype=np_dtype)
        self.scale = (np.zeros((num_shards, main_slots), dtype=np.float32)
                      if mode == "int8" else None)
        # parked sub-grid remainders, (shard, slot) -> f32 row; bounded
        # (dict preserves insertion order -> FIFO eviction)
        self.resid: Dict[Tuple[int, int], np.ndarray] = {}
        self.resid_cap = max(1, resid_cap)
        self.ef_evicted = 0   # residual rows dropped at the cap
        self.ef_folds = 0     # lossy write events that re-quantized

    # -- geometry / accounting (gauges; lock-free reads) -----------------

    @property
    def shape(self):
        return self.q.shape

    def nbytes(self) -> int:
        """Actual host bytes of the cold tier: stored rows + scale
        column + parked residuals (tier.cold_bytes_per_row counts ALL
        of it — the honest bytes/row, not just the dense array)."""
        n = self.q.nbytes
        if self.scale is not None:
            n += self.scale.nbytes
        n += len(self.resid) * self.value_length * 4
        return n

    def bytes_per_row(self) -> float:
        return self.nbytes() / float(self.num_shards * self.main_slots)

    def resid_rows(self) -> int:
        return len(self.resid)

    # -- internal helpers ------------------------------------------------

    def _true_rows(self, sh: np.ndarray, sl: np.ndarray,
                   consume: bool) -> np.ndarray:
        """deq + parked residual per (sh, sl) entry; consume=True
        deletes the folded residual entries (move/promote semantics)."""
        out = dequantize_rows(
            self.mode, self.q[sh, sl],
            self.scale[sh, sl] if self.scale is not None else None)
        if self.resid:
            for i, (s, l) in enumerate(zip(sh.tolist(), sl.tolist())):
                r = self.resid.get((s, l))
                if r is not None:
                    out[i] += r
                    if consume:
                        del self.resid[(s, l)]
        return out

    def _park(self, sh: np.ndarray, sl: np.ndarray,
              resid: np.ndarray) -> None:
        """Park per-row residuals (replacing any prior entry); all-zero
        rows clear instead — exact quantizations cost no bytes. The
        common all-exact / empty-map case is a vectorized no-op (this
        runs under the server lock on every quantized cold write)."""
        self.ef_folds += 1
        nz = resid.any(axis=1)
        if not nz.any() and not self.resid:
            return
        sh_l, sl_l = sh.tolist(), sl.tolist()
        n = len(sh_l)
        pair = np.asarray(sh, np.int64) * np.int64(self.main_slots) \
            + np.asarray(sl, np.int64)
        # iterate LAST occurrences only (duplicate coordinates: last
        # wins — the fancy-assignment semantics the per-row loop had)
        _, rev_first = np.unique(pair[::-1], return_index=True)
        clearing = bool(self.resid)
        for i in ((n - 1) - rev_first):
            if nz[i]:
                self.resid[(sh_l[i], sl_l[i])] = resid[i].copy()
            elif clearing:
                self.resid.pop((sh_l[i], sl_l[i]), None)
        while len(self.resid) > self.resid_cap:
            # FIFO eviction: injects <= half a grid step once, counted
            self.resid.pop(next(iter(self.resid)))
            self.ef_evicted += 1

    def _store_rows(self, sh: np.ndarray, sl: np.ndarray,
                    vals: np.ndarray) -> None:
        """Quantize `vals` into (sh, sl) and park the remainders.
        Duplicate coordinates: last occurrence wins on BOTH the stored
        row and the residual (numpy fancy-assignment semantics)."""
        q, s = quantize_rows(self.mode, vals)
        self.q[sh, sl] = q
        if self.scale is not None:
            self.scale[sh, sl] = s
        resid = vals - dequantize_rows(self.mode, q, s)
        self._park(sh, sl, resid)

    # -- the coldpath surface --------------------------------------------

    def read(self, sh: np.ndarray, sl: np.ndarray) -> np.ndarray:
        """Visible f32 values (deq only — residuals are private)."""
        if self.mode == "fp32":
            return self.q[sh, sl]
        return dequantize_rows(
            self.mode, self.q[sh, sl],
            self.scale[sh, sl] if self.scale is not None else None)

    def take_true(self, sh: np.ndarray, sl: np.ndarray) -> np.ndarray:
        """Full-precision rows for a MOVE (relocation source): deq +
        residual, consuming the residual — the value leaves with all
        its error-feedback state."""
        if self.mode == "fp32":
            return self.q[sh, sl]
        return self._true_rows(sh, sl, consume=True)

    def drop_resid(self, sh: np.ndarray, sl: np.ndarray) -> None:
        """Forget residuals of slots leaving the store entirely
        (release/abandon after the caller already took the value)."""
        if self.mode == "fp32" or not self.resid:
            return
        for s, l in zip(np.asarray(sh).tolist(), np.asarray(sl).tolist()):
            self.resid.pop((s, l), None)

    def add_at(self, sh: np.ndarray, sl: np.ndarray,
               rows: np.ndarray) -> None:
        """Additive merge on the authoritative cold rows; in-batch
        duplicates accumulate in batch order (np.add.at semantics on
        every mode — the fold runs on the duplicate-accumulated true
        values, so no update is lost below the grid)."""
        if self.mode == "fp32":
            np.add.at(self.q, (sh, sl), rows)
            return
        rows = np.ascontiguousarray(rows, dtype=np.float32)
        pair = sh.astype(np.int64) * np.int64(self.main_slots) \
            + sl.astype(np.int64)
        upair, first, inv = np.unique(pair, return_index=True,
                                      return_inverse=True)
        ush, usl = sh[first], sl[first]
        true = self._true_rows(ush, usl, consume=True)
        np.add.at(true, inv, rows)
        self._store_rows(ush, usl, true)

    def set_at(self, sh: np.ndarray, sl: np.ndarray,
               rows: np.ndarray) -> None:
        """Overwrite rows (set / demote / relocation landing): prior
        residuals are discarded — a set REPLACES the sum — and the new
        sub-grid remainder parks."""
        if self.mode == "fp32":
            self.q[sh, sl] = rows
            return
        self._store_rows(sh, sl,
                         np.ascontiguousarray(rows, dtype=np.float32))

    def wire(self, sh: np.ndarray, sl: np.ndarray
             ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """Stored wire rows (+ scales) for the dequant-fused device
        gather — what a transport would ship for these rows."""
        return (self.q[sh, sl],
                self.scale[sh, sl] if self.scale is not None else None)

    def promote_wire(self, shard: int, slots: np.ndarray):
        """Promotion payload for `slots` of `shard`: (wire rows, scales
        or None, fixup positions, fixup f32 rows). Rows with a parked
        residual are listed as fixups carrying their full-precision
        value (deq + residual, residual consumed) — the promotion
        scatter uploads the wire rows fused with the dequant, then
        overwrites the (few) fixup rows exactly (tier/promote.py)."""
        q = self.q[shard, slots]
        s = self.scale[shard, slots] if self.scale is not None else None
        fix_pos = []
        fix_vals = []
        if self.mode != "fp32" and self.resid:
            for i, l in enumerate(slots.tolist()):
                r = self.resid.pop((shard, l), None)
                if r is not None:
                    fix_pos.append(i)
                    fix_vals.append(
                        dequantize_rows(
                            self.mode, q[i],
                            s[i] if s is not None else None) + r)
        fp = np.asarray(fix_pos, dtype=np.int64)
        fv = (np.stack(fix_vals).astype(np.float32) if fix_vals
              else np.empty((0, self.value_length), np.float32))
        return q, s, fp, fv

    def full(self) -> np.ndarray:
        """The whole cold table, dequantized to f32 (checkpoint /
        full-table assembly — inherently a full-size materialization)."""
        if self.mode == "fp32":
            return self.q.copy()
        return dequantize_rows(self.mode, self.q, self.scale)

    def install_full(self, arr: np.ndarray) -> None:
        """Checkpoint restore: re-quantize the full table shard by
        shard (bounds the transient to one shard of f32 temporaries)
        and drop all residuals — idempotent for values already on the
        grid, so a save/restore round trip of a quantized store is
        value-stable."""
        assert arr.shape == self.q.shape, (
            f"main table geometry mismatch: checkpoint {arr.shape} vs "
            f"cold store {self.q.shape}")
        if self.mode == "fp32":
            self.q[:] = np.asarray(arr, dtype=np.float32)
            return
        self.resid.clear()
        for s in range(self.num_shards):
            q, sc = quantize_rows(
                self.mode, np.asarray(arr[s], dtype=np.float32))
            self.q[s] = q
            if self.scale is not None:
                self.scale[s] = sc
