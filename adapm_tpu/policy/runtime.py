"""Live policy plane: learned-mode vetoes and shadow A/B scoring
(ISSUE 18 tentpole d).

One `PolicyPlane` per Server when `--sys.policy.file` names a trained
artifact (policy/train.py); default **off** — `Server.policy is None`,
every hook site pays one `is None` check (the r7 skip-wrapper
discipline), and the registry holds zero `policy.*` names (pinned by
`scripts/metrics_overhead_check.py`; `policy` is an adapm-lint
OPTIONAL_HANDLE).

Per decision plane, `--sys.policy.<plane>` selects:

  `heuristic`  (default) the hand-tuned law decides, exactly as before.
               With `--sys.policy.shadow 1` the learned model is ALSO
               scored at each decision — `policy.shadow_agree` /
               `policy.shadow_disagree` count whether it would have
               done the same — but its verdict is never applied (the
               observer-effect pin: shadow on/off replays produce
               identical reads digests).
  `learned`    the model's regret prediction may VETO the heuristic's
               action (hold a background promotion, skip a landed
               move, dirty-filter a ship, keep the serve window).
               The veto is the ONLY power the policy has — it never
               proposes an action the heuristic would not take — and
               each hook site applies it through a value-preservation
               guard (see the site comments in core/kv.py,
               tier/promote.py, core/sync.py, obs/slo.py): a policy
               changes *what/when*, never *values*, so any
               value-preserving replay reproduces the heuristic
               `reads_digest` bitwise. `policy.guard_vetoes_total`
               counts verdicts the guard refused to apply.

Promotion gate: `learned` is only worth turning on after
`replay.rank_candidates` over {heuristic, learned} ranks learned at or
above the heuristic on the plane's regret objective
(docs/POLICY.md; scripts/policy_gate_check.py enforces it for tier in
CI).

Thread safety: hook sites consult concurrently; per-plane tallies are
folded under one small lock (counter bumps + dict increments only —
never a device wait, never the server lock).
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

from .features import core_features
from .model import PolicyBundle, load_policy

PLANE_KNOBS = ("reloc", "tier", "sync", "serve")
POLICY_MODES = ("heuristic", "learned")


class PolicyPlane:
    """Owned and built by the Server (core/kv.py) when
    `--sys.policy.file` is set; stateless between consults apart from
    tallies — the models themselves are immutable after load."""

    def __init__(self, server, opts=None):
        from ..obs.metrics import Counter
        o = opts if opts is not None else server.opts
        self._server = server
        self.modes: Dict[str, str] = {
            "reloc": o.policy_reloc, "tier": o.policy_tier,
            "sync": o.policy_sync, "serve": o.policy_serve}
        self.shadow = bool(o.policy_shadow)
        self.file = o.policy_file
        self.bundle: PolicyBundle = load_policy(o.policy_file)
        # planes worth paying the feature read for: learned mode, or
        # shadow scoring — in both cases only when the artifact
        # actually shipped a model for the plane
        self._active = frozenset(
            p for p in PLANE_KNOBS if p in self.bundle.planes and
            (self.modes[p] == "learned" or self.shadow))
        self._lock = threading.Lock()
        z = {"consults": 0, "vetoes": 0, "applied": 0,
             "guard_blocked": 0, "agree": 0, "disagree": 0}
        self._tallies = {p: dict(z) for p in PLANE_KNOBS}
        # serve batch-window observations (serve/batcher.py): how the
        # live windows actually close — the denominator a shadow A/B
        # reads the serve model against (docs/POLICY.md runbook)
        self._batch_window_limited = 0
        self._batch_size_limited = 0
        reg = server.obs
        if reg is not None and reg.enabled:
            self.c_consults = reg.counter("policy.consults_total")
            self.c_applied = reg.counter("policy.applied_total")
            self.c_guard = reg.counter("policy.guard_vetoes_total")
            self.c_agree = reg.counter("policy.shadow_agree")
            self.c_disagree = reg.counter("policy.shadow_disagree")
        else:  # works with --sys.metrics 0 (standalone tallies)
            self.c_consults = Counter("policy.consults_total")
            self.c_applied = Counter("policy.applied_total")
            self.c_guard = Counter("policy.guard_vetoes_total")
            self.c_agree = Counter("policy.shadow_agree")
            self.c_disagree = Counter("policy.shadow_disagree")

    # -- hook-site API -------------------------------------------------------

    def active(self, plane: str) -> bool:
        """Cheap pre-check for hook sites: is there anything to score
        here? False for heuristic-mode planes with shadow off — the
        site then skips even building its extras dict."""
        return plane in self._active

    def consult(self, plane: str, extras: Dict, batch_n: int) -> bool:
        """Score the plane's model on the live features. In `learned`
        mode returns the veto verdict (True = hold the heuristic's
        action, subject to the SITE's value-preservation guard). In
        shadow mode the verdict only feeds the agree/disagree counters
        — the heuristic's action (always: proceed) is applied, so the
        return is False by construction."""
        if plane not in self._active:
            return False
        m = self.bundle.planes[plane]
        f = core_features(self._server, batch_n)
        f.update(extras)
        verdict = m.veto(f)
        self.c_consults.inc()
        learned = self.modes[plane] == "learned"
        with self._lock:
            t = self._tallies[plane]
            t["consults"] += 1
            if learned:
                if verdict:
                    t["vetoes"] += 1
            elif verdict:
                t["disagree"] += 1
            else:
                t["agree"] += 1
        if not learned:  # shadow: scored, never applied
            (self.c_disagree if verdict else self.c_agree).inc()
            return False
        return verdict

    def applied(self, plane: str) -> None:
        """The site's value-preservation guard admitted the veto and
        the heuristic's action was held."""
        self.c_applied.inc()
        with self._lock:
            self._tallies[plane]["applied"] += 1

    def guard_blocked(self, plane: str) -> None:
        """The guard refused the veto (applying it could have changed
        read values) — the heuristic's action proceeded."""
        self.c_guard.inc()
        with self._lock:
            self._tallies[plane]["guard_blocked"] += 1

    def note_batch(self, window_limited: bool) -> None:
        """serve/batcher.py per-batch close reason: the window expired
        (coalescing lever bound) vs the batch filled first."""
        with self._lock:
            if window_limited:
                self._batch_window_limited += 1
            else:
                self._batch_size_limited += 1

    # -- snapshot ------------------------------------------------------------

    def stats(self) -> Dict:
        """Plain-value summary for `metrics_snapshot()["policy"]` (the
        registry-backed policy.* counters land in the same section)."""
        with self._lock:
            out: Dict = {"file": self.file, "shadow": self.shadow,
                         "planes_loaded":
                             sorted(self.bundle.planes),
                         "batch_window_limited":
                             self._batch_window_limited,
                         "batch_size_limited":
                             self._batch_size_limited}
            for p in PLANE_KNOBS:
                out[f"mode.{p}"] = self.modes[p]
                t = self._tallies[p]
                out[f"consults.{p}"] = t["consults"]
                out[f"vetoes.{p}"] = t["vetoes"]
                out[f"applied.{p}"] = t["applied"]
                out[f"guard_blocked.{p}"] = t["guard_blocked"]
                out[f"shadow_agree.{p}"] = t["agree"]
                out[f"shadow_disagree.{p}"] = t["disagree"]
        return out
