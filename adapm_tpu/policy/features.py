"""ONE shared feature-extraction module for the decision planes
(ISSUE 18 tentpole a).

Both halves of the policy loop import THIS module:

  - capture (`obs/decisions.py DecisionRecorder`) stamps every decision
    event's `features` dict through `core_features()` plus the plane's
    extra fields, and
  - runtime inference (`policy/runtime.py PolicyPlane`) builds the
    model input through the SAME `core_features()` + `vectorize()`,

so train/serve skew is impossible by construction: a feature the model
was fit on is, by definition, a feature the live site computes the
same way. `PLANE_FEATURES` is the other half of that contract — the
ORDERED per-plane input spec. Training (`policy/train.py`) selects
exactly these columns from the dataset's `f.*` fields and `vectorize`
lays the live dict out in the same order; columns the capture records
but the spec omits (post-decision counts like `n_shipped`, verdict
tallies like `n_beat`) are visible in the dataset for analysis but can
never leak into a model input, because they are not known at the
moment the live site must decide.

Dependency-light on purpose (numpy only): `obs/decisions.py` imports
this module at the top level, so it must not pull in the obs/metrics
stack.
"""
from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

# the feature keys EVERY decision event carries (the "complete feature
# vector" contract scripts/decision_quality_check.py pins); planes add
# their own fields on top
CORE_FEATURES = ("clock", "replicas_live", "dirty_fraction",
                 "hot_free_rows", "hot_total_rows", "batch_n")

# ordered model-input spec per plane: CORE_FEATURES plus the
# plane-specific fields that are known BEFORE the action is taken at
# the live hook site (see module docstring — post-decision fields are
# deliberately excluded)
PLANE_FEATURES: Dict[str, Tuple[str, ...]] = {
    # kv._relocate_to: the landed-move veto sees the batch about to
    # move (nothing demoted yet)
    "reloc": CORE_FEATURES + ("n_moved", "n_demoted"),
    # tier ensure_hot_rows background path: the pin split is computed
    # before any promotion; victims/beaten are only known after
    "tier": CORE_FEATURES + ("n_pinned", "n_unpinned"),
    # sync_channel ship/hold: dirty count as the heuristic saw it
    # (-1 = dirty filter off, dirtiness unknown at decision time)
    "sync": CORE_FEATURES + ("n_dirty",),
    # obs/slo.py _control: the proposed window move and the tail it
    # reacts to
    "serve": CORE_FEATURES + ("old_us", "new_us", "p99_ms",
                              "target_ms"),
}


def core_features(server, batch_n: int) -> Dict:
    """The CORE_FEATURES context visible at decision time — all
    lock-free host reads (dirty fraction is the sync plane's memoized
    gauge read; hot-pool occupancy is the allocator's free-count).
    Never takes the server lock, never waits on the device."""
    sync = server.sync
    c = server._clocks
    out = {"clock": int(c.max()) if len(c) else 0,
           "replicas_live": int(sum(len(t) for t in sync.replicas)),
           "dirty_fraction": round(float(sync._dirty_fraction(None)), 6),
           "hot_free_rows": 0, "hot_total_rows": 0,
           "batch_n": int(batch_n)}
    if server.tier is not None:
        free = total = 0
        for st in server.stores:
            res = getattr(st, "res", None)
            if res is None:
                continue
            total += int(res.hot_rows) * int(res.num_shards)
            free += int(sum(res.alloc.num_free(s)
                            for s in range(res.num_shards)))
        out["hot_free_rows"] = free
        out["hot_total_rows"] = total
    return out


def vectorize(plane: str, features: Dict) -> np.ndarray:
    """Lay a feature dict out as the plane's ordered model-input
    vector (float64; missing fields are 0.0 — e.g. `hot_free_rows`
    on an untiered server). Raises KeyError for an unknown plane: a
    model for a plane this spec does not define cannot exist."""
    spec = PLANE_FEATURES[plane]
    return np.array([float(features.get(k, 0.0)) for k in spec],
                    dtype=np.float64)
