"""Tiny deterministic per-plane decision models (ISSUE 18 tentpole b).

The modeling shape is COGNATE's (PAPERS.md), not a deep net: one
pure-NumPy logistic scorer per decision plane, fit from the labeled
(features, decision, outcome) dataset by `policy/train.py`. Each model
predicts the probability that the HEURISTIC's action at this decision
point will be REGRETTED (the plane's own regret verdict from
obs/decisions.py — promoted rows never re-touched, a move immediately
undone, a fully-clean ship, a window move that pushed the tail farther
from target). The runtime (`policy/runtime.py`) uses that as a VETO
score: `learned` mode holds the heuristic's action when the predicted
regret probability crosses the threshold, and never proposes anything
the heuristic would not have done — a policy changes *what/when*,
never *values*.

Determinism, end to end:

  - inference: a fixed-order dot product over `PLANE_FEATURES`
    (policy/features.py) — no RNG, no wall clock;
  - training: zero-initialized full-batch gradient descent with fixed
    iteration count (train.py), so the same dataset + seed produces
    the same weights;
  - serialization: weights rounded to `_ROUND` decimals and written
    through the shared `write_trace_file` machinery (obs/wtrace.py) —
    a versioned, checksummed, atomically-written JSON artifact.
    `load_policy` verifies format/version/length/sha256 BEFORE parsing
    (the wtrace/dtrace/ckpt discipline): a truncated or bit-flipped
    artifact raises the named `PolicyError`, never a half-loaded
    policy steering a live server.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional

import numpy as np

from .features import PLANE_FEATURES, vectorize

POLICY_FORMAT = "adapm-policy"
POLICY_VERSION = 1

# serialization rounding: enough precision that re-loading cannot flip
# any verdict the fit produced, few enough digits that the JSON bytes
# are stable (train.py's byte-determinism contract is over the WHOLE
# artifact)
_ROUND = 10


class PolicyError(RuntimeError):
    """The policy artifact is unusable: wrong format/version, truncated
    body, checksum mismatch, malformed model block, or a plane/feature
    spec this build does not know. Raised by `load_policy` during
    verification, BEFORE anything consults the policy."""


def _sigmoid(z: np.ndarray) -> np.ndarray:
    # piecewise-stable: never overflows exp for large |z|
    out = np.empty_like(z)
    pos = z >= 0
    out[pos] = 1.0 / (1.0 + np.exp(-z[pos]))
    ez = np.exp(z[~pos])
    out[~pos] = ez / (1.0 + ez)
    return out


def fit_logistic(X: np.ndarray, y: np.ndarray,
                 sample_weight: Optional[np.ndarray] = None,
                 iters: int = 400, lr: float = 0.5,
                 l2: float = 1e-3):
    """Weighted logistic regression by zero-initialized full-batch
    gradient descent on standardized inputs — deterministic for fixed
    inputs (no RNG, no convergence-dependent early exit). Returns
    (mean, scale, weights, bias) in INPUT space semantics: score(x) =
    sigmoid(w . ((x - mean) / scale) + b)."""
    X = np.asarray(X, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    w = np.ones(len(y)) if sample_weight is None \
        else np.asarray(sample_weight, dtype=np.float64)
    wsum = float(w.sum())
    mean = (X * w[:, None]).sum(axis=0) / wsum
    var = ((X - mean) ** 2 * w[:, None]).sum(axis=0) / wsum
    scale = np.sqrt(np.maximum(var, 1e-12))
    scale[scale < 1e-6] = 1.0  # constant column: center only
    Z = (X - mean) / scale
    beta = np.zeros(X.shape[1], dtype=np.float64)
    bias = 0.0
    for _ in range(int(iters)):
        p = _sigmoid(Z @ beta + bias)
        err = (p - y) * w
        beta -= lr * ((Z.T @ err) / wsum + l2 * beta)
        bias -= lr * float(err.sum() / wsum)
    return mean, scale, beta, bias


class PlaneModel:
    """One plane's regret scorer: logistic over the plane's ordered
    feature spec (policy/features.py PLANE_FEATURES)."""

    __slots__ = ("plane", "features", "mean", "scale", "weights",
                 "bias", "threshold", "n_rows", "n_pos")

    def __init__(self, plane: str, mean, scale, weights, bias: float,
                 threshold: float = 0.5, n_rows: int = 0,
                 n_pos: int = 0):
        spec = PLANE_FEATURES.get(plane)
        if spec is None:
            raise PolicyError(f"unknown policy plane {plane!r} "
                              f"(this build knows "
                              f"{'/'.join(sorted(PLANE_FEATURES))})")
        self.plane = plane
        self.features = spec
        self.mean = np.asarray(mean, dtype=np.float64)
        self.scale = np.asarray(scale, dtype=np.float64)
        self.weights = np.asarray(weights, dtype=np.float64)
        self.bias = float(bias)
        self.threshold = float(threshold)
        self.n_rows = int(n_rows)
        self.n_pos = int(n_pos)
        for name, arr in (("mean", self.mean), ("scale", self.scale),
                          ("weights", self.weights)):
            if arr.shape != (len(spec),):
                raise PolicyError(
                    f"plane {plane!r} {name} has {arr.shape} entries; "
                    f"the {plane} feature spec has {len(spec)} — the "
                    f"artifact was trained against a different "
                    f"PLANE_FEATURES contract")

    @classmethod
    def constant(cls, plane: str, pos_rate: float, n_rows: int = 0,
                 n_pos: int = 0) -> "PlaneModel":
        """Degenerate fit (too few rows, or one label class): zero
        weights, bias at the clipped log-odds of the positive rate —
        scores the base rate for every input, deterministically."""
        p = min(max(float(pos_rate), 1e-3), 1.0 - 1e-3)
        spec = PLANE_FEATURES.get(plane)
        if spec is None:
            raise PolicyError(f"unknown policy plane {plane!r} "
                              f"(this build knows "
                              f"{'/'.join(sorted(PLANE_FEATURES))})")
        k = len(spec)
        return cls(plane, np.zeros(k), np.ones(k), np.zeros(k),
                   math.log(p / (1.0 - p)), n_rows=n_rows, n_pos=n_pos)

    def score(self, features: Dict) -> float:
        """Predicted probability that the heuristic's action at this
        decision point will be regretted."""
        z = (vectorize(self.plane, features) - self.mean) / self.scale
        return float(_sigmoid(np.array([z @ self.weights + self.bias]))
                     [0])

    def veto(self, features: Dict) -> bool:
        """True = the learned policy would HOLD the heuristic's action
        here (predicted regret crosses the threshold)."""
        return self.score(features) >= self.threshold

    def to_dict(self) -> Dict:
        return {"kind": "logistic", "plane": self.plane,
                "features": list(self.features),
                "mean": [round(float(v), _ROUND) for v in self.mean],
                "scale": [round(float(v), _ROUND) for v in self.scale],
                "weights": [round(float(v), _ROUND)
                            for v in self.weights],
                "bias": round(float(self.bias), _ROUND),
                "threshold": round(float(self.threshold), _ROUND),
                "n_rows": self.n_rows, "n_pos": self.n_pos}

    @classmethod
    def from_dict(cls, d: Dict) -> "PlaneModel":
        if d.get("kind") != "logistic":
            raise PolicyError(f"unknown model kind {d.get('kind')!r} "
                              f"for plane {d.get('plane')!r} (this "
                              f"build reads 'logistic')")
        m = cls(d["plane"], d["mean"], d["scale"], d["weights"],
                d["bias"], d.get("threshold", 0.5),
                d.get("n_rows", 0), d.get("n_pos", 0))
        if list(m.features) != list(d.get("features", [])):
            raise PolicyError(
                f"plane {m.plane!r} artifact feature order "
                f"{d.get('features')} does not match this build's "
                f"spec {list(m.features)} — retrain against this "
                f"build (the shared features.py contract)")
        return m


class PolicyBundle:
    """A verified set of per-plane models plus training provenance.
    Construction from `load_policy` implies the checksum passed."""

    __slots__ = ("path", "meta", "planes")

    def __init__(self, meta: Dict, planes: Dict[str, PlaneModel],
                 path: Optional[str] = None):
        self.path = path
        self.meta = meta
        self.planes = planes

    def to_doc(self) -> Dict:
        return {"meta": self.meta,
                "planes": {p: m.to_dict()
                           for p, m in sorted(self.planes.items())}}

    def save(self, path: str) -> int:
        """Write the artifact atomically through the shared trace-file
        machinery (one-line verified header + JSON body). Returns bytes
        written; the bytes are deterministic for a fixed bundle."""
        from ..obs.wtrace import write_trace_file
        n = write_trace_file(path, self.to_doc(), POLICY_FORMAT,
                             POLICY_VERSION)
        self.path = path
        return n


def load_policy(path: str) -> PolicyBundle:
    """Read + verify a policy artifact. Raises `PolicyError` on a
    missing/truncated/corrupt/incompatible file — named, and BEFORE
    any plane consults a model."""
    from ..obs.wtrace import load_trace_doc
    doc = load_trace_doc(path, POLICY_FORMAT, POLICY_VERSION,
                         PolicyError, "policy artifact")
    raw = doc.get("planes")
    if not isinstance(raw, dict) or not raw:
        raise PolicyError(f"policy artifact {path!r} has no plane "
                          f"models — nothing to consult")
    planes: Dict[str, PlaneModel] = {}
    for p, d in raw.items():
        try:
            planes[p] = PlaneModel.from_dict(d)
        except PolicyError:
            raise
        except Exception as e:
            raise PolicyError(f"policy artifact {path!r} plane {p!r} "
                              f"is malformed: {e}") from e
    return PolicyBundle(doc.get("meta", {}), planes, path=path)


def plane_names() -> List[str]:
    return sorted(PLANE_FEATURES)
