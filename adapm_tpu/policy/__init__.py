"""Learned adaptive-policy plane (ISSUE 18): offline-trained
relocate/tier/sync/batch-window policies, replay-gated promotion, and
live shadow A/B. See docs/POLICY.md.

Layout:

  features.py  the ONE shared feature extractor + per-plane ordered
               input specs (capture and inference both import it —
               train/serve skew is impossible by construction)
  model.py     deterministic pure-NumPy per-plane regret scorers,
               serialized as a versioned, checksummed JSON artifact
  train.py     `python -m adapm_tpu.policy.train` — fit from the
               replay/dataset.py labeled table
  runtime.py   `PolicyPlane` — the live veto/shadow hook surface
               behind `--sys.policy.*` (built by core/kv.py)
"""
from .features import CORE_FEATURES, PLANE_FEATURES, core_features, \
    vectorize
from .model import POLICY_FORMAT, POLICY_VERSION, PlaneModel, \
    PolicyBundle, PolicyError, load_policy
from .runtime import PLANE_KNOBS, POLICY_MODES, PolicyPlane
from .train import train_policy

__all__ = [
    "CORE_FEATURES", "PLANE_FEATURES", "core_features", "vectorize",
    "POLICY_FORMAT", "POLICY_VERSION", "PlaneModel", "PolicyBundle",
    "PolicyError", "load_policy", "PLANE_KNOBS", "POLICY_MODES",
    "PolicyPlane", "train_policy",
]
