"""Offline policy training: fit the per-plane regret scorers from a
capture run's traces (ISSUE 18 tentpole c).

    python -m adapm_tpu.policy.train run.dtrace run.wtrace -o policy.json

Pipeline: `replay/dataset.py export_dataset` joins the `.dtrace` and
(optionally) `.wtrace` into the labeled (features, decision, outcome)
table; per plane, the rows whose action matches the plane's live hook
site (reloc `move`, tier `promote`, sync `ship`/`hold`, serve
`shrink`/`grow`) become a training set with the plane's OWN regret
verdict as the label, and `model.fit_logistic` fits the scorer over
exactly the `PLANE_FEATURES` columns (policy/features.py — the same
module the live sites vectorize through, so train/serve skew is
impossible by construction).

Label hygiene (ISSUE 18 satellite):

  - **Unresolved rows are not labels.** A decision whose outcome
    window never resolved (dropped under the event budget, run died)
    has `regret: null` and is skipped.
  - **Forced-close rows are not labels.** A window resolved by
    `close()` at shutdown (`truncated: true`) observed an arbitrary
    prefix of its follow-up horizon — its verdict reflects when the
    run ended, not what the decision bought. These rows are
    down-weighted by `--truncated-weight` (default 0.0 = excluded)
    and counted LOUDLY: the CLI prints
    `policy.train.truncated_rows=N` and the artifact's per-plane
    `train` meta carries the count.
  - A plane with too few usable rows, or only one label class, gets
    the deterministic base-rate constant model (model.py
    `PlaneModel.constant`) — with the default 0.5 threshold it never
    vetoes unless the base regret rate itself crosses it.

Byte determinism: no RNG is consumed and no timestamp is minted — the
same dataset + seed re-trains to a byte-identical artifact
(`scripts/policy_gate_check.py` pins this).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from .features import PLANE_FEATURES
from .model import PlaneModel, PolicyBundle, fit_logistic

# dataset actions whose pre-decision features match each plane's live
# hook site (policy/features.py PLANE_FEATURES); other actions of the
# same plane (reloc `classify`, tier `demote`) are analysis-only
PLANE_ACTIONS: Dict[str, tuple] = {
    "reloc": ("move",),
    "tier": ("promote",),
    "sync": ("ship", "hold"),
    "serve": ("shrink", "grow"),
}

# below this many usable rows a gradient fit is noise — emit the
# base-rate constant model instead
MIN_FIT_ROWS = 8


def _plane_rows(rows: List[Dict], plane: str):
    """(features-dict, label, truncated) triples for one plane's
    trainable rows — resolved, labeled, action-matched."""
    out = []
    acts = PLANE_ACTIONS[plane]
    for r in rows:
        if r.get("plane") != plane or r.get("action") not in acts:
            continue
        regret = r.get("regret")
        if not r.get("resolved") or regret is None:
            continue  # no verdict: not a label
        f = {k[2:]: v for k, v in r.items() if k.startswith("f.")}
        out.append((f, bool(regret), bool(r.get("truncated"))))
    return out


def train_policy(dtrace: str, wtrace: Optional[str] = None,
                 out_path: Optional[str] = None, seed: int = 0,
                 horizon_clocks: int = 4,
                 truncated_weight: float = 0.0) -> PolicyBundle:
    """Fit all four plane models from a capture run's traces; returns
    the bundle (written to `out_path` when given). Deterministic for
    fixed inputs + seed."""
    if not (0.0 <= truncated_weight <= 1.0):
        raise ValueError(f"truncated_weight must be in [0, 1] "
                         f"(got {truncated_weight}): forced-close "
                         f"rows may be down-weighted, never "
                         f"up-weighted — they are not labels")
    from ..replay.dataset import export_dataset
    ds = export_dataset(dtrace, wtrace, horizon_clocks=horizon_clocks)
    planes: Dict[str, PlaneModel] = {}
    train_meta: Dict[str, Dict] = {}
    total_truncated = 0
    for plane in sorted(PLANE_FEATURES):
        triples = _plane_rows(ds["rows"], plane)
        n_trunc = sum(1 for _, _, t in triples if t)
        total_truncated += n_trunc
        if truncated_weight == 0.0:
            kept = [(f, y, 1.0) for f, y, t in triples if not t]
        else:
            kept = [(f, y, truncated_weight if t else 1.0)
                    for f, y, t in triples]
        n_pos = sum(1 for _, y, _ in kept if y)
        meta = {"rows": len(triples), "truncated_rows": n_trunc,
                "used": len(kept), "pos": n_pos}
        if len(kept) < MIN_FIT_ROWS or n_pos in (0, len(kept)):
            # too sparse or single-class: deterministic base rate
            rate = n_pos / len(kept) if kept else 0.0
            planes[plane] = PlaneModel.constant(
                plane, rate, n_rows=len(kept), n_pos=n_pos)
            meta["fit"] = "constant"
        else:
            from .features import vectorize
            X = np.stack([vectorize(plane, f) for f, _, _ in kept])
            y = np.array([1.0 if l else 0.0 for _, l, _ in kept])
            w = np.array([wt for _, _, wt in kept])
            mean, scale, beta, bias = fit_logistic(X, y, w)
            planes[plane] = PlaneModel(plane, mean, scale, beta, bias,
                                       n_rows=len(kept), n_pos=n_pos)
            meta["fit"] = "logistic"
        train_meta[plane] = meta
    bundle = PolicyBundle(
        {"seed": int(seed), "horizon_clocks": int(horizon_clocks),
         "truncated_weight": float(truncated_weight),
         "dtrace": dtrace, "wtrace": wtrace,
         "dataset_rows": int(ds["n_rows"]),
         "truncated_rows": int(total_truncated),
         "train": train_meta}, planes)
    if out_path:
        bundle.save(out_path)
    return bundle


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    p = argparse.ArgumentParser(
        prog="python -m adapm_tpu.policy.train",
        description="Fit the per-plane learned policies from a capture "
                    "run's decision (+ workload) traces.")
    p.add_argument("dtrace", help=".dtrace from --sys.trace.decisions")
    p.add_argument("wtrace", nargs="?", default=None,
                   help="optional .wtrace from the SAME run")
    p.add_argument("-o", "--out", required=True,
                   help="policy artifact path (written atomically)")
    p.add_argument("--seed", type=int, default=0,
                   help="provenance seed recorded in the artifact "
                        "(the fit itself consumes no RNG)")
    p.add_argument("--horizon", type=int, default=4,
                   help="w.* label window in logical clocks "
                        "(default 4)")
    p.add_argument("--truncated-weight", type=float, default=0.0,
                   help="sample weight for forced-close rows "
                        "(default 0.0 = excluded; forced outcomes "
                        "are not labels)")
    a = p.parse_args(argv)
    b = train_policy(a.dtrace, a.wtrace, out_path=a.out, seed=a.seed,
                     horizon_clocks=a.horizon,
                     truncated_weight=a.truncated_weight)
    t = b.meta["train"]
    for plane in sorted(t):
        m = t[plane]
        print(f"{plane}: {m['fit']} fit from {m['used']}/{m['rows']} "
              f"rows ({m['pos']} regretted, "
              f"{m['truncated_rows']} truncated)")
    print(f"policy.train.truncated_rows={b.meta['truncated_rows']} "
          f"(weight {b.meta['truncated_weight']}) -> {a.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
