"""The deterministic replay engine (ISSUE 15 tentpole, replay half).

`ReplayEngine` re-drives a captured `.wtrace` op stream against a fresh
in-process server under candidate knob overrides and scores the run
from the existing `metrics_snapshot()`. The replay server is built
from the trace's RECORDED geometry AND knobs (a candidate's diff is
measured against the configuration that produced the workload — that
is what makes the ranking transfer to the live system), with the
determinism/hygiene pins applied on top. One driver thread replays the
recorded event order; the determinism contract (docs/REPLAY.md) is:

  **same trace + same seed + same knobs => bit-identical replayed
  reads** (the sha256 `reads_digest` folded over every pull /
  serve-lookup / sample result, pinned by tests/test_wtrace.py and
  scripts/trace_replay_check.py at 1x and 10x logical speed).

Why that holds here and nowhere cheaper: every plane in this codebase
already guarantees reads are bit-identical to a plain pull at the same
dispatch point — across tier churn, sync rounds, relocations, serve
coalescing, and episodic execution (the r5-r17 storm pins). The engine
adds the missing piece: a deterministic DISPATCH ORDER. It

  - drives every op from one thread in recorded `seq` order;
  - disables the timer-driven planes (`sync_max_per_sec=0`, prefetch
    off) and re-drives sync rounds / quiesces where the TRACE recorded
    them — rounds happen where the workload put them, not where a wall
    clock did;
  - strips serve deadlines (a deadline shed is a wall-clock race; the
    scoring run serves every lookup) unless `keep_deadlines=True`;
  - synthesizes push/set values and reconstructs key-sampled batches
    from per-event seeded RNGs (`seed` x event seq) — the trace stores
    keys and shapes, never value payloads.

Background executor streams (tier maintenance, SLO ticks) still run —
they move rows and walk windows but can never change read VALUES (the
bit-identity contracts above), so they affect the SCORE metrics
statistically while the reads stay pinned.

Logical speed: recorded inter-event monotonic gaps are slept at
`gap / speed` (capped per gap), so time-based policies (SLO control,
refresh throttles) see a compressed-but-shaped arrival process.
`speed=100` (the default) is effectively as-fast-as-possible — the
capacity-sim mode; `speed=1` re-creates the recorded pacing.

`rank_candidates` sweeps overrides over one trace and emits the ranked
comparison artifact (the "which knob wins on MY workload" answer, and
the "how many shards / hot rows for this load" capacity question).
"""
from __future__ import annotations

import hashlib
import time
from typing import Dict, List, Optional

import numpy as np

from ..obs.wtrace import (WorkloadTrace, WorkloadTraceError, event_keys,
                          load_wtrace)

# per-gap sleep cap: a capture with long idle gaps replays in bounded
# time even at 1x (the gap SHAPE survives; multi-second idles do not)
_MAX_GAP_SLEEP_S = 0.05

# objective name -> direction for rank_candidates (every numeric key
# extract_scores produces ranks; keep the two in sync)
OBJECTIVES = {
    "hot_hit_rate": "max",
    "replica_hit_rate": "max",
    "plan_cache_hit_rate": "max",
    "serve_p50_ms": "min",
    "serve_p99_ms": "min",
    "cold_serve_p99_ms": "min",
    "bytes_per_round": "min",
    "bytes_shipped": "min",
    "dispatch_wait_p99_ms": "min",
    "shed_total": "min",
    "wall_s": "min",
    # decision-regret rates (ISSUE 18 promotion gate): need
    # score_decisions=True so the metrics-only recorder runs in replay
    "regret_rate_reloc": "min",
    "regret_rate_tier": "min",
    "regret_rate_sync": "min",
    "regret_rate_serve": "min",
}

# determinism pins a candidate may NOT override (module docstring):
# re-enabling any of these turns a wall-clock race back into replayed
# behavior — keep_deadlines / engine params are the sanctioned levers
_PINNED_KNOBS = ("serve_deadline_ms", "sync_max_per_sec", "prefetch")

# event kinds replay re-drives vs observes (decisions re-decided by the
# candidate policy under test)
_DECISION_KINDS = frozenset({"reloc", "promote"})


def _build_opts(trace: WorkloadTrace, overrides: Optional[Dict]):
    """SystemOptions for one replay run: the RECORDED knobs (so a
    candidate diff is measured against the configuration that actually
    produced the workload, and the ranking transfers to the live
    system) + the determinism/hygiene pins + candidate overrides
    (dataclass field names; unknown or pinned names fail loudly)."""
    from ..base import MgmtTechniques
    from ..config import SystemOptions
    opts = SystemOptions()
    for k, v in dict(trace.meta.get("knobs", {})).items():
        if not hasattr(opts, k):
            continue  # knob from a newer/older recorder: skip
        if k == "techniques":
            v = MgmtTechniques(v)  # serialized as the enum value
        setattr(opts, k, v)
    # determinism pins (module docstring): the trace drives rounds
    opts.sync_max_per_sec = 0
    opts.prefetch = False
    opts.serve_deadline_ms = 0.0
    # scoring reads the registry; capture never recurses into replay
    opts.metrics = True
    opts.trace_workload = None
    # decision capture (ISSUE 17) stays with the system that recorded
    # the workload: a replay re-decides under the candidate policy, and
    # its decisions are scored via the registry, not re-captured
    opts.trace_decisions = None
    # output/periodic hygiene: a replay run must not write the
    # captured run's stats/traces/checkpoint chains or re-arm its
    # timers — those belong to the system that recorded them
    opts.stats_out = None
    opts.trace_spans = False
    opts.trace_spans_out = None
    opts.trace_flight = False
    opts.trace_flight_out = None
    opts.metrics_report_s = 0.0
    opts.ckpt_every_s = 0.0
    opts.ckpt_path = None
    opts.heartbeat_s = 0.0
    # streaming plane (ISSUE 20): the captured run's ingest pump and
    # freshness controller are live timer loops, and every push they
    # issued is ALREADY in the op stream being re-driven — a replayed
    # server must not ingest the events a second time (and the
    # controller's sensor, trace_flight, is off above anyway)
    opts.stream_batch = 0
    opts.stream_rate = 0.0
    opts.stream_freshness_slo_ms = 0.0
    opts.stream_freshness_slo_class = ""
    num_shards = int(trace.meta.get("num_shards", 0)) or None
    for k, v in dict(overrides or {}).items():
        if k == "num_shards":  # engine-level: the capacity-sim knob
            num_shards = int(v)
            continue
        if not hasattr(opts, k):
            raise ValueError(
                f"unknown replay knob override {k!r} (use "
                f"SystemOptions field names, e.g. tier_hot_rows, "
                f"serve_dispatchers, sync_compress, serve_slo_ms, "
                f"episode_batches)")
        if k in _PINNED_KNOBS:
            raise ValueError(
                f"replay determinism pin {k!r} cannot be overridden "
                f"by a candidate: deadlines/timer loops are wall-clock "
                f"races, not replayable behavior (use "
                f"keep_deadlines=True on the engine to study sheds)")
        setattr(opts, k, v)
    if not opts.metrics:
        raise ValueError("replay scoring requires metrics; do not "
                         "override metrics=False")
    if opts.trace_workload:
        raise ValueError("replay must not capture itself; do not "
                         "override trace_workload")
    if opts.trace_decisions:
        raise ValueError("replay must not capture itself; do not "
                         "override trace_decisions (export the "
                         "labeled dataset from the CAPTURED run's "
                         ".dtrace via replay/dataset.py)")
    opts.validate_serve()
    return opts, num_shards


class ReplayEngine:
    """One replay run of one trace under one knob configuration.

    Construction LOADS AND VERIFIES the trace (`WorkloadTraceError` on
    a corrupt/truncated file — before any server exists); `run()`
    builds the fresh server, re-drives the stream, scores it, and
    shuts the server down."""

    def __init__(self, trace, overrides: Optional[Dict] = None,
                 seed: int = 0, speed: float = 100.0,
                 keep_deadlines: bool = False,
                 score_decisions: bool = False):
        if not isinstance(trace, WorkloadTrace):
            trace = load_wtrace(trace)  # raises WorkloadTraceError
        if speed <= 0:
            raise ValueError(f"replay speed must be > 0 (got {speed}); "
                             f"1 = recorded pacing, 100 = as fast as "
                             f"possible")
        self.trace = trace
        self.overrides = dict(overrides or {})
        self.seed = int(seed)
        self.speed = float(speed)
        self.keep_deadlines = bool(keep_deadlines)
        # ISSUE 18: attach a metrics-only DecisionRecorder (path=None)
        # to the replayed server so `decision.regret_rate.<plane>`
        # gauges score the re-decided decisions; the dtrace capture
        # pin (trace_decisions=None) stays untouched
        self.score_decisions = bool(score_decisions)

    # -- deterministic reconstruction ---------------------------------------

    def _rng(self, ev_seq: int) -> np.random.Generator:
        return np.random.default_rng([self.seed, int(ev_seq)])

    def _keys(self, ev: Dict) -> np.ndarray:
        return event_keys(ev, rng=self._rng(ev["seq"]))

    def _vals(self, srv, ev: Dict, keys: np.ndarray) -> np.ndarray:
        total = int(srv.value_lengths[keys].sum())
        return self._rng(ev["seq"]).normal(
            size=total).astype(np.float32)

    # -- the run -------------------------------------------------------------

    def run(self, include_snapshot: bool = False) -> Dict:
        import adapm_tpu

        trace = self.trace
        opts, num_shards = _build_opts(trace, self.overrides)
        nw = trace.max_worker_id() + 1
        srv = adapm_tpu.setup(int(trace.meta["num_keys"]),
                              trace.value_lengths, opts=opts,
                              num_shards=num_shards, num_workers=nw)
        if self.score_decisions:
            # metrics-only mode: windows/regret folding runs and the
            # regret gauges land in snap["decision"]; flush() is a
            # no-op so nothing is written (replay never captures)
            from ..obs.decisions import DecisionRecorder
            srv.decisions = DecisionRecorder(srv, None)
        digest = hashlib.sha256()
        workers: Dict[int, object] = {}
        sessions: Dict = {}
        handles: Dict[int, int] = {}  # recorded handle -> live handle
        plane = None
        replayed = 0
        reads = 0
        skipped: Dict[str, int] = {}
        prev_mono: Optional[float] = None
        t0 = time.perf_counter()

        def worker(wid: int):
            w = workers.get(wid)
            if w is None:
                w = workers[wid] = srv.make_worker(wid)
            return w

        def fold(arr) -> None:
            nonlocal reads
            reads += 1
            digest.update(np.ascontiguousarray(
                arr, dtype=arr.dtype).tobytes())

        def get_session(tenant: Optional[str], priority: int):
            nonlocal plane
            if plane is None:
                from ..serve import ServePlane
                plane = ServePlane(srv)
            skey = (tenant, priority)
            sess = sessions.get(skey)
            if sess is None:
                if tenant is not None:
                    plane.configure_tenant(tenant, priority=priority)
                sess = sessions[skey] = plane.session(
                    tenant=tenant, priority=priority)
            return sess

        if any(ev["kind"] == "prep_sample" for ev in trace.events):
            nk = int(trace.meta["num_keys"])
            srv.enable_sampling_support(
                lambda n, rng: rng.integers(0, nk, n), 0, nk)

        try:
            for ev in trace.events:
                mono = ev.get("mono")
                if prev_mono is not None and mono is not None:
                    gap = (mono - prev_mono) / self.speed
                    if gap > 1e-4:
                        time.sleep(min(gap, _MAX_GAP_SLEEP_S))
                prev_mono = mono
                kind = ev["kind"]
                if kind in _DECISION_KINDS:
                    # observed decisions: the candidate policy under
                    # test re-decides these during replay
                    skipped[kind] = skipped.get(kind, 0) + 1
                    continue
                replayed += 1
                if kind == "pull":
                    fold(worker(ev["wid"]).pull_sync(self._keys(ev)))
                elif kind == "push":
                    w = worker(ev["wid"])
                    keys = self._keys(ev)
                    ts = w.push(keys, self._vals(srv, ev, keys))
                    w.wait(ts)
                elif kind == "set":
                    w = worker(ev["wid"])
                    keys = self._keys(ev)
                    ts = w.set(keys, self._vals(srv, ev, keys))
                    w.wait(ts)
                elif kind == "intent":
                    worker(ev["wid"]).intent(self._keys(ev),
                                             ev["start"], ev["end"])
                elif kind == "clock":
                    worker(ev["wid"]).advance_clock()
                elif kind == "serve":
                    sess = get_session(ev.get("tenant"),
                                       int(ev.get("priority", 0)))
                    dl = ev.get("deadline_ms") or None
                    fold(sess.lookup(
                        self._keys(ev),
                        deadline_ms=dl if self.keep_deadlines
                        else None))
                elif kind == "prep_sample":
                    handles[ev["handle"]] = worker(
                        ev["wid"]).prepare_sample(
                        ev["n"], ev.get("start"), ev.get("end"))
                elif kind == "pull_sample":
                    h = handles.get(ev["handle"])
                    if h is None:
                        skipped[kind] = skipped.get(kind, 0) + 1
                        replayed -= 1
                        continue
                    ks, vals = worker(ev["wid"]).pull_sample(
                        h, ev.get("n"))
                    fold(np.asarray(ks, dtype=np.int64))
                    fold(np.asarray(vals, dtype=np.float32))
                elif kind == "finish_sample":
                    h = handles.pop(ev["handle"], None)
                    if h is not None:
                        worker(ev["wid"]).finish_sample(h)
                elif kind == "sync":
                    with srv._round_lock:
                        srv.sync.run_round(
                            force_intents=bool(ev.get("forced")),
                            all_channels=bool(ev.get("all")))
                elif kind == "quiesce":
                    srv.quiesce()
                else:  # unknown kind from a newer recorder: loud skip
                    skipped[kind] = skipped.get(kind, 0) + 1
                    replayed -= 1
            srv.quiesce()
            wall_s = time.perf_counter() - t0
            reads_digest = digest.hexdigest()
            srv.replay_stats = {
                "trace": trace.path,
                "events_replayed": replayed,
                "events_skipped_total": int(sum(skipped.values())),
                "reads": reads,
                "reads_digest": reads_digest,
                "seed": self.seed,
                "speed": self.speed,
            }
            snap = srv.metrics_snapshot()
        finally:
            if plane is not None:
                plane.close()
            srv.shutdown()
        out = {"overrides": dict(self.overrides), "seed": self.seed,
               "speed": self.speed,
               "events_total": len(trace.events),
               "events_replayed": replayed,
               "events_skipped": skipped,
               "reads": reads, "reads_digest": reads_digest,
               "wall_s": round(wall_s, 4),
               "score": extract_scores(snap, wall_s)}
        if include_snapshot:
            out["snapshot"] = snap
        return out


def replay_trace(trace, overrides: Optional[Dict] = None, seed: int = 0,
                 speed: float = 100.0, **kw) -> Dict:
    """One-shot convenience: load (or take) a trace, replay under
    `overrides`, return the scored result."""
    return ReplayEngine(trace, overrides=overrides, seed=seed,
                        speed=speed, **kw).run()


def per_shard_hot_rows(num_keys: int, fraction: float,
                       num_shards: Optional[int] = None) -> int:
    """`--sys.tier.hot_rows` for "this fraction of the table hot":
    the knob is PER SHARD per length class, so a whole-table fraction
    must divide by the shard count or a multi-shard mesh silently
    grants N_shards x the intended capacity (a capacity sweep then
    near-ties — every candidate is effectively all-hot). Floors at the
    minimum pool the store accepts. Shared by the bench `replay` phase
    and scripts/trace_replay_check.py so the two cannot drift."""
    if num_shards is None:
        import jax
        num_shards = len(jax.devices())
    s = max(1, int(num_shards))
    want = int(num_keys * float(fraction))
    return max(8, -(-want // s))


def extract_scores(snap: Dict, wall_s: float) -> Dict:
    """The policy-scoring surface distilled from one metrics snapshot:
    hit rates, wire bytes per round, executor dispatch wait, serve
    tails, shed totals (the ISSUE 15 scoring set). Keys double as
    `rank_candidates` objective names; absent subsystems score None."""
    from ..obs.metrics import hist_percentile

    def _pct(section: Dict, name: str, q: float):
        h = section.get(name)
        if isinstance(h, dict) and h.get("count"):
            return round(hist_percentile(h, q) * 1e3, 4)
        return None

    serve = snap.get("serve", {})
    tier = snap.get("tier", {})
    sync = snap.get("sync", {})
    ex = snap.get("exec", {})
    pc = snap.get("plan_cache", {})
    dec = snap.get("decision", {})
    hits = float(pc.get("hits", 0))
    misses = float(pc.get("misses", 0))
    shed = (serve.get("shed_total", 0) or 0) + \
        (serve.get("rejected_total", 0) or 0) + \
        (serve.get("degraded_shed_total", 0) or 0)
    return {
        "wall_s": round(wall_s, 4),
        "serve_p50_ms": _pct(serve, "latency_s", 0.50),
        "serve_p99_ms": _pct(serve, "latency_s", 0.99),
        "shed_total": int(shed),
        "replica_hit_rate": serve.get("replica_hit_rate"),
        "hot_hit_rate": tier.get("hot_hit_rate"),
        "cold_serve_p99_ms": _pct(tier, "cold_serve_s", 0.99),
        "bytes_per_round": sync.get("bytes_per_round"),
        "bytes_shipped": sync.get("bytes_shipped"),
        "dispatch_wait_p99_ms": _pct(ex, "dispatch_wait_s", 0.99),
        "plan_cache_hit_rate": round(hits / (hits + misses), 4)
        if (hits + misses) else None,
        # present only with score_decisions=True (the metrics-only
        # recorder); None otherwise, so regret objectives rank last
        "regret_rate_reloc": dec.get("regret_rate.reloc"),
        "regret_rate_tier": dec.get("regret_rate.tier"),
        "regret_rate_sync": dec.get("regret_rate.sync"),
        "regret_rate_serve": dec.get("regret_rate.serve"),
    }


def _auto_objective(results: Dict[str, Dict]) -> str:
    """Pick the headline objective from what the runs actually scored:
    tiered runs rank by hot-hit rate, serving runs by P99, else wall."""
    scores = [r["score"] for r in results.values()]
    if any(s.get("hot_hit_rate") is not None for s in scores):
        return "hot_hit_rate"
    if any(s.get("serve_p99_ms") is not None for s in scores):
        return "serve_p99_ms"
    return "wall_s"


def rank_candidates(trace, candidates: Dict[str, Optional[Dict]],
                    objective: str = "auto", seed: int = 0,
                    speed: float = 100.0,
                    out_path: Optional[str] = None,
                    score_decisions: bool = False) -> Dict:
    """Replay one trace under each candidate's knob overrides and emit
    the ranked comparison artifact (best first; deterministic name
    tie-break; runs missing the objective rank last). `candidates`
    maps a display name to an overrides dict (None = stock knobs).
    With `out_path`, the artifact is also written as JSON (atomic)."""
    if not candidates:
        raise ValueError("rank_candidates needs at least one candidate")
    trace_obj = trace if isinstance(trace, WorkloadTrace) \
        else load_wtrace(trace)
    results: Dict[str, Dict] = {}
    for name in sorted(candidates):
        results[name] = ReplayEngine(
            trace_obj, overrides=candidates[name], seed=seed,
            speed=speed, score_decisions=score_decisions).run()
    if objective == "auto":
        objective = _auto_objective(results)
    direction = OBJECTIVES.get(objective)
    if direction is None:
        raise ValueError(
            f"unknown objective {objective!r}; one of "
            f"{sorted(OBJECTIVES)} (or 'auto')")

    def sort_key(name: str):
        v = results[name]["score"].get(objective)
        missing = v is None
        if missing:
            v = 0.0
        return (missing, -v if direction == "max" else v, name)

    ranking: List[str] = sorted(results, key=sort_key)
    artifact = {
        "format": "adapm-replay-compare",
        "version": 1,
        "trace": trace_obj.path,
        "trace_events": len(trace_obj.events),
        "trace_kinds": trace_obj.kinds(),
        "seed": int(seed),
        "speed": float(speed),
        "objective": objective,
        "direction": direction,
        "candidates": {n: {"overrides": dict(candidates[n] or {}),
                           **{k: v for k, v in results[n].items()
                              if k != "overrides"}}
                       for n in sorted(results)},
        "ranking": ranking,
        "winner": ranking[0],
    }
    if out_path:
        import json

        from ..utils import write_atomic
        write_atomic(out_path,
                     json.dumps(artifact, indent=1,
                                default=float).encode())
    return artifact
