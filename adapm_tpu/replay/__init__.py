"""Deterministic trace replay: the offline policy lab + capacity
simulator (ISSUE 15 tentpole, replay half; ROADMAP item 3).

A workload captured once with `--sys.trace.workload` (obs/wtrace.py) is
re-driven here against a FRESH in-process server under candidate knob
overrides, at 1x-100x logical speed, and scored from the existing
metrics snapshot — no live traffic, no hardware beyond this process.
`rank_candidates` sweeps a set of knob overrides over one trace and
emits a ranked comparison artifact; docs/REPLAY.md has the
policy-scoring and capacity-sim recipes, and the determinism contract
(same trace + same seed + same knobs => bit-identical replayed reads,
pinned by tests/test_wtrace.py and scripts/trace_replay_check.py).

`dataset.py` (ISSUE 17) joins a capture run's decision trace
(`--sys.trace.decisions`, obs/decisions.py) against its workload trace
into the labeled (features, decision, outcome) table the policy lab
trains and scores against — see docs/REPLAY.md "Policy scoring".
"""
from __future__ import annotations

from ..obs.decisions import (DecisionTrace,  # noqa: F401
                             DecisionTraceError, load_dtrace)
from ..obs.wtrace import (WorkloadTrace, WorkloadTraceError,  # noqa: F401
                          load_wtrace)
from .dataset import dataset_bytes, export_dataset  # noqa: F401
from .engine import (OBJECTIVES, ReplayEngine,  # noqa: F401
                     per_shard_hot_rows, rank_candidates, replay_trace)
