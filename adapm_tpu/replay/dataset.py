"""Labeled decision dataset export: join a `.dtrace` against its
`.wtrace` (ISSUE 17 tentpole, export third).

A capture run with BOTH `--sys.trace.decisions` and
`--sys.trace.workload` produces two verified artifacts over the same
logical clock: the decision stream (features + outcome per adaptive
choice; obs/decisions.py) and the op stream (what the workload actually
did; obs/wtrace.py). `export_dataset` joins them into one flat
(features, decision, outcome) table for the policy lab:

  - one row per decision, sorted by `seq`, columns flattened with
    stable prefixes: `f.*` the feature vector seen at decision time,
    `d.*` plane-specific decision fields, `o.*` outcome-probe fields,
    `w.*` workload context (ops/reads/writes landing within
    `horizon_clocks` logical clocks AFTER the decision — the labels a
    learned policy would train against);
  - `regret` / `truncated` / `outcome_latency_s` from the attribution
    window (obs/decisions.py), None where a plane records no verdict;
  - `truncated=true` rows are FORCED verdicts, not labels: close()
    sealed the attribution window before its horizon elapsed, so the
    outcome probe observed a shorter window than every other row.
    Training consumers must down-weight or exclude them (the trainer's
    `truncated_weight`, default 0.0, and the loud
    `policy.train.truncated_rows` count — policy/train.py); the
    artifact carries `n_truncated` so the bias is visible at export;
  - DETERMINISTIC bytes: same inputs => byte-identical JSON (sorted
    keys, fixed separators, no timestamps minted at export time —
    scripts/decision_quality_check.py pins the round-trip).

The replay engine refuses to capture decisions DURING a replay
(`replay/engine.py` pins `trace_decisions = None`): the dataset is
exported from the CAPTURED run's traces, never from the simulator
observing itself.

Offline (no server, no jax): both loaders verify format/version/
length/sha256 before parsing, so a corrupt input dies with the named
trace error, never a half-joined table.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Union

from ..obs.decisions import DecisionTrace, load_dtrace
from ..obs.wtrace import WorkloadTrace, load_wtrace

DATASET_FORMAT = "adapm-decision-dataset"
DATASET_VERSION = 1

# event keys consumed by the row skeleton itself; everything else is a
# plane-specific extra and lands under the d./o. prefix
_BASE_DECISION = frozenset(("kind", "plane", "seq", "clock", "wall",
                            "mono", "action", "features"))
_BASE_OUTCOME = frozenset(("kind", "plane", "seq", "clock", "wall",
                           "mono", "ref", "truncated", "regret"))

# wtrace kinds that count as demand (reads) vs mutation (writes) when
# labeling the post-decision window
_READ_KINDS = frozenset(("pull", "serve"))
_WRITE_KINDS = frozenset(("push", "set"))


def _workload_labels(wt: WorkloadTrace, clock: int,
                     horizon: int) -> Dict[str, int]:
    """Aggregate the op stream over logical clocks
    (clock, clock + horizon]: what the workload did AFTER this decision
    was taken."""
    lo, hi = clock, clock + horizon
    events = reads = writes = 0
    for ev in wt.events:
        c = ev.get("clock")
        if c is None or not (lo < c <= hi):
            continue
        events += 1
        n = int(ev.get("n", 0))
        if ev["kind"] in _READ_KINDS:
            reads += n
        elif ev["kind"] in _WRITE_KINDS:
            writes += n
    return {"w.events_after": events, "w.keys_read_after": reads,
            "w.keys_written_after": writes}


def export_dataset(dtrace: Union[str, DecisionTrace],
                   wtrace: Union[str, WorkloadTrace, None] = None,
                   out_path: Optional[str] = None,
                   horizon_clocks: int = 4) -> Dict:
    """Build (and optionally write) the labeled decision dataset.

    `dtrace` is a path or a loaded `DecisionTrace`; `wtrace` optionally
    adds the `w.*` workload-context columns from the SAME capture run.
    With `out_path` the artifact is written atomically; the bytes are
    deterministic for fixed inputs. Returns the artifact dict."""
    if horizon_clocks < 1:
        raise ValueError(
            f"horizon_clocks must be >= 1 (got {horizon_clocks})")
    tr = dtrace if isinstance(dtrace, DecisionTrace) \
        else load_dtrace(dtrace)
    wt = None
    if wtrace is not None:
        wt = wtrace if isinstance(wtrace, WorkloadTrace) \
            else load_wtrace(wtrace)

    outcomes = tr.outcomes()
    rows: List[Dict] = []
    n_unresolved = n_regretted = n_truncated = 0
    for d in sorted(tr.decisions(), key=lambda e: e["seq"]):
        row: Dict = {"seq": d["seq"], "clock": d["clock"],
                     "plane": d["plane"], "action": d["action"]}
        for k, v in d.get("features", {}).items():
            row[f"f.{k}"] = v
        for k, v in d.items():
            if k not in _BASE_DECISION:
                row[f"d.{k}"] = v
        oc = outcomes.get(d["seq"])
        if oc is None:
            # dropped under the event budget, or the run died before
            # close() forced the window — labeled, not silently skipped
            n_unresolved += 1
            row["resolved"] = False
            row["regret"] = None
            row["truncated"] = None
        else:
            row["resolved"] = True
            row["regret"] = oc.get("regret")
            row["truncated"] = bool(oc.get("truncated", False))
            if row["truncated"]:
                n_truncated += 1
            row["outcome_clock"] = oc["clock"]
            row["outcome_latency_s"] = round(oc["mono"] - d["mono"], 6)
            if row["regret"]:
                n_regretted += 1
            for k, v in oc.items():
                if k not in _BASE_OUTCOME:
                    row[f"o.{k}"] = v
        if wt is not None:
            row.update(_workload_labels(wt, d["clock"], horizon_clocks))
        rows.append(row)

    columns = sorted({k for r in rows for k in r})
    artifact = {
        "format": DATASET_FORMAT,
        "version": DATASET_VERSION,
        "source": {"dtrace": tr.path,
                   "wtrace": wt.path if wt is not None else None},
        "capture": dict(tr.meta),
        "horizon_clocks": int(horizon_clocks),
        "planes": tr.planes(),
        "n_rows": len(rows),
        "n_unresolved": n_unresolved,
        "n_regretted": n_regretted,
        "n_truncated": n_truncated,
        "events_dropped_at_capture": int(tr.dropped),
        "columns": columns,
        "rows": rows,
    }
    if out_path:
        from ..utils import write_atomic
        write_atomic(out_path, dataset_bytes(artifact))
    return artifact


def dataset_bytes(artifact: Dict) -> bytes:
    """Canonical serialization: sorted keys, fixed separators — the
    determinism contract is over THESE bytes."""
    return json.dumps(artifact, sort_keys=True,
                      separators=(",", ":"), default=float).encode()


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    p = argparse.ArgumentParser(
        prog="python -m adapm_tpu.replay.dataset",
        description="Export the labeled (features, decision, outcome) "
                    "dataset from a capture run's traces.")
    p.add_argument("dtrace", help=".dtrace from --sys.trace.decisions")
    p.add_argument("wtrace", nargs="?", default=None,
                   help="optional .wtrace from the SAME run "
                        "(adds w.* workload-context columns)")
    p.add_argument("-o", "--out", required=True,
                   help="output JSON path (written atomically)")
    p.add_argument("--horizon", type=int, default=4,
                   help="w.* label window in logical clocks "
                        "(default 4)")
    a = p.parse_args(argv)
    art = export_dataset(a.dtrace, a.wtrace, out_path=a.out,
                         horizon_clocks=a.horizon)
    print(f"{art['n_rows']} rows ({art['n_unresolved']} unresolved, "
          f"{art['n_regretted']} regretted, "
          f"{art['n_truncated']} truncated) x "
          f"{len(art['columns'])} columns -> {a.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
