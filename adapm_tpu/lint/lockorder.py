"""Runtime lock-order sentinel (ISSUE 11 tentpole, dynamic half).

The static rules (rules.py) claim the lock discipline from the source:
the dispatch gate is a LEAF (never held while acquiring anything
else), the server lock may be held across a gated ENQUEUE but never
across a wait, and no two lock domains order each other both ways.
This module validates the same claims at runtime: an opt-in
(``--sys.lint.lockorder``, default off) wrapper around the server
lock, the dispatch gate, and the admission/registry locks records the
per-thread acquisition graph and raises ``LockOrderError`` the moment

  - an acquisition would create a CYCLE in the process-wide
    lock-order graph (the classic deadlock precondition — caught on
    the first inverted pair, deterministically, instead of waiting for
    the storm test's scheduler to actually interleave the deadlock), or
  - any NEW lock is acquired while the dispatch gate is held anywhere
    in the thread's stack (the gate's leaf contract, docs/EXECUTOR.md:
    it brackets the enqueue only — a lock taken under it is a
    held-across-dispatch edge by definition).

The graph is keyed by lock IDENTITY, not name: two servers on one
process each own a lock named "server", and a thread nesting server A
under server B is an orderable (and invertible!) pair, never a
reentrant no-op — exactly the multi-server configuration the storm
tests run. Names are display labels in the error chain.

Zero-cost skip-wrapper like every other optional plane (r7): with the
knob off, ``Server`` builds plain ``threading.RLock`` objects (no
wrapper exists at all) and the process-global gate — which dispatch
sites capture at import (``_GATE = dispatch_gate()``) and therefore
cannot be swapped per server — is a ``SentinelLock`` paying ONE
``is None`` check per acquire. With it on, every tracked
acquire/release notes the edge under the sentinel's own internal mutex
(deliberately NOT tracked — the sentinel cannot deadlock with itself).

The tier-1 storm tests (exec enqueue-order property test, the tier and
serve storms) run with the sentinel enabled, so the dynamic checker
rides the existing suites: a lock-order regression fails those tests
with a named edge trace, not a hung CI job.
"""
from __future__ import annotations

import itertools
import threading
from typing import Dict, List, Optional, Tuple

#: the gate's display name — leaf by contract (docs/EXECUTOR.md)
GATE_NAME = "dispatch_gate"

# unique identity per SentinelLock (id() can recycle after GC; a
# monotonic counter cannot); uid 1 is reserved for the process gate
_UIDS = itertools.count(1)
GATE_UID = next(_UIDS)


class LockOrderError(RuntimeError):
    """A lock acquisition violated the ordering contract (cycle or
    gate-leaf). The message names the full edge chain so the report
    points at both call sites."""


class LockOrderSentinel:
    """The process-wide acquisition-graph recorder. Thread-safe;
    per-thread held-lock stacks live in a ``threading.local``.

    Edges are directed over lock UIDs: holding A while acquiring B
    records (A -> B). Reentrant re-acquisition of the SAME lock object
    records nothing — same-lock nesting is the RLock contract, not an
    ordering fact. Edge checks happen BEFORE the underlying acquire,
    so a would-be deadlock raises instead of deadlocking."""

    def __init__(self):
        self._mu = threading.Lock()
        self._edges: Dict[Tuple[int, int], bool] = {}
        self._names: Dict[int, str] = {GATE_UID: GATE_NAME}
        self._violations = 0
        self._local = threading.local()

    # -- per-thread held stack ----------------------------------------------

    def _held(self) -> List[int]:
        h = getattr(self._local, "held", None)
        if h is None:
            h = self._local.held = []
        return h

    # -- recording -----------------------------------------------------------

    def note_acquire(self, uid: int, name: str) -> None:
        held = self._held()
        if uid in held:
            held.append(uid)  # reentrant: count, no new ordering fact
            return
        if GATE_UID in held:
            # anywhere in the stack, not just the top: a reentrant
            # re-acquire above the gate must not mask the leaf contract
            with self._mu:
                self._violations += 1
            raise LockOrderError(
                f"lock {name!r} acquired while holding the dispatch "
                f"gate — the gate is a LEAF: it brackets the sharded "
                f"ENQUEUE only, and any lock taken under it is a "
                f"held-across-dispatch edge (docs/EXECUTOR.md; "
                f"APM001/APM002)")
        top = held[-1] if held else None
        if top is not None:
            with self._mu:
                self._names.setdefault(uid, name)
                edge = (top, uid)
                if edge not in self._edges:
                    cycle = self._path(uid, top)
                    if cycle is not None:
                        self._violations += 1
                        chain = " -> ".join(
                            [self._names.get(top, "?"), name]
                            + [self._names.get(u, "?")
                               for u in cycle[1:]])
                        raise LockOrderError(
                            f"lock-order cycle: acquiring {name!r} "
                            f"while holding "
                            f"{self._names.get(top, '?')!r} inverts "
                            f"the recorded order {chain} — two "
                            f"threads taking these in opposite orders "
                            f"can deadlock (docs/INVARIANTS.md)")
                    self._edges[edge] = True
        else:
            with self._mu:
                self._names.setdefault(uid, name)
        held.append(uid)

    def note_release(self, uid: int) -> None:
        held = self._held()
        # release the innermost matching hold (RLock semantics)
        for i in range(len(held) - 1, -1, -1):
            if held[i] == uid:
                del held[i]
                return

    def _path(self, src: int, dst: int) -> Optional[List[int]]:
        """DFS over recorded edges: a path src ->* dst means adding
        (dst -> src) closes a cycle. Caller holds ``_mu``."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            cur, path = stack.pop()
            if cur == dst:
                return path
            for (a, b) in self._edges:
                if a == cur and b not in seen:
                    seen.add(b)
                    stack.append((b, path + [b]))
        return None

    # -- introspection (tests / tooling) -------------------------------------

    def edges(self) -> List[Tuple[str, str]]:
        """Recorded edges as (holder name, acquired name) pairs —
        deduplicated by NAME for readability (identity dedup lives in
        the graph itself)."""
        with self._mu:
            return sorted({(self._names.get(a, "?"),
                            self._names.get(b, "?"))
                           for a, b in self._edges})

    @property
    def violations(self) -> int:
        return self._violations

    def assert_clean(self) -> None:
        """Fail loudly if any violation was ever raised through this
        sentinel (storm tests call this at teardown — a violation that
        a storm thread swallowed must still fail the test)."""
        if self._violations:
            raise AssertionError(
                f"lock-order sentinel recorded {self._violations} "
                f"violation(s); edges seen: {self.edges()}")


class SentinelLock:
    """A named lock wrapper that reports acquire/release to the active
    sentinel — one ``is None`` check per acquire when no sentinel is
    installed (the r7 skip-wrapper price; this is why the
    process-global dispatch gate can be a SentinelLock permanently).
    Wraps any lock-like object (Lock/RLock); delegates the Condition
    integration surface (``_is_owned``/``_acquire_restore``/
    ``_release_save``) so ``threading.Condition(SentinelLock(...))``
    works — and a condvar WAIT correctly releases the hold in the
    sentinel's view (the wait parks without the lock; re-acquiring on
    wake re-records).

    Per-server locks are built ONLY when ``--sys.lint.lockorder`` is
    on (kv.py/serve): with the knob off the plain ``threading.RLock``
    is used directly and no wrapper cost exists on the hot path."""

    __slots__ = ("name", "inner", "uid")

    def __init__(self, name: str, inner=None, uid: Optional[int] = None):
        self.name = name
        self.inner = inner if inner is not None else threading.RLock()
        self.uid = uid if uid is not None else next(_UIDS)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        s = _SENTINEL
        if s is not None:
            s.note_acquire(self.uid, self.name)
        ok = self.inner.acquire(blocking, timeout)
        if not ok and s is not None:
            s.note_release(self.uid)
        return ok

    def release(self) -> None:
        self.inner.release()
        s = _SENTINEL
        if s is not None:
            s.note_release(self.uid)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # -- Condition integration ----------------------------------------------

    def _is_owned(self):
        return self.inner._is_owned()

    def _release_save(self):
        state = self.inner._release_save()
        s = _SENTINEL
        if s is not None:
            s.note_release(self.uid)
        return state

    def _acquire_restore(self, state):
        s = _SENTINEL
        if s is not None:
            s.note_acquire(self.uid, self.name)
        self.inner._acquire_restore(state)

    def __repr__(self):
        return f"SentinelLock({self.name!r}, uid={self.uid})"


# ---------------------------------------------------------------------------
# the process-global sentinel (None = off, the default)
# ---------------------------------------------------------------------------

_SENTINEL: Optional[LockOrderSentinel] = None
_ENABLE_MU = threading.Lock()


def enable_sentinel() -> LockOrderSentinel:
    """Install (or return the already-installed) process sentinel.
    Called by ``Server.__init__`` when ``--sys.lint.lockorder`` is on,
    and directly by tests. Idempotent — concurrent servers share one
    graph, which is the point (the gate orders across servers)."""
    global _SENTINEL
    with _ENABLE_MU:
        if _SENTINEL is None:
            _SENTINEL = LockOrderSentinel()
        return _SENTINEL


def disable_sentinel() -> None:
    """Drop the process sentinel (tests; idempotent). Locks already
    wrapped keep working — their per-acquire check just sees None."""
    global _SENTINEL
    with _ENABLE_MU:
        _SENTINEL = None


def get_sentinel() -> Optional[LockOrderSentinel]:
    return _SENTINEL
