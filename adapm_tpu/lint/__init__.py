"""adapm-lint (ISSUE 11): the AST invariant analyzer + runtime
lock-order sentinel for the seven-plane concurrency contract.

Two halves, one contract (docs/INVARIANTS.md):

  - ``analyzer``/``rules`` — the static pass: rule IDs ``APM001``..
    ``APM007`` over the package's own ASTs, justified
    ``# apm-lint: disable=`` suppressions that fail CI when unused,
    deterministic JSON + human reports. Run by
    ``scripts/invariant_lint_check.py`` inside run_tests.sh.
  - ``lockorder`` — the dynamic pass: an opt-in
    (``--sys.lint.lockorder``) sentinel wrapped around the server
    lock, the dispatch gate, and the admission/registry locks that
    records the per-thread acquisition graph and raises on a cycle or
    a gate-leaf violation — enabled inside the tier-1 storm tests so
    the runtime checker validates exactly what the static rules claim.

Pure stdlib on purpose: importable with no device stack.
"""
from .analyzer import (Analyzer, Finding, ModuleInfo,  # noqa: F401
                       ProjectContext, Report, Rule, Suppression)
from .lockorder import (LockOrderError, LockOrderSentinel,  # noqa: F401
                        SentinelLock, enable_sentinel, get_sentinel,
                        disable_sentinel)
from .rules import default_rules  # noqa: F401
