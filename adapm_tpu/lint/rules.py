"""The adapm-lint rule set (ISSUE 11): one rule per concurrency/plane
discipline, each grounded in a prose contract that used to be enforced
only by randomized storm tests. docs/INVARIANTS.md is the user-facing
catalog — rule ID, rationale, what fires, how to suppress.

| id     | discipline                                                   |
|--------|--------------------------------------------------------------|
| APM001 | gate-coverage: sharded device programs dispatch under the    |
|        | process-wide dispatch_gate() (docs/EXECUTOR.md)              |
| APM002 | no-blocking-under-lock: never .result()/wait/join/sleep/     |
|        | block inside a `with *._lock:` section (lock-narrowing rule) |
| APM003 | skip-wrapper: optional planes are used behind an `is None`   |
|        | guard and register zero metric names at import time (r7)     |
| APM004 | raw-thread ban: threading.Thread only in the executor/       |
|        | launcher/DCN/reporter allowlist (r11 subsumed the rest)      |
| APM005 | donation-after-dispatch: a local passed at a donate_argnums  |
|        | position is dead after the dispatching call                  |
| APM006 | revalidate-before-enqueue: topology read outside the lock +  |
|        | enqueue under it requires an under-lock re-read              |
| APM007 | metric-catalog drift: registered metric names <-> the        |
|        | docs/OBSERVABILITY.md catalog + snapshot schema sections     |
| APM008 | device-API confinement: jax.jit / device_put / pmap /        |
|        | shard_map only under adapm_tpu/device/ (the DevicePort)      |

Rules are LEXICAL: they reason about the AST as written (a `with
dispatch_gate():` block, an `is None` test), not about runtime values.
That is the point — the disciplines were designed to be auditable from
the source ("enqueue under the server lock, dispatch never"), and a
lexical checker runs in milliseconds with zero device stack. The cost
is the occasional intentional exception; those carry a justified
`# apm-lint: disable=` suppression (analyzer.py), never a weakened
rule.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Tuple

from .analyzer import (Finding, ModuleInfo, ProjectContext, Rule,
                       terminal_name)

# ---------------------------------------------------------------------------
# shared helpers
# ---------------------------------------------------------------------------


def _iter_functions(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _with_item_is(item: ast.withitem, names) -> bool:
    """True when a with-item's context expression terminates in one of
    `names` — either the object itself (`with _GATE:`) or a zero-ish
    call (`with dispatch_gate():`)."""
    ctx = item.context_expr
    if isinstance(ctx, ast.Call):
        return terminal_name(ctx.func) in names
    return terminal_name(ctx) in names


def _enclosing_with(mod: ModuleInfo, node: ast.AST, names) -> bool:
    for anc in mod.ancestors(node):
        if isinstance(anc, ast.With) and \
                any(_with_item_is(i, names) for i in anc.items):
            return True
    return False


def _callee_program_name(mod: ModuleInfo,
                         call: ast.Call) -> Optional[str]:
    """Name of the called module-level program, for calls that can
    target one: a bare name (`_gather(...)`, `_launder_fn(...)`) or an
    imported-module attribute (`dequant._write_main_rows_fp16(...)`).
    Method calls (`self._sync_replicas(...)`) return None — Server
    methods legitimately share names with the store programs they
    orchestrate."""
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name) \
            and fn.value.id in mod.imported_names:
        return fn.attr
    return None


def _mentions_handle(node: ast.AST, handle: str) -> bool:
    """True when `node`'s subtree mentions optional-subsystem `handle`:
    an attribute access `x.<handle>`, a bare name `<handle>`, or a
    `getattr(x, "<handle>", ...)` probe."""
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr == handle:
            return True
        if isinstance(n, ast.Name) and n.id == handle:
            return True
        if isinstance(n, ast.Call) and terminal_name(n.func) == "getattr":
            if len(n.args) >= 2 and isinstance(n.args[1], ast.Constant) \
                    and n.args[1].value == handle:
                return True
    return False


def _has_none_compare(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Compare) and \
                any(isinstance(op, (ast.Is, ast.IsNot)) for op in n.ops) \
                and any(isinstance(c, ast.Constant) and c.value is None
                        for c in n.comparators):
            return True
    return False


def _terminates(stmts: List[ast.stmt]) -> bool:
    """A statement list that unconditionally leaves the enclosing block
    (the early-return guard shape: `if x is None: return`)."""
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))


# ---------------------------------------------------------------------------
# APM001 — gate coverage
# ---------------------------------------------------------------------------

# The sharded-program site manifest: module-level jitted programs whose
# dispatch enqueues onto every per-device execution queue. Each is
# defined next to its callers and dispatched by NAME (store/coldpath/
# dequant/promote programs, the checkpoint launder) — fused step fns
# dispatch through runner-held variables and are covered by their own
# `with srv.exec.track("main"), _GATE:` blocks, which this rule cannot
# (and need not) see through. Grow this list when a new program class
# appears; the matching docs section is docs/INVARIANTS.md#apm001.
SHARDED_DISPATCH_SITES = frozenset({
    # core/store.py
    "_gather", "_scatter_add", "_set_rows", "_replica_create",
    "_sync_replicas", "_sync_replicas_compressed",
    "_sync_replicas_thresholded", "_read_rows_at", "_install_rows",
    "_refresh_after_sync", "_relocate",
    # promotion uploads (device/jaxport.py; formerly tier/promote.py +
    # ops/dequant.py)
    "_write_main_rows", "_write_main_rows_fp16", "_write_main_rows_int8",
    # tier/coldpath.py (cold-path programs)
    "_gather_cold", "_gather_cold_fp16", "_gather_cold_int8",
    "_clear_rows", "_install_cache_rows", "_install_cache_rows_resid",
    # fused embedding-bag reads (device/jaxport.py, ISSUE 16)
    "_gather_pool", "_gather_pool_cold", "_gather_pool_cold_fp16",
    "_gather_pool_cold_int8",
    # utils/checkpoint.py (restore launder)
    "_launder_fn",
})

# context managers that ARE the gate at a dispatch site
_GATE_NAMES = frozenset({"dispatch_gate", "_GATE", "_DISPATCH_GATE"})


class GateCoverageRule(Rule):
    """APM001: every call to a known sharded-dispatch program must sit
    lexically under `with dispatch_gate():` / `with _GATE:` (possibly
    combined: `with srv.exec.track("main"), _GATE:`). Two lock domains
    dispatching sharded programs concurrently land them on the
    per-device execution queues in different orders — the r10 XLA-CPU
    collective-rendezvous deadlock the gate retired by construction
    (docs/EXECUTOR.md)."""

    id = "APM001"
    name = "gate-coverage"
    doc = "sharded program dispatched outside the dispatch gate"

    def check_module(self, mod: ModuleInfo,
                     ctx: ProjectContext) -> List[Finding]:
        out = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _callee_program_name(mod, node)
            if name not in SHARDED_DISPATCH_SITES:
                continue
            if _enclosing_with(mod, node, _GATE_NAMES):
                continue
            out.append(self.finding(
                mod, node.lineno,
                f"[gate-coverage] sharded program {name}() dispatched "
                f"outside `with dispatch_gate():` — two ungated "
                f"dispatch domains can deadlock the per-device "
                f"collective rendezvous (docs/EXECUTOR.md)"))
        return out


# ---------------------------------------------------------------------------
# APM002 — no blocking under the server lock
# ---------------------------------------------------------------------------

# attribute names that identify the guarded mutex in a with-item
_LOCK_ATTRS = frozenset({"_lock"})

# terminal call names that park the calling thread. `wait` on a
# condition variable is exempt below (a condvar RELEASES its lock while
# waiting — that is its contract, not a violation).
_BLOCKING_CALLS = frozenset({
    "result", "wait", "block_until_ready", "join", "sleep", "drain",
    "drain_streams", "block",
})


class NoBlockingUnderLockRule(Rule):
    """APM002: inside a `with <x>._lock:` section, never call
    `.result()`, `.wait()`, `.join()`, `block_until_ready`, `sleep`,
    executor `drain`s, or `.block()`. The lock-narrowing rule
    (docs/EXECUTOR.md): the server lock brackets snapshot +
    revalidation + program ENQUEUE only — a lock held across a device
    wait serializes every producer behind the device, and at
    NestPipe-style scale that is a fleet-wide stall. Condvar waits on
    the lock itself are exempt (they release it)."""

    id = "APM002"
    name = "no-blocking-under-lock"
    doc = "blocking call inside a `with *._lock:` section"

    def check_module(self, mod: ModuleInfo,
                     ctx: ProjectContext) -> List[Finding]:
        out = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = terminal_name(node.func)
            if name not in _BLOCKING_CALLS:
                continue
            if not _enclosing_with(mod, node, _LOCK_ATTRS):
                continue
            # condvar exemption: `self._cond.wait()` under the condvar's
            # own lock is the parking idiom, not a held-lock wait
            recv = node.func.value \
                if isinstance(node.func, ast.Attribute) else None
            rname = terminal_name(recv) if recv is not None else ""
            if name == "wait" and rname and "cond" in rname.lower():
                continue
            out.append(self.finding(
                mod, node.lineno,
                f"[no-blocking-under-lock] {name}() inside a "
                f"`with *._lock:` section — the lock brackets enqueue "
                f"only, never a wait (lock-narrowing rule, "
                f"docs/EXECUTOR.md)"))
        return out


# ---------------------------------------------------------------------------
# APM003 — skip-wrapper discipline for optional planes
# ---------------------------------------------------------------------------

# Optional-subsystem handles (None when the plane is off). The r7
# discipline: feature off = ONE `is None` check on the hot path and
# ZERO registry names — so every call THROUGH one of these attributes
# must sit behind an `is (not) None` guard of that handle (enclosing
# `if`, or a preceding early-return), or bind it to a local first
# (`f = self.fault; if f is not None: f.fire(...)` — the canonical
# form, which this rule never flags).
OPTIONAL_HANDLES = frozenset({
    "fault", "flight", "tracer", "slo", "tier", "prefetch", "recorder",
    "wtrace", "decisions", "policy", "stream",
})

# metric-registry factory methods (import-time registration ban)
_REGISTRY_FACTORIES = frozenset({"counter", "gauge", "histogram"})


class SkipWrapperRule(Rule):
    """APM003: (a) no metric registration at import time — a module
    that registers `flight.*`/`fault.*` names on import makes the
    "off = zero registry names" contract unfalsifiable (the
    metrics_overhead_check pins it at runtime; this pins it in the
    source); (b) a call through an optional-plane handle
    (`srv.fault.fire(...)`) must be guarded by an `is None` check of
    that handle — unguarded uses crash the hot path the moment the
    plane is off."""

    id = "APM003"
    name = "skip-wrapper"
    doc = "optional-plane use without an `is None` guard, or " \
          "import-time metric registration"

    # -- (a) import-time registration ---------------------------------------

    def _import_time_registrations(self, mod: ModuleInfo) -> List[Finding]:
        out = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            is_factory = (isinstance(node.func, ast.Attribute)
                          and node.func.attr in _REGISTRY_FACTORIES)
            is_group = terminal_name(node.func) == "CounterGroup"
            if not (is_factory or is_group):
                continue
            if any(isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda))
                   for a in mod.ancestors(node)):
                continue  # inside a function: runtime registration
            out.append(self.finding(
                mod, node.lineno,
                "[skip-wrapper] metric registered at import time — "
                "registration must happen at construction, behind the "
                "plane's knob, so a disabled plane leaves zero "
                "registry names (docs/OBSERVABILITY.md overhead "
                "contract)"))
        return out

    # -- (b) unguarded handle use -------------------------------------------

    @staticmethod
    def _handle_in_chain(call: ast.Call) -> Optional[str]:
        """The optional-handle attribute a call reaches through, e.g.
        `srv.flight.freshness.note_push(...)` -> "flight". Only the
        RECEIVER chain counts (the callee attr itself is the method)."""
        node = call.func
        if not isinstance(node, ast.Attribute):
            return None
        node = node.value  # skip the method name
        while isinstance(node, ast.Attribute):
            if node.attr in OPTIONAL_HANDLES:
                return node.attr
            node = node.value
        return None

    @staticmethod
    def _guarded(mod: ModuleInfo, call: ast.Call, handle: str) -> bool:
        # enclosing if/while/ternary whose test None-checks the handle
        for anc in mod.ancestors(call):
            test = getattr(anc, "test", None)
            if isinstance(anc, (ast.If, ast.While, ast.IfExp)) and \
                    test is not None and _has_none_compare(test) and \
                    _mentions_handle(test, handle):
                return True
            # preceding early-return guard in any enclosing block:
            # `if x.handle is None: return` before this statement
            for field in ("body", "orelse", "finalbody"):
                block = getattr(anc, field, None)
                if not isinstance(block, list):
                    continue
                for stmt in block:
                    if stmt.lineno >= call.lineno:
                        break
                    if isinstance(stmt, ast.If) and \
                            _has_none_compare(stmt.test) and \
                            _mentions_handle(stmt.test, handle) and \
                            _terminates(stmt.body):
                        return True
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break  # guards don't cross function boundaries
        return False

    def check_module(self, mod: ModuleInfo,
                     ctx: ProjectContext) -> List[Finding]:
        out = self._import_time_registrations(mod)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            handle = self._handle_in_chain(node)
            if handle is None:
                continue
            if self._guarded(mod, node, handle):
                continue
            out.append(self.finding(
                mod, node.lineno,
                f"[skip-wrapper] call through optional handle "
                f"`.{handle}` without an `is None` guard — the plane "
                f"is None when off; bind it to a local and test once "
                f"(`h = x.{handle}` / `if h is not None:`), the r7 "
                f"skip-wrapper discipline"))
        return out


# ---------------------------------------------------------------------------
# APM004 — raw-thread ban
# ---------------------------------------------------------------------------

# Paths (repo-relative prefixes/suffixes) still allowed to own threads:
# the executor's worker pool IS the thread plane; the launcher and the
# DCN van manage process-boundary I/O the executor cannot subsume; the
# metrics reporter predates r11 and is import-gated. Everything else
# runs as executor-stream programs since r11 — a new raw thread is an
# unaccounted, undrained producer.
RAW_THREAD_ALLOWLIST = (
    "adapm_tpu/exec/",
    "adapm_tpu/launcher.py",
    "adapm_tpu/parallel/dcn.py",
    "adapm_tpu/obs/reporter.py",
    # the transport plane's threads are process-boundary I/O by nature
    # (socket readers, membership beats that must outlive the executor
    # into the teardown window, the loopback fallback drainer) — the
    # same exemption the DCN van carries
    "adapm_tpu/net/",
)


class RawThreadBanRule(Rule):
    """APM004: `threading.Thread(...)` outside the allowlist. r11
    subsumed every subsystem thread (sync loop, prefetch pipeline, tier
    maintenance, serve dispatchers, SLO ticks) into executor streams —
    ordered, drained at shutdown, visible in queue/overlap accounting.
    A raw thread has none of that; route the work through
    `Server.exec.submit` instead, or carry a justified suppression."""

    id = "APM004"
    name = "raw-thread-ban"
    doc = "threading.Thread outside the executor/launcher/dcn/reporter " \
          "allowlist"

    def check_module(self, mod: ModuleInfo,
                     ctx: ProjectContext) -> List[Finding]:
        if any(mod.relpath.startswith(p) or mod.relpath == p
               for p in RAW_THREAD_ALLOWLIST):
            return []
        out = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            is_thread = (isinstance(fn, ast.Attribute)
                         and fn.attr == "Thread"
                         and terminal_name(fn.value) == "threading") or \
                        (isinstance(fn, ast.Name) and fn.id == "Thread")
            if not is_thread:
                continue
            out.append(self.finding(
                mod, node.lineno,
                "[raw-thread-ban] threading.Thread outside the "
                "allowlist — background work runs as executor-stream "
                "programs (Server.exec.submit) so it is ordered, "
                "drained at shutdown, and visible in the exec.* "
                "accounting (docs/EXECUTOR.md)"))
        return out


# ---------------------------------------------------------------------------
# APM005 — donation after dispatch
# ---------------------------------------------------------------------------


class DonationAfterDispatchRule(Rule):
    """APM005: a LOCAL variable passed at a `donate_argnums` position
    of a jitted program is consumed by the dispatch — its device buffer
    is invalid the moment the call returns. Reading it afterwards (in
    the same function, before any rebind) intermittently segfaults or
    returns garbage depending on allocator reuse. The donation map is
    derived from the `@partial(jax.jit, donate_argnums=...)` decorators
    across the whole tree, so the rule can never lag the programs."""

    id = "APM005"
    name = "donation-after-dispatch"
    doc = "donated local read after the dispatching call"

    def check_module(self, mod: ModuleInfo,
                     ctx: ProjectContext) -> List[Finding]:
        out = []
        for fn in _iter_functions(mod.tree):
            out.extend(self._check_function(mod, ctx, fn))
        return out

    def _check_function(self, mod: ModuleInfo, ctx: ProjectContext,
                        fn) -> List[Finding]:
        out = []
        # loads/stores of every name in this function (Name NODES, not
        # just lines: a multi-line call's own argument loads must never
        # count as "read after the dispatch")
        loads: Dict[str, List[ast.Name]] = {}
        stores: Dict[str, List[int]] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Load):
                    loads.setdefault(node.id, []).append(node)
                else:
                    stores.setdefault(node.id, []).append(node.lineno)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _callee_program_name(mod, node)
            donated = ctx.donations.get(name or "")
            if not donated:
                continue
            own = {id(n) for n in ast.walk(node)
                   if isinstance(n, ast.Name)}
            end = getattr(node, "end_lineno", node.lineno)
            for idx in donated:
                if idx >= len(node.args):
                    continue
                arg = node.args[idx]
                if not isinstance(arg, ast.Name):
                    continue  # attributes rebind via `self.x = prog(...)`
                # alive again at the first rebind after the call (the
                # `a = prog(a, ...)` idiom rebinds on the same line)
                rebinds = [ln for ln in stores.get(arg.id, ())
                           if ln >= node.lineno]
                horizon = min(rebinds) if rebinds else float("inf")
                bad = [n.lineno for n in loads.get(arg.id, ())
                       if id(n) not in own and end < n.lineno < horizon]
                if bad:
                    out.append(self.finding(
                        mod, min(bad),
                        f"[donation-after-dispatch] `{arg.id}` was "
                        f"donated to {name}() at line {node.lineno} "
                        f"and read again before any rebind — the "
                        f"buffer is consumed by the dispatch; use the "
                        f"program's RESULT or copy before donating"))
        return out


# ---------------------------------------------------------------------------
# APM006 — revalidate before enqueue
# ---------------------------------------------------------------------------

# the versioned placement state the optimistic planners snapshot
_VERSION_ATTRS = frozenset({"topology_version"})

# store/server entry points whose under-lock call constitutes a
# placement-dependent program ENQUEUE
_ENQUEUE_CALLS = frozenset({
    "_pull", "_push", "gather", "stage_gather", "scatter_add",
    "set_rows", "replica_create", "sync_replicas", "relocate_rows",
})


class RevalidateBeforeEnqueueRule(Rule):
    """APM006: a function that snapshots `topology_version` OUTSIDE the
    server lock (optimistic planning) and later enqueues a
    placement-dependent program UNDER the lock must re-read the version
    inside that locked section (`if srv.topology_version != tv: plan =
    None`). Skipping the re-check dispatches a plan computed against a
    topology that may have moved — the staged-pull/plan-cache
    correctness rule from r6, applied at every enqueue site."""

    id = "APM006"
    name = "revalidate-before-enqueue"
    doc = "optimistic topology snapshot without an under-lock re-check"

    def check_module(self, mod: ModuleInfo,
                     ctx: ProjectContext) -> List[Finding]:
        out = []
        for fn in _iter_functions(mod.tree):
            out.extend(self._check_function(mod, fn))
        return out

    def _check_function(self, mod: ModuleInfo, fn) -> List[Finding]:
        version_reads = []   # (line, under_lock)
        lock_blocks = []     # ast.With nodes guarding _lock
        for node in ast.walk(fn):
            if isinstance(node, ast.Attribute) and \
                    node.attr in _VERSION_ATTRS and \
                    isinstance(node.ctx, ast.Load):
                version_reads.append(
                    (node.lineno,
                     _enclosing_with(mod, node, _LOCK_ATTRS)))
            elif isinstance(node, ast.With) and \
                    any(_with_item_is(i, _LOCK_ATTRS)
                        for i in node.items):
                lock_blocks.append(node)
        outside = [ln for ln, locked in version_reads if not locked]
        if not outside:
            return []
        first_read = min(outside)
        out = []
        for wb in lock_blocks:
            if wb.lineno < first_read:
                continue
            enqueues = [n for n in ast.walk(wb)
                        if isinstance(n, ast.Call)
                        and terminal_name(n.func) in _ENQUEUE_CALLS]
            if not enqueues:
                continue
            revalidated = any(
                isinstance(n, ast.Attribute)
                and n.attr in _VERSION_ATTRS
                and isinstance(n.ctx, ast.Load)
                for n in ast.walk(wb))
            if not revalidated:
                out.append(self.finding(
                    mod, enqueues[0].lineno,
                    f"[revalidate-before-enqueue] enqueue under the "
                    f"lock after an out-of-lock topology_version "
                    f"snapshot (line {first_read}) without re-reading "
                    f"it under the lock — revalidate or drop the "
                    f"optimistic plan (r6 staged-pull discipline)"))
        return out


# ---------------------------------------------------------------------------
# APM007 — metric-catalog drift
# ---------------------------------------------------------------------------

_METRIC_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z0-9_<>{}]+)+$")


class _RegistrationScanner(ast.NodeVisitor):
    """Collect metric registrations from one module: literal names,
    dynamic prefixes (f-strings), CounterGroup expansions, and
    one-level registering helpers (`def _hist(name): ...
    registry.histogram(name, ...)` / `mk = lambda n:
    registry.counter(f"plan_cache.{n}")`)."""

    def __init__(self, mod: ModuleInfo):
        self.mod = mod
        self.literals: List[Tuple[str, int]] = []   # (name, line)
        self.prefixes: List[Tuple[str, int]] = []   # (prefix, line)
        # helper name -> "" (identity: literal arg IS the name) or the
        # f-string's literal prefix (name = prefix + arg)
        self.helpers: Dict[str, str] = {}
        # module-level literal string tuples (incl. class attributes),
        # for `for name in FIELDS:` expansion
        self.str_tuples: Dict[str, Tuple[str, ...]] = {}
        self._collect_tuples()
        self._collect_helpers()

    # -- literal tuple assignments ------------------------------------------

    def _collect_tuples(self):
        for node in ast.walk(self.mod.tree):
            if not isinstance(node, ast.Assign):
                continue
            if not isinstance(node.value, (ast.Tuple, ast.List)):
                continue
            elts = node.value.elts
            if not elts or not all(isinstance(e, ast.Constant)
                                   and isinstance(e.value, str)
                                   for e in elts):
                continue
            vals = tuple(e.value for e in elts)
            for t in node.targets:
                n = terminal_name(t)
                if n:
                    self.str_tuples[n] = vals

    # -- registering helpers -------------------------------------------------

    @staticmethod
    def _fstring_split(js: ast.JoinedStr) -> Optional[Tuple[str, str]]:
        """(prefix, param) for a single-placeholder f-string like
        f"plan_cache.{n}"; None for anything more complex."""
        prefix = ""
        param = None
        for part in js.values:
            if isinstance(part, ast.Constant):
                if param is not None and part.value:
                    return None  # trailing literal: too complex
                prefix += str(part.value)
            elif isinstance(part, ast.FormattedValue):
                if param is not None or \
                        not isinstance(part.value, ast.Name):
                    return None
                param = part.value.id
        return (prefix, param) if param is not None else None

    def _collect_helpers(self):
        for node in ast.walk(self.mod.tree):
            fn_name, params, body_calls = None, None, None
            if isinstance(node, ast.FunctionDef):
                fn_name = node.name
                params = [a.arg for a in node.args.args]
                body_calls = node
            elif isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Lambda):
                fn_name = terminal_name(node.targets[0])
                params = [a.arg for a in node.value.args.args]
                body_calls = node.value
            elif isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.IfExp):
                # `mk = (lambda n: reg...) if use_reg else (lambda n: ...)`
                for half in (node.value.body, node.value.orelse):
                    if isinstance(half, ast.Lambda):
                        self._maybe_helper(
                            terminal_name(node.targets[0]),
                            [a.arg for a in half.args.args], half)
                continue
            if fn_name is None or body_calls is None:
                continue
            self._maybe_helper(fn_name, params, body_calls)

    def _maybe_helper(self, fn_name, params, scope):
        if not fn_name or not params:
            return
        for call in ast.walk(scope):
            if not isinstance(call, ast.Call):
                continue
            if not (isinstance(call.func, ast.Attribute)
                    and call.func.attr in _REGISTRY_FACTORIES):
                continue
            if not call.args:
                continue
            arg = call.args[0]
            if isinstance(arg, ast.Name) and arg.id == params[0]:
                self.helpers.setdefault(fn_name, "")
            elif isinstance(arg, ast.JoinedStr):
                split = self._fstring_split(arg)
                if split is not None and split[1] == params[0]:
                    self.helpers.setdefault(fn_name, split[0])

    # -- call sites ----------------------------------------------------------

    def visit_Call(self, node: ast.Call):
        self.generic_visit(node)
        fn = node.func
        if isinstance(fn, ast.Attribute) and \
                fn.attr in _REGISTRY_FACTORIES and node.args:
            self._record(node.args[0], node)
            return
        tname = terminal_name(fn)
        if tname == "CounterGroup" and len(node.args) >= 3:
            prefix_node, keys_node = node.args[1], node.args[2]
            if isinstance(prefix_node, ast.Constant):
                prefix = str(prefix_node.value)
                keys = None
                if isinstance(keys_node, (ast.Tuple, ast.List)) and \
                        all(isinstance(e, ast.Constant)
                            for e in keys_node.elts):
                    keys = [e.value for e in keys_node.elts]
                elif isinstance(keys_node, ast.Name):
                    keys = self.str_tuples.get(keys_node.id)
                if keys:
                    for k in keys:
                        self.literals.append(
                            (f"{prefix}.{k}", node.lineno))
                else:
                    self.prefixes.append((prefix + ".", node.lineno))
            return
        if tname in self.helpers and node.args:
            prefix = self.helpers[tname]
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and \
                    isinstance(arg.value, str):
                self.literals.append((prefix + arg.value, node.lineno))
            else:
                self._record_dynamic(prefix, arg, node)

    def _record(self, arg: ast.AST, node: ast.Call):
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            self.literals.append((arg.value, node.lineno))
        elif isinstance(arg, ast.JoinedStr):
            prefix = ""
            for part in arg.values:
                if isinstance(part, ast.Constant):
                    prefix += str(part.value)
                else:
                    break
            if prefix:
                self.prefixes.append((prefix, node.lineno))
        elif isinstance(arg, ast.Name):
            # loop variable over a literal tuple in this module:
            # `for name in SyncStats.FIELDS: reg.gauge(f"sync.{name}")`
            # is handled by the f-string branch; a bare Name arg is a
            # helper param (handled in _collect_helpers) or opaque
            pass

    def _record_dynamic(self, prefix: str, arg: ast.AST, node: ast.Call):
        if prefix:
            self.prefixes.append((prefix, node.lineno))


class MetricCatalogRule(Rule):
    """APM007: the metric namespace must agree across three surfaces —
    the registration call sites (`registry.counter("kv.pull_s")`, ...),
    the docs/OBSERVABILITY.md "Metric catalog" table, and the
    `metrics_snapshot()` schema section list. v1->v9 schema churn had
    no mechanical check; this rule is it. Literal registrations must
    appear in the catalog (and their section in the schema block);
    literal catalog rows of registry kinds (counter/gauge/histogram)
    must be registered somewhere (exactly, or under a dynamic
    registration prefix like `fault.injections.`). Rows whose kind is
    derived/merged/snapshot describe computed snapshot surfaces, not
    registry names, and rows with `…` are explicitly non-exhaustive —
    both are exempt from the code-presence direction."""

    id = "APM007"
    name = "metric-catalog-drift"
    doc = "metric names out of sync between code and " \
          "docs/OBSERVABILITY.md"

    # doc rows of these kinds are not registry registrations
    _EXEMPT_KINDS = ("derived", "merged", "snapshot")

    def check_project(self, ctx: ProjectContext) -> List[Finding]:
        doc = ctx.docs.get("observability")
        if doc is None:
            return []
        doc_path, doc_text = doc
        literals: List[Tuple[str, str, int]] = []  # (name, path, line)
        prefixes: List[str] = []
        for mod in ctx.modules:
            if mod.relpath.endswith("obs/metrics.py"):
                continue  # the registry itself, not a call site
            if "/lint/" in mod.relpath:
                continue  # the linter registers nothing
            sc = _RegistrationScanner(mod)
            sc.visit(mod.tree)
            literals.extend((n, mod.relpath, ln) for n, ln in sc.literals)
            prefixes.extend(p for p, _ in sc.prefixes)
        cat_literals, cat_patterns, exempt, row_lines = \
            self._parse_catalog(doc_text)
        sections = self._parse_schema_sections(doc_text)
        out: List[Finding] = []
        # code -> doc
        for name, path, line in sorted(set(literals)):
            sec = name.split(".", 1)[0]
            if sections and sec not in sections:
                out.append(self.finding(
                    path, line,
                    f"[metric-catalog-drift] metric `{name}`'s section "
                    f"`{sec}` is not in the metrics_snapshot() schema "
                    f"block of docs/OBSERVABILITY.md"))
            if name in cat_literals or name in exempt:
                continue
            if any(name.startswith(p) for p in cat_patterns):
                continue
            out.append(self.finding(
                path, line,
                f"[metric-catalog-drift] metric `{name}` is registered "
                f"here but missing from the docs/OBSERVABILITY.md "
                f"catalog table — add a row (name, kind, unit, "
                f"meaning)"))
        # doc -> code
        code_names = {n for n, _, _ in literals}
        for name in sorted(cat_literals - exempt):
            if name in code_names:
                continue
            if any(name.startswith(p) for p in prefixes):
                continue
            out.append(self.finding(
                doc_path, row_lines.get(name, 1),
                f"[metric-catalog-drift] catalog row `{name}` has no "
                f"registration in the code — stale doc (delete the "
                f"row) or a renamed metric (fix the name)"))
        return out

    # -- doc parsing ---------------------------------------------------------

    def _parse_catalog(self, text: str):
        """(literal names, pattern prefixes, exempt names, name->line)
        from the `## Metric catalog` table. A backticked token expands
        on `/` and `,`; fragments without a dot re-prefix with the
        row's section; tokens containing `<`/`{`/`…`/`*` become
        prefix patterns; rows whose kind is derived/merged/snapshot or
        whose name cell carries `…` are exempt from doc->code."""
        lines = text.splitlines()
        in_catalog = False
        literals: set = set()
        patterns: set = set()
        exempt: set = set()
        row_lines: Dict[str, int] = {}
        for i, line in enumerate(lines, start=1):
            if line.startswith("## "):
                in_catalog = line.strip() == "## Metric catalog"
                continue
            if not in_catalog or not line.startswith("|"):
                continue
            cells = [c.strip() for c in line.strip("|").split("|")]
            if len(cells) < 2 or set(cells[0]) <= {"-", " "}:
                continue
            name_cell, kind_cell = cells[0], cells[1]
            row_exempt = any(k in kind_cell.lower()
                             for k in self._EXEMPT_KINDS) or \
                "…" in name_cell or "..." in name_cell
            tokens = re.findall(r"`([^`]+)`", name_cell)
            # tokens like "(+ per-stream `.<stream>`)" are suffix
            # patterns for the preceding name: note the base as a prefix
            section = None
            for tok in tokens:
                tok = tok.strip()
                if tok.startswith("."):
                    if section:
                        patterns.add(section + ".")
                    continue
                for frag in re.split(r"[/,]", tok):
                    frag = frag.strip()
                    if not frag or frag in ("…", "..."):
                        continue
                    if "." not in frag and section:
                        frag = f"{section}.{frag}"
                    if any(c in frag for c in "<{*…"):
                        prefix = re.split(r"[<{*…]", frag)[0]
                        if prefix:
                            patterns.add(prefix)
                        continue
                    if not _METRIC_NAME_RE.match(frag):
                        continue
                    section = frag.split(".", 1)[0]
                    literals.add(frag)
                    row_lines.setdefault(frag, i)
                    if row_exempt:
                        exempt.add(frag)
        return literals, patterns, exempt, row_lines

    @staticmethod
    def _parse_schema_sections(text: str) -> set:
        """Section names from the metrics_snapshot() schema block
        (`"kv": {...}` entries in the first fenced block after the
        heading)."""
        m = re.search(r"##\s*`Server\.metrics_snapshot\(\)`.*?```(.*?)```",
                      text, re.S)
        if m is None:
            return set()
        return set(re.findall(r'"([a-z_]+)":\s*\{', m.group(1)))


# ---------------------------------------------------------------------------
# APM008 — device-API confinement
# ---------------------------------------------------------------------------

# jax program-construction / transfer attributes (`jax.<attr>`) and
# bare names whose use constitutes constructing a device program or
# placing a buffer — the DevicePort surface (adapm_tpu/device/port.py).
_DEVICE_API_ATTRS = frozenset({"jit", "device_put", "pmap"})
_DEVICE_API_NAMES = frozenset({"shard_map"})

# The one place allowed to touch the device APIs directly: the port
# implementations. Everything else reaches the accelerator through a
# DevicePort method (store dispatches, port.compile for fused steps,
# port.compile_collective for exchanges, port.put_* for transfers), so
# a new backend is one new port class — the ISSUE 14 refactor contract.
# device/refport.py (the pure-NumPy reference port, ISSUE 16) sits
# inside the allowlist but deliberately needs none of it: it imports no
# jax at all, which scripts/portdiff_check.py asserts — the existence
# proof that the DevicePort seam is honest (a backend that never
# touches the device APIs still passes every storm bitwise).
DEVICE_PLANE_ALLOWLIST = ("adapm_tpu/device/",)


class DeviceApiConfinementRule(Rule):
    """APM008: `jax.jit` / `jax.device_put` / `jax.pmap` / `shard_map`
    only under `adapm_tpu/device/`. A jit or device_put call anywhere
    else re-opens the tree-wide-edit problem the DevicePort closed:
    the next accelerator backend would have to find and port that site
    too. Route program construction through `port.compile(...)` /
    `port.compile_collective(...)`, transfers through `port.put_*` /
    `port.install_pool`, and data-plane dispatch through the store's
    port methods. Model-math / inherently-backend-specific modules
    (KGE eval programs, Pallas kernels) carry justified suppressions,
    never a widened allowlist (docs/INVARIANTS.md#apm008)."""

    id = "APM008"
    name = "device-api-confinement"
    doc = "jax program-construction API outside adapm_tpu/device/"

    @staticmethod
    def _attr_root(node: ast.AST) -> Optional[str]:
        """Root Name of an attribute chain (`jax.experimental.
        shard_map.shard_map` -> "jax"); None for non-Name roots."""
        while isinstance(node, ast.Attribute):
            node = node.value
        return node.id if isinstance(node, ast.Name) else None

    def check_module(self, mod: ModuleInfo,
                     ctx: ProjectContext) -> List[Finding]:
        if any(mod.relpath.startswith(p)
               for p in DEVICE_PLANE_ALLOWLIST):
            return []
        banned_attrs = _DEVICE_API_ATTRS | _DEVICE_API_NAMES
        out = []
        seen = set()  # (line, attr): a nested chain like
        # jax.experimental.shard_map.shard_map matches twice
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Attribute) and \
                    node.attr in banned_attrs and \
                    self._attr_root(node.value) == "jax":
                key = (node.lineno, node.attr)
                if key in seen:
                    continue
                seen.add(key)
                out.append(self.finding(
                    mod, node.lineno,
                    f"[device-api-confinement] jax …{node.attr} outside "
                    f"adapm_tpu/device/ — construct programs through "
                    f"the DevicePort (port.compile / port.put_* / the "
                    f"store's dispatch methods) so a new accelerator "
                    f"backend is one port implementation, not a "
                    f"tree-wide edit (docs/INVARIANTS.md#apm008)"))
            elif isinstance(node, ast.Name) and \
                    node.id in _DEVICE_API_NAMES and \
                    isinstance(node.ctx, ast.Load):
                out.append(self.finding(
                    mod, node.lineno,
                    "[device-api-confinement] shard_map outside "
                    "adapm_tpu/device/ — collective programs are "
                    "constructed by port.compile_collective "
                    "(docs/INVARIANTS.md#apm008)"))
            elif isinstance(node, ast.ImportFrom):
                names = {a.name for a in node.names}
                banned = names & (_DEVICE_API_NAMES |
                                  (_DEVICE_API_ATTRS
                                   if (node.module or "") == "jax"
                                   else frozenset()))
                if banned:
                    out.append(self.finding(
                        mod, node.lineno,
                        f"[device-api-confinement] importing "
                        f"{sorted(banned)} outside adapm_tpu/device/ — "
                        f"reach the device stack through the "
                        f"DevicePort (docs/INVARIANTS.md#apm008)"))
            elif isinstance(node, ast.Import):
                # plain `import jax.experimental.shard_map` — the
                # evasion form the attribute check alone would miss
                mods = [a.name for a in node.names
                        if set(a.name.split(".")) & banned_attrs]
                if mods:
                    out.append(self.finding(
                        mod, node.lineno,
                        f"[device-api-confinement] importing "
                        f"{sorted(mods)} outside adapm_tpu/device/ — "
                        f"reach the device stack through the "
                        f"DevicePort (docs/INVARIANTS.md#apm008)"))
        return out


# ---------------------------------------------------------------------------


def default_rules() -> List[Rule]:
    """The shipping rule set, in ID order (analyzer entry point)."""
    return [
        GateCoverageRule(),
        NoBlockingUnderLockRule(),
        SkipWrapperRule(),
        RawThreadBanRule(),
        DonationAfterDispatchRule(),
        RevalidateBeforeEnqueueRule(),
        MetricCatalogRule(),
        DeviceApiConfinementRule(),
    ]
