"""adapm-lint: the AST invariant analyzer (ISSUE 11 tentpole).

The system's correctness rests on a handful of concurrency disciplines
that used to live only in prose (docs/EXECUTOR.md's lock-narrowing
rule, the r11 dispatch-gate coverage, the r7 skip-wrapper contract,
the topology/epoch revalidate-under-lock pattern) and were enforced
only probabilistically, by randomized storm tests. This module checks
them mechanically, on every run, over the package's own ASTs — the way
AdaPM's per-key sequential-consistency contract is pinned by
construction rather than by sampling (PAPER.md). docs/INVARIANTS.md is
the catalog: one section per rule, with the prose rationale each rule
mechanizes.

Shape:

  - A **Rule** owns an ID (``APM001``..), a short name, and a
    ``check_module`` hook (per-file AST walk) and/or a
    ``check_project`` hook (whole-tree facts, e.g. the metric-catalog
    drift rule needs every registration site AND the docs). Rules are
    registered in ``adapm_tpu/lint/rules.py`` and looked up through
    ``default_rules()``.
  - The **Analyzer** parses every file once, builds shared project
    facts (import aliases, the donate_argnums map), runs the rules,
    applies suppressions, and emits a deterministic report.
  - A **suppression** is an in-source escape hatch::

        with self._lock:
            s.block()  # apm-lint: disable=APM002 donated buffers are
                       # replaced by racing ops; blocking on one raises

    It must name the rule AND carry a non-empty justification, covers
    findings on its own line, the rest of its contiguous comment
    block, and the first code line after the block (justifications
    routinely wrap), and FAILS the run when unused (``APM000``) — a suppression
    that outlives its violation is stale documentation, deleted, not
    kept. The meta-rule APM000 also covers malformed suppressions and
    unparseable files.
  - Reports: ``Report.to_json()`` is byte-deterministic for a given
    tree (sorted findings, repo-relative posix paths, no timestamps —
    pinned by tests/test_lint.py), ``Report.to_text()`` is the human
    ``path:line: APM00N [name] message`` form.

Run it via ``scripts/invariant_lint_check.py`` (wired into
scripts/run_tests.sh; zero unsuppressed findings, zero unused
suppressions) or programmatically::

    from adapm_tpu.lint import Analyzer
    rep = Analyzer(root).run()
    assert not rep.findings, rep.to_text()

Pure stdlib (ast/re/json): the linter must import in any environment
the package sources exist in, device stack present or not.
"""
from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize
from typing import Dict, List, Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One rule violation at a source location (path is repo-relative,
    posix separators — part of the deterministic-report contract)."""

    path: str
    line: int
    rule: str
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


@dataclasses.dataclass
class Suppression:
    """One ``# apm-lint: disable=APM00N <justification>`` comment."""

    path: str
    line: int            # line the comment sits on (1-based)
    rules: Tuple[str, ...]
    justification: str
    used: bool = False


# the suppression-comment shape: "apm-lint: disable=" + one or more
# comma-separated rule ids + the (required) justification text
_SUPPRESS_RE = re.compile(
    r"#\s*apm-lint:\s*disable=([A-Z]{3}\d{3}(?:\s*,\s*[A-Z]{3}\d{3})*)"
    r"[ \t]*(.*)$")


class ModuleInfo:
    """One parsed source file plus the per-file facts rules share:
    the AST (with parent back-links), source lines, and the set of
    names bound by imports (used to tell a module-attribute call
    ``dequant._write_main_rows_fp16(...)`` from a method call
    ``self._sync_replicas(...)`` — only the former can be a
    module-level device program)."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=relpath)
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                child._apm_parent = node  # type: ignore[attr-defined]
        self.imported_names = self._collect_imports()

    def _collect_imports(self) -> set:
        names = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    names.add(a.asname or a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    names.add(a.asname or a.name)
        return names

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return getattr(node, "_apm_parent", None)

    def ancestors(self, node: ast.AST):
        cur = self.parent(node)
        while cur is not None:
            yield cur
            cur = self.parent(cur)


class ProjectContext:
    """Whole-tree facts shared by the rules: every parsed module, the
    package-wide ``donate_argnums`` map (function name -> donated
    positional indices, derived from the ``@partial(jax.jit,
    donate_argnums=...)`` decorators themselves so the manifest can
    never drift from the programs), and the doc sources project rules
    read (docs/OBSERVABILITY.md for the catalog-drift rule)."""

    def __init__(self, modules: Sequence[ModuleInfo],
                 docs: Optional[Dict[str, Tuple[str, str]]] = None):
        self.modules = list(modules)
        # docs: logical name -> (relpath, text)
        self.docs = dict(docs or {})
        self.donations = self._collect_donations()

    def _collect_donations(self) -> Dict[str, Tuple[int, ...]]:
        out: Dict[str, Tuple[int, ...]] = {}
        for mod in self.modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.FunctionDef):
                    continue
                idx = _donated_indices(node)
                if idx:
                    out[node.name] = idx
        return out


def _donated_indices(fn: ast.FunctionDef) -> Tuple[int, ...]:
    """Donated positional indices from a ``@partial(jax.jit,
    donate_argnums=...)`` decorator, or () when the function does not
    donate."""
    for dec in fn.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        if terminal_name(dec.func) != "partial":
            continue
        if not dec.args or terminal_name(dec.args[0]) != "jit":
            continue
        for kw in dec.keywords:
            if kw.arg != "donate_argnums":
                continue
            v = kw.value
            if isinstance(v, ast.Tuple):
                return tuple(int(e.value) for e in v.elts
                             if isinstance(e, ast.Constant))
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
    return ()


def terminal_name(node: ast.AST) -> Optional[str]:
    """The rightmost identifier of a Name/Attribute chain (``x`` of
    ``a.b.x``), or None for anything else."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


class Rule:
    """Base class. Subclasses set ``id``/``name``/``doc`` and override
    one (or both) of the hooks."""

    id = "APM000"
    name = "meta"
    doc = ""

    def check_module(self, mod: ModuleInfo,
                     ctx: ProjectContext) -> List[Finding]:
        return []

    def check_project(self, ctx: ProjectContext) -> List[Finding]:
        return []

    def finding(self, mod_or_path, line: int, message: str) -> Finding:
        path = mod_or_path.relpath if isinstance(mod_or_path, ModuleInfo) \
            else mod_or_path
        return Finding(path=path, line=line, rule=self.id, message=message)


@dataclasses.dataclass
class Report:
    """Analyzer output: post-suppression findings (sorted), the
    suppressions that fired, and file/rule accounting."""

    findings: List[Finding]
    suppressions_used: List[Suppression]
    files_scanned: int
    rules: List[str]

    def ok(self) -> bool:
        return not self.findings

    def to_json(self) -> str:
        """Deterministic (same tree -> byte-identical) JSON report —
        sorted findings, sorted keys, no timestamps."""
        payload = {
            "version": 1,
            "files_scanned": self.files_scanned,
            "rules": sorted(self.rules),
            "findings": [dataclasses.asdict(f)
                         for f in sorted(self.findings)],
            "suppressions_used": [
                {"path": s.path, "line": s.line,
                 "rules": sorted(s.rules),
                 "justification": s.justification}
                for s in sorted(self.suppressions_used,
                                key=lambda s: (s.path, s.line))],
        }
        return json.dumps(payload, indent=2, sort_keys=True) + "\n"

    def to_text(self) -> str:
        if not self.findings:
            return (f"adapm-lint: clean ({self.files_scanned} files, "
                    f"{len(self.rules)} rules, "
                    f"{len(self.suppressions_used)} suppressions used)\n")
        out = [f.format() for f in sorted(self.findings)]
        out.append(f"adapm-lint: {len(self.findings)} finding(s) over "
                   f"{self.files_scanned} files")
        return "\n".join(out) + "\n"


class Analyzer:
    """Parse -> facts -> rules -> suppressions -> report (module
    docstring). ``root`` anchors the repo-relative paths in findings;
    ``paths`` defaults to every ``.py`` under ``<root>/adapm_tpu``
    except this linter's own fixtures; ``docs`` maps logical doc names
    to file paths (default: ``observability`` ->
    ``<root>/docs/OBSERVABILITY.md`` when present)."""

    def __init__(self, root: str, rules: Optional[Sequence[Rule]] = None,
                 paths: Optional[Sequence[str]] = None,
                 docs: Optional[Dict[str, str]] = None):
        self.root = os.path.abspath(root)
        if rules is None:
            from .rules import default_rules
            rules = default_rules()
        self.rules = list(rules)
        self._paths = list(paths) if paths is not None else None
        if docs is None:
            obs = os.path.join(self.root, "docs", "OBSERVABILITY.md")
            docs = {"observability": obs} if os.path.exists(obs) else {}
        self._doc_paths = docs

    # -- inputs --------------------------------------------------------------

    def _default_paths(self) -> List[str]:
        pkg = os.path.join(self.root, "adapm_tpu")
        out = []
        for dirpath, dirnames, filenames in os.walk(pkg):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__")
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
        return out

    def _relpath(self, path: str) -> str:
        rel = os.path.relpath(os.path.abspath(path), self.root)
        return rel.replace(os.sep, "/")

    # -- suppressions --------------------------------------------------------

    def _collect_suppressions(self, mod: ModuleInfo,
                              meta: List[Finding]) -> List[Suppression]:
        # real COMMENT tokens only (tokenize): a suppression-shaped
        # string literal — a doc example, this very regex — must not
        # create a suppression
        sups = []
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(mod.source).readline))
        except tokenize.TokenError:
            return sups
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            i = tok.start[0]
            m = _SUPPRESS_RE.search(tok.string)
            if m is None:
                continue
            rules = tuple(r.strip() for r in m.group(1).split(","))
            just = m.group(2).strip()
            if not just:
                meta.append(Finding(
                    path=mod.relpath, line=i, rule="APM000",
                    message="suppression without justification: "
                            "'# apm-lint: disable=<RULE> <why>' — the "
                            "reason is the point (docs/INVARIANTS.md "
                            "suppression policy)"))
                continue
            sups.append(Suppression(mod.relpath, i, rules, just))
        return sups

    @staticmethod
    def _suppressed_lines(mod: ModuleInfo, s: Suppression) -> List[int]:
        """Lines a suppression covers: its own line (trailing-comment
        style), the rest of its contiguous comment block, and the first
        code line after the block (comment-above-the-statement style —
        justifications routinely wrap over several comment lines)."""
        lines = [s.line]
        i = s.line  # 1-based; mod.lines[i] is the NEXT line
        while i < len(mod.lines):
            stripped = mod.lines[i].strip()
            lines.append(i + 1)
            if stripped and not stripped.startswith("#"):
                break  # first code line: covered, stop
            i += 1
        return lines

    def _apply_suppressions(self, modules: List[ModuleInfo],
                            findings: List[Finding],
                            sups: List[Suppression]) -> List[Finding]:
        by_rel = {m.relpath: m for m in modules}
        by_loc: Dict[Tuple[str, int], List[Suppression]] = {}
        for s in sups:
            for ln in self._suppressed_lines(by_rel[s.path], s):
                by_loc.setdefault((s.path, ln), []).append(s)
        kept = []
        for f in findings:
            hit = None
            for s in by_loc.get((f.path, f.line), ()):
                if f.rule in s.rules:
                    hit = s
                    break
            if hit is not None:
                hit.used = True
            else:
                kept.append(f)
        return kept

    # -- run -----------------------------------------------------------------

    def run(self) -> Report:
        paths = self._paths if self._paths is not None \
            else self._default_paths()
        meta: List[Finding] = []
        modules: List[ModuleInfo] = []
        for p in paths:
            rel = self._relpath(p)
            try:
                with open(p, "r", encoding="utf-8") as fh:
                    src = fh.read()
                modules.append(ModuleInfo(p, rel, src))
            except (OSError, SyntaxError, ValueError) as e:
                meta.append(Finding(
                    path=rel, line=getattr(e, "lineno", 1) or 1,
                    rule="APM000",
                    message=f"unparseable source: "
                            f"{type(e).__name__}: {e}"))
        docs = {}
        for name, p in self._doc_paths.items():
            with open(p, "r", encoding="utf-8") as fh:
                docs[name] = (self._relpath(p), fh.read())
        ctx = ProjectContext(modules, docs=docs)

        findings: List[Finding] = []
        for rule in self.rules:
            for mod in modules:
                findings.extend(rule.check_module(mod, ctx))
            findings.extend(rule.check_project(ctx))

        sups: List[Suppression] = []
        for mod in modules:
            sups.extend(self._collect_suppressions(mod, meta))
        findings = self._apply_suppressions(modules, findings, sups)
        for s in sups:
            if not s.used:
                meta.append(Finding(
                    path=s.path, line=s.line, rule="APM000",
                    message=f"unused suppression for "
                            f"{','.join(s.rules)}: the violation it "
                            f"justified is gone — delete the comment "
                            f"(stale suppressions fail CI by design)"))
        return Report(
            findings=sorted(findings + meta),
            suppressions_used=[s for s in sups if s.used],
            files_scanned=len(modules),
            rules=[r.id for r in self.rules])
