"""Atomic timestamped logging (reference ALOG macro, dmlc/logging.h:129-143):
one writev-ish print per call so concurrent worker threads don't interleave,
prefixed with wall time since process start."""
from __future__ import annotations

import os
import sys
import threading
import time

_T0 = time.monotonic()
_LOCK = threading.Lock()


def alog(*parts, file=None) -> None:
    msg = " ".join(str(p) for p in parts)
    line = f"[{time.monotonic() - _T0:10.3f}] {msg}\n"
    with _LOCK:
        (file or sys.stdout).write(line)
        (file or sys.stdout).flush()


def verbose_level() -> int:
    """PS_VERBOSE-gated logging (reference PS_VLOG, postoffice.h:268)."""
    return int(os.environ.get("PS_VERBOSE", "0") or 0)
