"""Observability: key-event tracing and locality statistics.

Reference (SURVEY.md §5):
  - key tracing (PS_TRACE_KEYS + --sys.trace.keys): timestamped
    ALLOC/DEALLOC/REPLICA_SETUP/REPLICA_DROP/INTENT_START/INTENT_STOP events
    for traced keys, dumped to traces.<rank>.tsv at shutdown
    (coloc_kv_server_handle.h:86-104, 213-255, 978-992).
  - locality stats (PS_LOCALITY_STATS): per-key access / local-access
    counters written to locality_stats.rank.<r>.tsv
    (handle.h:206-210, 439-441, 961-975).

Here both are runtime-enabled (no compile-time define needed): tracing via
`--sys.trace.keys`, locality stats via `--sys.stats.locality`. Counter
updates are vectorized (np.add.at over the batch) so the overhead per op is
one masked scatter, not a per-key branch.
"""
from __future__ import annotations

import os
import time
from typing import List, Optional, Tuple

import numpy as np

# event names follow the reference's trace vocabulary
ALLOC = "ALLOC"
DEALLOC = "DEALLOC"
REPLICA_SETUP = "REPLICA_SETUP"
REPLICA_DROP = "REPLICA_DROP"
INTENT_START = "INTENT_START"
INTENT_STOP = "INTENT_STOP"
RELOCATE = "RELOCATE"

# dump schemas, fixed as module constants so trace TSVs keep a stable,
# diffable column order across runs (ISSUE 2 satellite; tests pin these)
TRACE_COLUMNS = ("time", "key", "event", "shard")
LOCALITY_COLUMNS = ("key", "accesses", "local_accesses",
                    "sampling_accesses")


def parse_trace_spec(spec: str, num_keys: int,
                     ) -> Optional[np.ndarray]:
    """Parse --sys.trace.keys (reference handle.h trace config):
    'all' | 'random-N-seed-S-range-A-B' | 'k1,k2,k3'. Returns traced key
    array or None."""
    if not spec:
        return None
    spec = spec.strip()
    if spec == "all":
        return np.arange(num_keys, dtype=np.int64)
    if spec.startswith("random-"):
        parts = spec.split("-")
        n = int(parts[1])
        seed = int(parts[parts.index("seed") + 1]) if "seed" in parts else 0
        if "range" in parts:
            i = parts.index("range")
            lo, hi = int(parts[i + 1]), int(parts[i + 2])
            if not (0 <= lo < hi <= num_keys):
                raise ValueError(
                    f"--sys.trace.keys range [{lo}, {hi}) outside the key "
                    f"space [0, {num_keys})")
        else:
            lo, hi = 0, num_keys
        rng = np.random.default_rng(seed)
        return np.unique(rng.integers(lo, hi, n).astype(np.int64))
    keys = np.unique(np.asarray(
        [int(k) for k in spec.split(",") if k.strip()], dtype=np.int64))
    if len(keys) and (keys[0] < 0 or keys[-1] >= num_keys):
        raise ValueError(
            f"--sys.trace.keys contains keys outside [0, {num_keys}): "
            f"{keys[(keys < 0) | (keys >= num_keys)].tolist()}")
    return keys


class KeyTracer:
    """Records timestamped placement events for a traced key subset."""

    def __init__(self, traced_keys: np.ndarray, num_keys: int):
        self._mask = np.zeros(num_keys, dtype=bool)
        self._mask[traced_keys] = True
        self.events: List[Tuple[float, int, str, int]] = []
        self._t0 = time.monotonic()

    def record(self, keys, event: str, shard: int = -1) -> None:
        keys = np.atleast_1d(np.asarray(keys, dtype=np.int64))
        hit = keys[self._mask[keys]]
        if len(hit) == 0:
            return
        t = time.monotonic() - self._t0
        for k in hit:
            self.events.append((t, int(k), event, shard))

    def dump(self, path: str) -> None:
        # deterministic row order: events are appended by several threads
        # (worker, sync, prefetch), so the list order varies run to run;
        # sorting by (time, key, event, shard) makes same-history dumps
        # diff cleanly
        with open(path, "w") as f:
            f.write("\t".join(TRACE_COLUMNS) + "\n")
            for t, k, e, s in sorted(self.events):
                f.write(f"{t:.6f}\t{k}\t{e}\t{s}\n")


class LocalityStats:
    """Per-key access counters: how many pulls/pushes, how many of those
    were served locally (owner or replica on the accessing shard)."""

    def __init__(self, num_keys: int, native_lib=None):
        self.accesses = np.zeros(num_keys, dtype=np.int64)
        self.local = np.zeros(num_keys, dtype=np.int64)
        self.sampling_accesses = np.zeros(num_keys, dtype=np.int64)
        self._native = native_lib

    def record(self, keys: np.ndarray, local_mask: np.ndarray) -> None:
        if self._native is not None:
            bad = self._native.adapm_count(
                np.ascontiguousarray(keys, np.int64),
                np.ascontiguousarray(local_mask, np.uint8), len(keys),
                len(self.accesses), self.accesses, self.local)
            if bad:
                raise IndexError(f"{bad} stat keys outside the key range")
            return
        from ..base import check_key_range
        check_key_range(keys, len(self.accesses), "stat key")
        np.add.at(self.accesses, keys, 1)
        np.add.at(self.local, keys, local_mask.astype(np.int64))

    def record_sampling(self, keys: np.ndarray) -> None:
        np.add.at(self.sampling_accesses, keys, 1)

    def dump(self, path: str) -> None:
        touched = np.nonzero(self.accesses + self.sampling_accesses)[0]
        with open(path, "w") as f:
            f.write("\t".join(LOCALITY_COLUMNS) + "\n")
            for k in touched:
                f.write(f"{k}\t{self.accesses[k]}\t{self.local[k]}"
                        f"\t{self.sampling_accesses[k]}\n")


def write_stats(stats_out: str, rank: int, tracer: Optional[KeyTracer],
                locality: Optional["LocalityStats"]) -> List[str]:
    """Dump enabled collectors into the stats dir (reference
    --sys.stats.out); returns written paths."""
    os.makedirs(stats_out, exist_ok=True)
    written = []
    if tracer is not None:
        p = os.path.join(stats_out, f"traces.{rank}.tsv")
        tracer.dump(p)
        written.append(p)
    if locality is not None:
        p = os.path.join(stats_out, f"locality_stats.rank.{rank}.tsv")
        locality.dump(p)
        written.append(p)
    return written
