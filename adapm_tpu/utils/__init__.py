"""App utility layer (reference include/utils.h + include/dmlc/logging.h)."""
import os

from .log import alog, verbose_level
from .stopwatch import Stopwatch

__all__ = ["Stopwatch", "alog", "verbose_level", "write_atomic"]


def write_atomic(path: str, data: bytes) -> None:
    """THE durable-write discipline (one implementation — checkpoint
    links, workload traces, and replay artifacts all use it): write to
    a writer-unique tmp, fsync, rename. A crash mid-write leaves the
    previous file (or nothing), never a torn one; the mkstemp-unique
    tmp name keeps two concurrent writers of the same path from
    truncating each other's bytes (last rename wins with a COMPLETE
    file)."""
    import tempfile
    fd, tmp = tempfile.mkstemp(
        prefix=os.path.basename(path) + ".", suffix=".tmp",
        dir=os.path.dirname(path) or ".")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
