"""App utility layer (reference include/utils.h + include/dmlc/logging.h)."""
from .log import alog, verbose_level
from .stopwatch import Stopwatch

__all__ = ["Stopwatch", "alog", "verbose_level"]
