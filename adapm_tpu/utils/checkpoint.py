"""Whole-manager checkpoint/restore.

The reference checkpoints only at the application level (pull full model ->
write; resume = push inside BeginSetup/EndSetup — kge.cc:327-401, SURVEY.md
§5 "Checkpoint / resume"); its adaptive state (ownership, replicas) is lost
on restart. Here the *entire* manager state is a handful of arrays, so a
checkpoint captures it exactly: pools (main/cache/delta per length class),
addressbook tables, registered intent horizons, and worker clocks. Restore
rebuilds the free-list allocators and the sync manager's replica registry
from the tables, so an adapted placement survives a restart.
"""
from __future__ import annotations

from typing import Dict

import numpy as np


FORMAT_VERSION = 1


def save_server(server, path: str) -> None:
    """Write the full manager state to an .npz (single-controller view)."""
    server.block()
    with server._lock:
        arrs: Dict[str, np.ndarray] = {
            "format_version": np.int64(FORMAT_VERSION),
            "num_keys": np.int64(server.num_keys),
            "num_shards": np.int64(server.num_shards),
            "value_lengths": server.value_lengths,
            "owner": server.ab.owner,
            "slot": server.ab.slot,
            "cache_slot": server.ab.cache_slot,
            "relocation_counter": server.ab.relocation_counter,
            "intent_end": server.sync.intent_end,
            "clocks": server._clocks,
        }
        for cid, st in enumerate(server.stores):
            arrs[f"main_{cid}"] = np.asarray(st.main)
            arrs[f"cache_{cid}"] = np.asarray(st.cache)
            arrs[f"delta_{cid}"] = np.asarray(st.delta)
    np.savez_compressed(path, **arrs)


def restore_server(server, path: str) -> None:
    """Restore state saved by save_server into a compatibly-constructed
    Server (same num_keys, value_lengths, shard count, pool geometry)."""
    import jax
    ck = np.load(path)
    assert int(ck["format_version"]) == FORMAT_VERSION
    assert int(ck["num_keys"]) == server.num_keys, "key count mismatch"
    assert int(ck["num_shards"]) == server.num_shards, "shard mismatch"
    assert (ck["value_lengths"] == server.value_lengths).all(), \
        "value-length layout mismatch"
    with server._lock:
        ab = server.ab
        ab.owner[:] = ck["owner"]
        ab.slot[:] = ck["slot"]
        ab.cache_slot[:] = ck["cache_slot"]
        ab.relocation_counter[:] = ck["relocation_counter"]
        ab.replica_count[:] = (ab.cache_slot >= 0).sum(axis=0)
        server.sync.intent_end[:] = ck["intent_end"]
        server._clocks[:] = ck["clocks"]
        # Workers registered before the restore carry their own _clock and
        # write it back on advance_clock — re-seed them so the first advance
        # after a restore can't regress the restored clocks (intent windows
        # and replica expiry are computed from these).
        for wid, w in server._workers.items():
            w._clock = int(server._clocks[wid])

        # pools back onto the mesh with their original shardings
        for cid, st in enumerate(server.stores):
            sh = st.ctx.shard0()
            for name in ("main", "cache", "delta"):
                arr = ck[f"{name}_{cid}"]
                cur = getattr(st, name)
                assert arr.shape == cur.shape, (
                    f"pool {name}_{cid} geometry mismatch: checkpoint "
                    f"{arr.shape} vs server {cur.shape}")
                setattr(st, name, jax.device_put(arr, sh))

        # rebuild free lists from table occupancy
        for cid in range(len(server.stores)):
            class_keys = np.nonzero(ab.key_class == cid)[0]
            _rebuild_alloc(ab.main_alloc[cid],
                           ab.owner[class_keys], ab.slot[class_keys])
            used_by_shard = [
                ab.cache_slot[s, class_keys] for s in range(server.num_shards)]
            _rebuild_cache_alloc(ab.cache_alloc[cid], used_by_shard)

        # rebuild the sync manager's replica registry
        from ..core.sync import key_channel
        for reps in server.sync.replicas:
            reps.clear()
        shards, keys = np.nonzero(ab.cache_slot >= 0)
        chans = key_channel(keys.astype(np.int64),
                            server.sync.num_channels)
        for k, s, c in zip(keys, shards, chans):
            server.sync.replicas[int(c)].add((int(k), int(s)))
        server.topology_version += 1
    server.block()


def _rebuild_alloc(alloc, owners: np.ndarray, slots: np.ndarray) -> None:
    for s in range(alloc.num_shards):
        alloc.set_used(s, slots[owners == s])


def _rebuild_cache_alloc(alloc, used_by_shard) -> None:
    for s in range(alloc.num_shards):
        row = np.asarray(used_by_shard[s])
        alloc.set_used(s, row[row >= 0])
