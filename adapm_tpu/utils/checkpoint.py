"""Whole-manager checkpoint/restore.

The reference checkpoints only at the application level (pull full model ->
write; resume = push inside BeginSetup/EndSetup — kge.cc:327-401, SURVEY.md
§5 "Checkpoint / resume"); its adaptive state (ownership, replicas) is lost
on restart. Here the *entire* manager state is a handful of arrays, so a
checkpoint captures it exactly: pools (main/cache/delta per length class),
addressbook tables, registered intent horizons, and worker clocks. Restore
rebuilds the free-list allocators and the sync manager's replica registry
from the tables, so an adapted placement survives a restart.

Multi-process: each rank writes `<path>.rank<r>.npz` with its local pools,
tables, and cross-process metadata (owner hints, relocation counters,
interest bitmasks), bracketed by the quiesce protocol (WaitSync -> Barrier
-> WaitSync) so the shards are mutually consistent; rank 0 also writes a
`<path>.manifest.npz` pinning the topology. Restore loads each rank's shard
into a freshly-launched job of the same shape — the adapted placement
(including cross-process relocations and replicas) survives the restart.
"""
from __future__ import annotations

import os
from typing import Dict

import numpy as np


# v3: pool slot counts are 8-aligned (core/store.py _round8), changing the
# saved raw-pool geometry — v2 checkpoints written before the alignment
# change cannot be restored into current pools and are rejected by version,
# not by an opaque shape assert.
FORMAT_VERSION = 3


def _launder(x):
    """Bit-exact copy through a jitted XLA program (see restore_server:
    a transfer-produced buffer entering the donated chain intermittently
    segfaults this image's XLA CPU; one extra pool copy at restore
    frequency is free). jnp.copy, NOT `a + 0`: addition maps -0.0 to
    +0.0, which would break the exact state round-trip this module
    promises. Lives on the DevicePort since ISSUE 14 (one compiled
    executable per pool shape, shared process-wide; the port holds the
    dispatch gate internally)."""
    from ..device import default_port
    return default_port().launder(x)


def rank_path(path: str, rank: int) -> str:
    return f"{path}.rank{rank}.npz"


def manifest_path(path: str) -> str:
    return f"{path}.manifest.npz"


def save_server(server, path: str) -> None:
    """Write the full manager state (single-controller: one .npz;
    multi-process: per-rank shards + manifest, globally quiesced)."""
    if server.fault is not None:
        # ISSUE 10 injection point (shared with the incremental chain):
        # fires before any I/O, so a failed save leaves the previous
        # checkpoint intact
        server.fault.fire("ckpt.save")
    if server.glob is not None:
        # quiesce so every delta is merged and every base is fresh
        server.wait_sync()
        server.barrier()
        server.wait_sync()
        server.barrier()
    server.block()
    with server._lock:
        arrs: Dict[str, np.ndarray] = {
            "format_version": np.int64(FORMAT_VERSION),
            "num_keys": np.int64(server.num_keys),
            "num_shards": np.int64(server.num_shards),
            "num_procs": np.int64(server.num_procs),
            "pid": np.int64(server.pid),
            "value_lengths": server.value_lengths,
            "owner": server.ab.owner,
            "slot": server.ab.slot,
            "cache_slot": server.ab.cache_slot,
            "relocation_counter": server.ab.relocation_counter,
            "intent_end": server.sync.intent_end,
            "clocks": server._clocks,
        }
        if server.glob is not None:
            arrs["owner_hint"] = server.glob.owner_hint
            arrs["reloc"] = server.glob.reloc
            arrs["interest"] = server.glob.interest
        for cid, st in enumerate(server.stores):
            # main_host() is the authoritative full-size main table
            # whether or not the store is tiered (cold store overlaid
            # with the hot pool), so checkpoints restore across tier
            # configurations — residency is transient state, not saved
            arrs[f"main_{cid}"] = st.main_host()
            arrs[f"cache_{cid}"] = np.asarray(st.cache)
            arrs[f"delta_{cid}"] = np.asarray(st.delta)
    if server.glob is None:
        np.savez_compressed(path, **arrs)
        return
    np.savez_compressed(rank_path(path, server.pid), **arrs)
    if server.pid == 0:
        np.savez_compressed(manifest_path(path),
                            format_version=np.int64(FORMAT_VERSION),
                            num_procs=np.int64(server.num_procs),
                            num_shards=np.int64(server.num_shards),
                            num_keys=np.int64(server.num_keys))
    server.barrier()  # checkpoint complete on every rank


def restore_server(server, path: str) -> None:
    """Restore state saved by save_server into a compatibly-constructed
    Server (same num_keys, value_lengths, shard count, pool geometry;
    multi-process: same process count — each rank reads its own shard)."""
    if server.fault is not None:
        # fires before any mutation: a failed restore leaves the live
        # server serving its current state (ISSUE 10)
        server.fault.fire("ckpt.restore")
    if server.glob is not None:
        mf = np.load(manifest_path(path))
        assert int(mf["num_procs"]) == server.num_procs, \
            "process count mismatch (elastic restore is not supported)"
        ck = np.load(rank_path(path, server.pid))
        assert int(ck["pid"]) == server.pid
    else:
        ck = np.load(path if os.path.exists(path) else rank_path(path, 0))
        assert int(ck["num_procs"]) == 1, (
            "this is one rank shard of a multi-process checkpoint; restore "
            "it under a launcher with the same process count")
    got = int(ck["format_version"])
    if got != FORMAT_VERSION:
        raise ValueError(
            f"checkpoint format v{got} is incompatible with this build "
            f"(expects v{FORMAT_VERSION}; v2->v3 changed pool geometry to "
            f"8-aligned slot counts) — re-export from the writing version")
    assert int(ck["num_keys"]) == server.num_keys, "key count mismatch"
    assert int(ck["num_shards"]) == server.num_shards, "shard mismatch"
    assert (ck["value_lengths"] == server.value_lengths).all(), \
        "value-length layout mismatch"
    # the whole addressbook is rewritten below (direct table writes, not
    # counted ab methods): run under the topology-mutation discipline so
    # the trailing version bump is the last mutation before the lock
    # releases, and keep the leading manual bump so any concurrently-
    # planned optimistic route (core/kv.py _plan_pull/_plan_push) fails
    # revalidation instead of dispatching pre-restore coordinates into
    # the restored pools
    with server._lock, server._topology_mutation():
        server.topology_version += 1
        ab = server.ab
        ab.owner[:] = ck["owner"]
        ab.slot[:] = ck["slot"]
        ab.cache_slot[:] = ck["cache_slot"]
        ab.relocation_counter[:] = ck["relocation_counter"]
        ab.replica_count[:] = (ab.cache_slot >= 0).sum(axis=0)
        server.sync.intent_end[:] = ck["intent_end"]
        server._clocks[:] = ck["clocks"]
        # Workers registered before the restore carry their own _clock and
        # write it back on advance_clock — re-seed them so the first advance
        # after a restore can't regress the restored clocks (intent windows
        # and replica expiry are computed from these).
        for wid, w in server._workers.items():
            w._clock = int(server._clocks[wid])

        # pools back onto the mesh with their original shardings
        for cid, st in enumerate(server.stores):
            sh = st.ctx.shard0()
            for name in ("main", "cache", "delta"):
                arr = ck[f"{name}_{cid}"]
                if name == "main":
                    # checkpoints carry the authoritative FULL main
                    # table (save_server main_host()); geometry is
                    # tier-independent
                    assert arr.shape == st.main_shape_full, (
                        f"pool main_{cid} geometry mismatch: checkpoint "
                        f"{arr.shape} vs server {st.main_shape_full}")
                    if st.res is not None:
                        # tiered restore: the table becomes the cold
                        # store and residency resets — everything cold,
                        # re-promoted lazily on access/intent (the
                        # device hot pool's stale rows are unmapped and
                        # never read)
                        from ..tier.coldpath import install_main_full
                        install_main_full(st, arr)
                        continue
                else:
                    cur = getattr(st, name)
                    assert arr.shape == cur.shape, (
                        f"pool {name}_{cid} geometry mismatch: "
                        f"checkpoint {arr.shape} vs server {cur.shape}")
                # install_pool routes the restored pool through an XLA
                # program before it re-enters the donated-buffer chain:
                # this image's XLA CPU intermittently SEGFAULTS when a
                # later donating program (e.g. the first post-restore
                # sync_replicas) consumes a buffer produced directly by
                # a host->device transfer (observed ~50% of
                # test_checkpoint sessions, also on pre-r6 code); an
                # XLA-produced buffer dodges it
                setattr(st, name, st.port.install_pool(arr, sh))

        # rebuild free lists from table occupancy
        for cid in range(len(server.stores)):
            class_keys = np.nonzero(ab.key_class == cid)[0]
            _rebuild_alloc(ab.main_alloc[cid],
                           ab.owner[class_keys], ab.slot[class_keys])
            used_by_shard = [
                ab.cache_slot[s, class_keys] for s in range(server.num_shards)]
            _rebuild_cache_alloc(ab.cache_alloc[cid], used_by_shard)

        # rebuild the sync manager's replica registry (one vectorized
        # channel-grouped insert, never per key), and reset the stores'
        # write-epoch tracking: the restored pools' replica bases may
        # predate their main rows, so everything starts dirty and the
        # first sync round re-ships every live replica once
        server.sync.replica_clear()
        shards, keys = np.nonzero(ab.cache_slot >= 0)
        server.sync.replica_add(keys.astype(np.int64),
                                shards.astype(np.int32))
        for st in server.stores:
            st.reset_write_tracking()
        if server.glob is not None:
            server.glob.owner_hint[:] = ck["owner_hint"]
            server.glob.reloc[:] = ck["reloc"]
            server.glob.interest[:] = ck["interest"]
    if server.prefetch is not None:
        # staged pull buffers predate the restore; the version bump
        # already invalidates them lazily — drop them now to release
        # their staging-pool rows promptly
        server.prefetch.invalidate_all()
    server.block()
    if server.glob is not None:
        server.barrier()  # all ranks restored before traffic resumes


def _rebuild_alloc(alloc, owners: np.ndarray, slots: np.ndarray) -> None:
    for s in range(alloc.num_shards):
        alloc.set_used(s, slots[owners == s])


def _rebuild_cache_alloc(alloc, used_by_shard) -> None:
    for s in range(alloc.num_shards):
        row = np.asarray(used_by_shard[s])
        alloc.set_used(s, row[row >= 0])
