"""Cumulative stopwatch (reference util::Stopwatch, include/utils.h:17-98):
start/stop accumulate elapsed time across multiple intervals; resume-able."""
from __future__ import annotations

import time


class Stopwatch:
    def __init__(self, start: bool = False):
        self._elapsed = 0.0
        self._t0 = None
        if start:
            self.start()

    def start(self) -> "Stopwatch":
        if self._t0 is None:
            self._t0 = time.perf_counter()
        return self

    def stop(self) -> "Stopwatch":
        if self._t0 is not None:
            self._elapsed += time.perf_counter() - self._t0
            self._t0 = None
        return self

    def resume(self) -> "Stopwatch":
        return self.start()

    def reset(self) -> "Stopwatch":
        self._elapsed = 0.0
        self._t0 = None
        return self

    @property
    def elapsed_s(self) -> float:
        running = (time.perf_counter() - self._t0) if self._t0 is not None \
            else 0.0
        return self._elapsed + running

    def __str__(self) -> str:
        return f"{self.elapsed_s:.3f}s"
