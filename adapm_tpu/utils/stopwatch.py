"""Cumulative stopwatch (reference util::Stopwatch, include/utils.h:17-98):
start/stop accumulate elapsed time across multiple intervals; resume-able.

Thread-safe for concurrent readers (ISSUE 2 satellite): the metrics
reporter thread snapshots `elapsed_s` while a worker thread is inside
start/stop (e.g. RuntimeGuard's watch). A single lock guards the
(_elapsed, _t0) pair so a reader can never observe a half-updated state
(interval counted twice or dropped)."""
from __future__ import annotations

import threading
import time


class Stopwatch:
    def __init__(self, start: bool = False):
        self._lock = threading.Lock()
        self._elapsed = 0.0
        self._t0 = None
        if start:
            self.start()

    def start(self) -> "Stopwatch":
        with self._lock:
            if self._t0 is None:
                self._t0 = time.perf_counter()
        return self

    def stop(self) -> "Stopwatch":
        with self._lock:
            if self._t0 is not None:
                self._elapsed += time.perf_counter() - self._t0
                self._t0 = None
        return self

    def resume(self) -> "Stopwatch":
        return self.start()

    def reset(self) -> "Stopwatch":
        with self._lock:
            self._elapsed = 0.0
            self._t0 = None
        return self

    @property
    def elapsed_s(self) -> float:
        with self._lock:
            running = (time.perf_counter() - self._t0) \
                if self._t0 is not None else 0.0
            return self._elapsed + running

    def __str__(self) -> str:
        return f"{self.elapsed_s:.3f}s"
