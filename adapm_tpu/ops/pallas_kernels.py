"""Pallas TPU kernels: alternative data-plane primitives.

Status (measured on TPU v5e, 2026-07; see docs/PERF.md): for the random
row-access patterns that dominate this framework (embedding gather /
scatter-add of ~2KB rows), XLA's native gather/scatter is the fastest
primitive available on this stack — a scalar-prefetch index-map Pallas
gather reaches ~0.7x of XLA's row rate, and manual-DMA kernels
(make_async_copy from HBM refs) are not supported by the deployment
compiler. The fused training step therefore rides XLA (ops/fused.py),
and these kernels are kept as (a) working, tested templates for future
kernel work, and (b) the fallback path should a target stack invert the
tradeoff.

The kernels use only the widely-supported Pallas subset: BlockSpec grid
pipelines + scalar prefetch (compiler-generated, double-buffered DMA), no
manual semaphores.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _copy_kernel(idx_ref, blk_ref, o_ref):
    o_ref[:] = blk_ref[:]


# apm-lint: disable=APM008 standalone Pallas TPU kernel (inherently
# backend-specific by definition): benchmarked in isolation, never
# dispatched by the PM planes — porting it IS writing a new backend
@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def gather_rows(pool: jnp.ndarray, block_idx: jnp.ndarray,
                block_rows: int = 8, interpret: bool = False) -> jnp.ndarray:
    """Gather `block_rows`-row blocks from a [slots, L] pool.

    block_idx[i] selects block i (rows block_idx[i]*block_rows ..+block_rows).
    The block index map is driven by the scalar-prefetched indices, so the
    pipeline overlaps each block's DMA with the previous block's copy-out —
    the canonical Pallas embedding-gather shape.
    """
    n = block_idx.shape[0]
    L = pool.shape[1]
    return pl.pallas_call(
        _copy_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n,),
            in_specs=[pl.BlockSpec((block_rows, L),
                                   lambda i, idx_ref: (idx_ref[i], 0),
                                   memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((block_rows, L),
                                   lambda i, idx_ref: (i, 0),
                                   memory_space=pltpu.VMEM)),
        out_shape=jax.ShapeDtypeStruct((n * block_rows, L), pool.dtype),
        interpret=interpret,
    )(block_idx, pool)


def _adagrad_kernel(g_ref, emb_ref, acc_ref, lr_ref, eps_ref,
                    new_emb_ref, new_acc_ref):
    g = g_ref[:]
    g2 = g * g
    acc = acc_ref[:] + g2
    new_acc_ref[:] = acc
    new_emb_ref[:] = emb_ref[:] - lr_ref[0] * g * jax.lax.rsqrt(
        acc + eps_ref[0])


# apm-lint: disable=APM008 standalone Pallas TPU kernel, same rationale
# as gather_rows above
@functools.partial(jax.jit, static_argnames=("block", "interpret"))
def adagrad_apply(grads: jnp.ndarray, emb: jnp.ndarray, acc: jnp.ndarray,
                  lr: float, eps: float = 1e-10, block: int = 256,
                  interpret: bool = False):
    """Blocked AdaGrad transform over gathered rows: emb' = emb - lr * g /
    sqrt(acc + g^2 + eps); acc' = acc + g^2 (the update rule every
    bundled app uses — reference apps/mf/update.h:23-79). One VMEM-blocked
    pass; XLA fuses the same chain automatically, kept as a template."""
    n, L = grads.shape
    grid = pl.cdiv(n, block)
    lr_arr = jnp.full((1,), lr, jnp.float32)
    eps_arr = jnp.full((1,), eps, jnp.float32)
    spec = pl.BlockSpec((block, L), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    sspec = pl.BlockSpec(memory_space=pltpu.SMEM)
    return pl.pallas_call(
        _adagrad_kernel,
        grid=(grid,),
        in_specs=[spec, spec, spec, sspec, sspec],
        out_specs=(spec, spec),
        out_shape=(jax.ShapeDtypeStruct((n, L), emb.dtype),
                   jax.ShapeDtypeStruct((n, L), acc.dtype)),
        interpret=interpret,
    )(grads, emb, acc, lr_arr, eps_arr)
