"""Fused embedding-update steps: the TPU-native hot path.

Every reference app's inner loop is the same triad: Pull a handful of rows,
run a small dense compute + AdaGrad, Push additive updates (mf/update.h:32-70,
word2vec.cc:718-743, kge.cc:415-530). Translating that per-key loop would
leave the MXU idle; instead the whole triad over a *batch* of data points is
ONE jitted program on the sharded pools:

    gather rows -> model loss -> grad -> AdaGrad transform -> scatter-add

Updates remain *additive deltas*, so the parameter-manager semantics
(concurrent pushes merge at the main copy; replica writes land in the delta
pool and flow back through sync rounds) are preserved exactly — the fused
step is a batched `Push` in PM terms, not a bypass.

Value-row layout follows the reference convention of carrying optimizer
state inside the value (`param_len = 2*rank = [factor | adagrad]`,
matrix_factorization.cc:695-697): row = [emb (D) | adagrad acc (D)].

Routing (which shard/slot serves each key) is resolved on the host from the
Addressbook — exactly what `Server._pull`/`_push` do — and handed to the
program as index arrays, so relocation/replication decisions made by the
planner between steps are transparently picked up.

Two routing modes:
  host routes (build_routes):  the host resolves every key and ships five
      index arrays per role. Simple, but at bench scale the host pays
      ~milliseconds per step in table lookups + host->device transfers
      while the device step takes microseconds.
  device routes (DeviceRouter): the Addressbook tables (owner, slot, the
      worker shard's cache-slot row) are mirrored into HBM, re-uploaded
      lazily when the planner changes placement (topology_version), and the
      jitted step resolves routes itself — per step the host ships only raw
      keys. This is the TPU-idiomatic shape: table lookups are trivial
      device gathers, and placement changes are rare relative to steps.

Negative sampling can also run on device (the `neg_role`/`neg_shape`
parameters of DeviceRoutedRunner / make_device_routed_step): drawing uniform
positions into a device mirror of the locally-resident key index is exactly
the Local sampling scheme (core/sampling.py) executed in-program,
eliminating the per-step sample key transfer too.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.store import OOB
from ..device import default_port
from ..exec import dispatch_gate

# sharded-dispatch serialization (adapm_tpu/exec, docs/EXECUTOR.md):
# the fused step is a sharded program like every store op — its
# dispatch funnels through the same process-wide gate so two servers
# on one device set can never interleave per-device enqueue orders
_GATE = dispatch_gate()


def _key_dtype(num_keys: int):
    """Key-upload dtype: int32 halves the transfer and is exact as long as
    every key fits; beyond 2^31 keys fall back to int64."""
    return np.int32 if num_keys <= 2**31 else np.int64


class Routes:
    """Device index arrays routing one role's key batch to pool rows.

    gather:  main[g_sh, g_sl] for owner-served keys, (cache+delta)[c_sh, c_sl]
             for replica-served keys (use_c mask).
    scatter: derived inside jit — owner path drops replica positions (OOB),
             delta path drops owner positions (mirrors Server._push).
    """

    __slots__ = ("g_sh", "g_sl", "c_sh", "c_sl", "use_c", "n_remote")

    def __init__(self, g_sh, g_sl, c_sh, c_sl, use_c, n_remote: int):
        self.g_sh, self.g_sl = g_sh, g_sl
        self.c_sh, self.c_sl = c_sh, c_sl
        self.use_c = use_c
        self.n_remote = n_remote

    def as_tuple(self):
        return (self.g_sh, self.g_sl, self.c_sh, self.c_sl, self.use_c)


def build_routes(server, keys: np.ndarray, shard: int,
                 expect_class: int = None) -> Routes:
    """Resolve keys (any shape) to pool coordinates for a worker on `shard`,
    via the one shared routing policy (Server._route: prefer a local replica,
    else the owner row). All keys must share a length class; pass
    `expect_class` to fail fast on a wrong role->class mapping (slots are
    per-class row indices, so a mismatch would corrupt another pool's rows).
    """
    keys = np.asarray(keys, dtype=np.int64)
    if expect_class is not None:
        kc = server.ab.key_class[keys]
        assert (kc == expect_class).all(), (
            f"keys span length classes {np.unique(kc)} but role is mapped "
            f"to class {expect_class}")
    # multi-process: a key owned by another process cannot be gathered by
    # the local program — make it local first (miss = fetch)
    server.ensure_local(keys, shard)
    o_sh, o_sl, c_sh, c_sl, use_c, n_remote, _ = server._route(keys, shard)
    g_sl = np.where(use_c, OOB, o_sl).astype(np.int32)
    if server.tier is not None:
        # tiered storage: the step program indexes the DEVICE hot pool,
        # so every owner-served key must be hot before dispatch. The
        # runners pin their whole batch as one union up front
        # (pin_step_keys), so the translation below normally finds
        # everything hot — the forced ensure only runs for rows still
        # cold (direct build_routes callers that skipped the union pin)
        cid = expect_class if expect_class is not None else \
            int(server.ab.key_class[keys.ravel()[0]])
        res = server.stores[cid].res
        slot_flat = g_sl.ravel()            # slots; OOB where replica-served
        o_flat = o_sh.ravel()
        m = slot_flat != OOB
        row = slot_flat.copy()
        row[m] = res.dev_row[o_flat[m], slot_flat[m]]
        if (row[m] < 0).any():
            server.tier.ensure_hot(cid, o_flat[m], slot_flat[m],
                                   pin_end=server.tier.step_pin_end(),
                                   force=True)
            row[m] = res.dev_row[o_flat[m], slot_flat[m]]
        g_sl = np.where(row < 0, OOB, row).reshape(
            g_sl.shape).astype(np.int32)
    put = server.ctx.put_replicated  # the staging rule, mesh.py
    return Routes(put(o_sh), put(g_sl), put(c_sh), put(c_sl), put(use_c),
                  n_remote)


def _mark_fused_writes(server, shard: int, role_class, role_keys,
                       skip_roles=()) -> None:
    """Dirty-delta write tracking for a fused step's host-known roles
    (caller holds the server lock): resolve each role's keys through the
    addressbook — the same tables the step's routes come from, so the
    marking is exact — and record the scatter in the stores' write
    epochs (ShardedStore.mark_routed_writes). `skip_roles`: frozen roles
    whose rows the step never updates."""
    ab = server.ab
    for r, keys in role_keys.items():
        if r in skip_roles:
            continue
        k = np.asarray(keys, dtype=np.int64).ravel()
        server.stores[role_class[r]].mark_routed_writes(
            shard, ab.cache_slot[shard, k], ab.owner[k], ab.slot[k])


def _read_rows(main, cache, delta, route):
    g_sh, g_sl, c_sh, c_sl, use_c = route
    m = main.at[g_sh, g_sl].get(mode="fill", fill_value=0)
    c = (cache.at[c_sh, c_sl].get(mode="fill", fill_value=0)
         + delta.at[c_sh, c_sl].get(mode="fill", fill_value=0))
    return jnp.where(use_c[..., None], c, m)


def _scatter_update(main, delta, route, upd):
    g_sh, g_sl, c_sh, c_sl, use_c = route
    # owner path: g_sl already carries OOB at replica positions
    main = main.at[g_sh, g_sl].add(upd, mode="drop")
    # replica path: c_sl already carries OOB at owner positions
    delta = delta.at[c_sh, c_sl].add(upd, mode="drop")
    return main, delta


def make_fused_adagrad_step(
        loss_fn: Callable[..., jnp.ndarray],
        role_class: Dict[str, int],
        role_dim: Dict[str, int],
        frozen_roles: Sequence[str] = ()):
    """Build the jitted fused step.

    loss_fn(embs: dict role -> [..., D_role] array, aux) -> scalar mean loss.
    role_class: role -> length-class id (index into the pools argument).
    role_dim:   role -> embedding dim D (row length must be 2*D: [emb|acc]).
    frozen_roles: gathered for the forward pass but never updated.

    Returns step(pools, routes, aux, lr, eps) -> (pools, loss) where
      pools  = tuple over classes of (main, cache, delta)   [donated]
      routes = dict role -> Routes.as_tuple()
      aux    = arbitrary pytree handed to loss_fn (labels, weights, rng keys)
    """
    roles = sorted(role_class)
    trainable = [r for r in roles if r not in frozen_roles]

    def step(pools, routes, aux, lr, eps):
        rows = {}
        for r in roles:
            main, cache, delta = pools[role_class[r]]
            rows[r] = _read_rows(main, cache, delta, routes[r])
        embs = {r: rows[r][..., : role_dim[r]] for r in roles}
        accs = {r: rows[r][..., role_dim[r]:] for r in roles}

        def objective(train_embs):
            merged = dict(embs)
            merged.update(train_embs)
            return loss_fn(merged, aux)

        loss, grads = jax.value_and_grad(objective)(
            {r: embs[r] for r in trainable})

        new_pools = list(pools)
        for r in trainable:
            g = grads[r]
            g2 = g * g
            # AdaGrad with the accumulator carried in the value row
            # (reference UpdateNsqlL2Adagrad, apps/mf/update.h:23-79)
            upd_emb = -lr * g * jax.lax.rsqrt(accs[r] + g2 + eps)
            upd = jnp.concatenate([upd_emb, g2], axis=-1)
            cid = role_class[r]
            main, cache, delta = new_pools[cid]
            main, delta = _scatter_update(main, delta, routes[r], upd)
            new_pools[cid] = (main, cache, delta)
        return tuple(new_pools), loss

    # program construction through the DevicePort (ISSUE 14): the body
    # is model math; the port owns how it becomes a device program
    return default_port().compile(step, donate_argnums=(0,))


class DeviceRouter:
    """Device mirrors of the Addressbook tables for one worker shard,
    refreshed lazily on placement changes (Server.topology_version)."""

    def __init__(self, server, shard: int):
        self.server = server
        self.shard = shard
        self._version = None   # (topology_version, residency epoch)
        self.owner = None      # [num_keys] int32
        self.slot = None       # [num_keys] int32
        self.cache_row = None  # [num_keys] int32 (this shard's replica slots)

    def refresh(self):
        srv = self.server
        ver = (srv.topology_version,
               srv.tier.epoch if srv.tier is not None else -1)
        if self._version == ver and self.owner is not None:
            return
        ab = srv.ab
        put = srv.ctx.put_replicated  # the staging rule, mesh.py
        self.owner = put(ab.owner)
        # tiered storage: the step indexes the DEVICE hot pool, so the
        # slot mirror carries hot-pool ROWS (composed against the
        # residency map, cached per epoch at the TierManager and shared
        # by all runners; OOB while cold — fill zeros / drop, never the
        # negative-index WRAP — and runners pin their batches hot so
        # the step never actually touches a cold row)
        self.slot = put(ab.slot if srv.tier is None
                        else srv.tier.compose_slot_table())
        self.cache_row = put(ab.cache_slot[self.shard])
        self._version = ver

    def tables(self):
        self.refresh()
        return self.owner, self.slot, self.cache_row


def _route_on_device(tables, keys, shard: int):
    """In-jit route resolution: the device-side twin of Server._route
    (and native adapm_route). keys int32/int64 device array."""
    owner, slot, cache_row = tables
    o_sh = owner[keys]
    cs = cache_row[keys]
    use_c = cs >= 0
    g_sl = jnp.where(use_c, OOB, slot[keys])
    c_sh = jnp.full_like(o_sh, shard)
    c_sl = jnp.where(use_c, cs, OOB)
    return (o_sh, g_sl, c_sh, c_sl, use_c)


def make_device_routed_step(loss_fn: Callable[..., jnp.ndarray],
                            role_class: Dict[str, int],
                            role_dim: Dict[str, int],
                            shard: int,
                            frozen_roles: Sequence[str] = (),
                            neg_role: str = None,
                            neg_shape: Tuple[int, ...] = None,
                            no_replicas: bool = False,
                            neg_alias: bool = False):
    """Fused step that resolves routing in-program from device table
    mirrors. Signature of the returned step:

        step(pools, tables, keys, local_index, rng_key, aux, lr, eps)
          pools       tuple per class of (main, cache, delta)  [donated]
          tables      (owner, slot, cache_row) device mirrors — key-indexed
                      global arrays, shared by all length classes
          keys        dict role -> device int array (raw PM keys)
          local_index [L] int32 device array of locally-resident keys for
                      on-device negative sampling (None disables)
          rng_key     jax PRNG key for the device-side sampler

    When `neg_role` is set and local_index is non-empty, that role's keys
    are DRAWN in-program: uniform positions into local_index — the Local
    sampling scheme (core/sampling.py LocalSampling) executed on device.

    `neg_alias=True` switches the draw to a NON-uniform app distribution:
    the step takes an extra `alias` argument (prob[V], alias[V], key[V]
    device arrays — a Vose table, models/sgns.py build_alias_table, e.g.
    unigram^0.75 for word2vec) and draws candidate keys from it, then
    SNAPS each to the nearest locally-resident key via a searchsorted
    probe — the device twin of LocalSampling._snap (binary search replaces
    the reference's linear probe, sampling.h:476-505).

    `no_replicas=True` compiles the replica-free specialization: reads touch
    only the main pool (1/3 of the gather traffic) and updates scatter only
    into main. Legal exactly while this shard holds zero replicas — the
    runner re-checks per step and switches variants (HBM bandwidth is the
    roofline for embedding workloads, so this is a large win whenever the
    planner hasn't replicated anything here).
    """
    body = _build_device_routed_body(
        loss_fn, role_class, role_dim, shard, frozen_roles, neg_role,
        neg_shape, no_replicas, neg_alias)
    # donate the pools only: donating the 4-scalar locstat accumulator
    # saves nothing and its aliased buffer has been observed returning
    # stale/garbage counts on the multi-device CPU backend (flaky
    # locality_counts mismatches in test_device_routed)
    return default_port().compile(body, donate_argnums=(0,))


def make_device_routed_scan(loss_fn: Callable[..., jnp.ndarray],
                            role_class: Dict[str, int],
                            role_dim: Dict[str, int],
                            shard: int,
                            frozen_roles: Sequence[str] = (),
                            neg_role: str = None,
                            neg_shape: Tuple[int, ...] = None,
                            no_replicas: bool = False,
                            neg_alias: bool = False,
                            has_aux: bool = True):
    """K training steps in ONE dispatch: `lax.scan` over stacked batches
    (VERDICT r3 item 2 — the per-step host dispatch is the residual over
    the HBM row-rate floor; amortizing it over a K-step window reclaims
    it). Placement is frozen for the window: the routing tables are read
    once, so the planner's moves land between scans — exactly the
    lookahead contract (intents are signaled a window ahead anyway).

    Signature: scan(pools, locstat, tables, keys[K,...], local_index,
    alias, rng_keys[K], aux[K,...]|None, lr, eps)
    -> (pools, locstat, losses[K])."""
    body = _build_device_routed_body(
        loss_fn, role_class, role_dim, shard, frozen_roles, neg_role,
        neg_shape, no_replicas, neg_alias)

    # pools-only donation, same rationale as make_device_routed_step
    def scan(pools, locstat, tables, keys, local_index, alias, rng_keys,
             aux, lr, eps):
        def f(carry, xs):
            pools, locstat = carry
            if has_aux:
                k, rkey, a = xs
            else:
                k, rkey = xs
                a = None
            pools, locstat, loss = body(
                pools, locstat, tables, k, local_index, alias, rkey, a,
                lr, eps)
            return (pools, locstat), loss

        xs = (keys, rng_keys, aux) if has_aux else (keys, rng_keys)
        (pools, locstat), losses = jax.lax.scan(f, (pools, locstat), xs)
        return pools, locstat, losses

    return default_port().compile(scan, donate_argnums=(0,))


def _build_device_routed_body(loss_fn, role_class, role_dim, shard,
                              frozen_roles, neg_role, neg_shape,
                              no_replicas, neg_alias):
    """The un-jitted single-step body shared by make_device_routed_step
    (one dispatch per step) and make_device_routed_scan (K steps per
    dispatch)."""
    roles = sorted(role_class)
    trainable = [r for r in roles if r not in frozen_roles]

    def step(pools, locstat, tables, keys, local_index, alias, rng_key,
             aux, lr, eps):
        keys = dict(keys)
        if neg_role is not None and neg_alias:
            prob, alias_t, key_table = alias
            k1, k2 = jax.random.split(rng_key)
            u = jax.random.randint(k1, neg_shape, 0, prob.shape[0])
            v = jax.random.uniform(k2, neg_shape)
            cand = key_table[jnp.where(v < prob[u], u, alias_t[u])]
            if local_index is not None:
                # Local-scheme snap: padded index is sorted with an
                # int-max sentinel tail, so searchsorted lands in
                # [0, count] and wraps (sampling.h:494)
                idx, count = local_index
                pos = jnp.searchsorted(idx, cand)
                pos = jnp.where(pos >= count, 0, pos)
                cand = idx[pos]
            keys[neg_role] = cand
        elif neg_role is not None and local_index is not None:
            idx, count = local_index  # padded index + valid count
            pos = jax.random.randint(rng_key, neg_shape, 0, count)
            keys[neg_role] = idx[pos]
        rows = {}
        routes = {}
        # device-side locality counters (reference coloc_kv_server.h:147-157
        # prints % accesses served locally; the host path records this in
        # Server._route, which this path never visits): a key access is
        # local when this worker's shard owns the row or holds a replica
        n_total = 0
        n_local = jnp.int32(0)
        for r in roles:
            cid = role_class[r]
            main, cache, delta = pools[cid]
            n_total += keys[r].size
            if no_replicas:
                owner, slot, _ = tables
                o_sh, o_sl = owner[keys[r]], slot[keys[r]]
                routes[r] = (o_sh, o_sl)
                rows[r] = main.at[o_sh, o_sl].get(mode="fill", fill_value=0)
                n_local += jnp.sum(o_sh == shard, dtype=jnp.int32)
                continue
            routes[r] = _route_on_device(tables, keys[r], shard)
            rows[r] = _read_rows(main, cache, delta, routes[r])
            o_sh, use_c = routes[r][0], routes[r][4]
            n_local += jnp.sum(use_c | (o_sh == shard), dtype=jnp.int32)
        # one step = one (batched) pull op + one push op of the same keys;
        # the op counts local iff every key it touched was local
        all_local = (n_local == n_total).astype(jnp.int32)
        locstat = locstat + jnp.stack(
            [jnp.int32(n_total), n_local, jnp.int32(1), all_local])
        embs = {r: rows[r][..., : role_dim[r]] for r in roles}
        accs = {r: rows[r][..., role_dim[r]:] for r in roles}

        def objective(train_embs):
            merged = dict(embs)
            merged.update(train_embs)
            return loss_fn(merged, aux)

        loss, grads = jax.value_and_grad(objective)(
            {r: embs[r] for r in trainable})

        new_pools = list(pools)
        for r in trainable:
            g = grads[r]
            g2 = g * g
            upd_emb = -lr * g * jax.lax.rsqrt(accs[r] + g2 + eps)
            upd = jnp.concatenate([upd_emb, g2], axis=-1)
            cid = role_class[r]
            main, cache, delta = new_pools[cid]
            if no_replicas:
                o_sh, o_sl = routes[r]
                main = main.at[o_sh, o_sl].add(upd, mode="drop")
            else:
                main, delta = _scatter_update(main, delta, routes[r], upd)
            new_pools[cid] = (main, cache, delta)
        return tuple(new_pools), locstat, loss

    return step


class StagedKeys:
    """A step's key batch pre-staged on device (DeviceRoutedRunner
    .prefetch_keys): the host->device upload happened at prepare/intent
    time instead of inside the dispatch critical section. Valid across
    topology changes — these are raw keys, not routes."""

    __slots__ = ("host", "dev")

    def __init__(self, host: Dict[str, np.ndarray], dev: Dict[str, object]):
        self.host = host
        self.dev = dev

    def matches(self, role_keys: Dict[str, np.ndarray]) -> bool:
        if set(self.host) != set(role_keys):
            return False
        return all(np.array_equal(self.host[r],
                                  np.asarray(k, dtype=self.host[r].dtype))
                   for r, k in role_keys.items())


class DeviceRoutedRunner:
    """FusedStepRunner's fast sibling: routing (and optionally negative
    sampling) happens on device. Per step the host ships only the raw key
    batch; table mirrors refresh lazily when the planner moves parameters.
    With the prefetch pipeline on (SystemOptions.prefetch), the mirrors
    are instead re-staged by the pipeline's background thread right after
    planner rounds, and `prefetch_keys` lets the app upload a future
    step's key batch ahead of its dispatch.

    Locality is recorded by a 4-scalar device accumulator folded into the
    step program (params seen / params local / steps / all-local steps) and
    drained to the host lazily — at `locality_counts()` (which
    Server.locality_summary calls) and often enough that the int32 counters
    cannot wrap. Per-KEY counters (--sys.stats.locality tsv dumps) still
    need host routing: routing never returns to the host here.
    """

    def __init__(self, server, loss_fn, role_class: Dict[str, int],
                 role_dim: Dict[str, int], shard: int = 0,
                 frozen_roles: Sequence[str] = (), neg_role: str = None,
                 neg_shape: Tuple[int, ...] = None,
                 neg_population=None, neg_alias=None, seed: int = 0):
        """`neg_alias=(prob, alias)` (models/sgns.py build_alias_table)
        switches on-device negative sampling to the app's non-uniform
        distribution over `neg_population` (position i of the population
        is drawn with prob ~ weight i), with a Local-scheme snap to
        locally-resident keys."""
        self.server = server
        self.shard = shard
        self.role_class = role_class
        self.frozen_roles = frozenset(frozen_roles)
        self.router = DeviceRouter(server, shard)
        self.neg_role = neg_role
        self._li_fallback = False  # set by _local_neg_index
        self._neg_shape = neg_shape
        self._rng = jax.random.PRNGKey(seed)
        self._alias = None
        if neg_alias is not None:
            assert neg_role is not None and neg_population is not None, \
                "neg_alias needs neg_role and neg_population"
            prob, alias = neg_alias
            key_table = np.asarray(neg_population,
                                   dtype=_key_dtype(server.num_keys))
            assert len(prob) == len(key_table), \
                "alias table must cover the population"
            put = server.ctx.put_replicated
            self._alias = (put(prob), put(alias), put(key_table))
        # population the device sampler may draw from (Local scheme: the
        # locally-resident slice of the allowed keys); None -> all keys
        self._neg_population = None if neg_population is None else \
            np.unique(np.asarray(neg_population, dtype=np.int64))
        if self._neg_population is not None and neg_role is not None:
            kc = server.ab.key_class[self._neg_population]
            assert (kc == role_class[neg_role]).all(), (
                "neg_population spans length classes "
                f"{np.unique(kc)} but role {neg_role} is class "
                f"{role_class[neg_role]}")
        self._local_index = None
        self._li_version = -1
        # per-step RNG keys come from a batched split (one tiny device
        # dispatch per 64 steps instead of per step — the relay's
        # per-dispatch cost makes per-step jax.random.split measurable,
        # ~0.75 ms/step) and device scalars are cached per value
        self._rng_pool: list = []
        self._scalars: Dict[float, jnp.ndarray] = {}
        # device locality accumulator [params, params_local, ops, ops_local]
        # (int32; drained before it can wrap — see _drain_locstat)
        self._locstat = server.ctx.put_replicated(np.zeros(4, np.int32))
        self._loc_host = np.zeros(4, dtype=np.int64)
        self._drain_every = None  # set on first step (needs params/step)
        server._locality_sources.append(self.locality_counts)
        # obs: drain cadence — how often the device accumulator is
        # folded to host (each drain is a device sync, so the count and
        # the computed interval belong in metrics_snapshot()['fused']).
        # `shared`: several runners per server feed the same counters.
        self._c_drains = server.obs.counter("fused.locstat_drains",
                                            shared=True)
        self._g_drain_every = server.obs.gauge(
            "fused.locstat_drain_every", unit="steps", shared=True)
        self._mk_kwargs = dict(
            loss_fn=loss_fn, role_class=role_class, role_dim=role_dim,
            shard=shard, frozen_roles=frozen_roles, neg_role=neg_role,
            neg_shape=neg_shape, neg_alias=self._alias is not None)
        mk = lambda nr: make_device_routed_step(  # noqa: E731
            no_replicas=nr, **self._mk_kwargs)
        self.step_fn = mk(False)
        # replica-free specialization: 1/3 the gather traffic; selected per
        # step while this shard holds no replicas
        self._step_fn_norep = mk(True)
        # K-step scan variants, built lazily per (no_replicas, has_aux)
        self._scan_fns: Dict[Tuple[bool, bool], Callable] = {}
        self._rep_version = -1
        self._has_replicas = True
        self.steps = 0
        if getattr(server, "prefetch", None) is not None:
            server.prefetch.register_refresher(self._prefetch_refresh)

    def _prefetch_refresh(self) -> None:
        """Called by the prefetch pipeline (under the server lock) after
        planner rounds: re-stage the device table mirrors, the local
        sampling index, and the replica-presence flag as soon as the
        topology settles, so the next dispatch finds them fresh instead
        of rebuilding + re-uploading them inside its critical section."""
        self.router.refresh()
        if self.neg_role is not None:
            self._local_neg_index()
        self._shard_has_replicas()

    def _note_step_writes(self, role_keys) -> None:
        """The fused step is a batched Push in PM terms: staged pull
        buffers covering trained keys must be invalidated like any other
        write (caller holds the server lock), and the stores' dirty-delta
        tracking must see the step's scatter (core/store.py) or the sync
        planner would skip shipping the trained replicas. Device-drawn
        negatives are not enumerable on the host, so runners with an
        in-program sampler conservatively invalidate every staged batch
        and mark the negative class's whole shard written."""
        srv = self.server
        _mark_fused_writes(srv, self.shard, self.role_class, role_keys,
                           skip_roles=self.frozen_roles)
        pre = srv.prefetch
        if pre is None or not pre._staged:
            return
        if self.neg_role is not None:
            pre.invalidate_all()
            return
        srv._prefetch_note(np.concatenate(
            [np.asarray(k, dtype=np.int64).ravel()
             for k in role_keys.values()]))

    def _mark_neg_writes(self) -> None:
        """Write tracking for device-drawn negatives (caller holds the
        server lock, AFTER _local_neg_index refreshed for this step):
        their rows are not enumerable on the host, so the negative
        class's whole shard counts as written — every shard when the
        local-index fallback is live, because a full-population draw
        scatters into other shards' main rows too."""
        if self.neg_role is None:
            return
        st = self.server.stores[self.role_class[self.neg_role]]
        if self._li_fallback:
            for s in range(self.server.num_shards):
                st.mark_shard_written(s)
        else:
            st.mark_shard_written(self.shard)

    def prefetch_keys(self, role_keys: Dict[str, np.ndarray]) -> StagedKeys:
        """Pre-stage a future step's key batch on device (the staging
        rule, docs/PERF.md): the upload runs now — on the app's
        intent/prepare path — instead of inside the next dispatch.
        Returns the handle for __call__'s `staged` parameter."""
        srv = self.server
        self._check_batch(role_keys)
        kdtype = _key_dtype(srv.num_keys)
        put = srv.ctx.put_replicated
        host = {r: np.asarray(k, dtype=kdtype)
                for r, k in role_keys.items()}
        return StagedKeys(host, {r: put(v) for r, v in host.items()})

    def _next_rng(self):
        if not self._rng_pool:
            self._rng, *pool = jax.random.split(self._rng, 65)
            self._rng_pool = pool
        return self._rng_pool.pop()

    def _scalar(self, v: float):
        out = self._scalars.get(v)
        if out is None:
            out = self._scalars[v] = jnp.float32(v)
            if len(self._scalars) > 64:  # lr schedules: bound the cache
                self._scalars = {v: out}
        return out

    def _ensure_drain_every(self, role_keys: Dict[str, np.ndarray]) -> None:
        """Size the locstat drain interval so the int32 params counter
        stays below 2^30 between drains (computed from the first batch's
        params-per-step; key shapes are fixed per runner)."""
        if self._drain_every is None:
            pps = sum(np.asarray(k).size for k in role_keys.values())
            if self._neg_shape is not None:
                pps += int(np.prod(self._neg_shape))
            self._drain_every = max(1, 2**30 // max(1, pps))
            self._g_drain_every.set(self._drain_every)

    def _drain_locstat(self) -> None:
        """Fold the device accumulator into the host int64 totals and reset
        it. A fetch syncs the device (~60 ms on a relay-attached backend),
        so this runs only at reporting time and every _drain_every steps —
        chosen so the int32 params counter stays below 2^30 between
        drains."""
        vals = np.asarray(self._locstat, dtype=np.int64)
        self._loc_host += vals
        self._locstat = self.server.ctx.put_replicated(
            np.zeros(4, np.int32))
        self._c_drains.inc()

    def locality_counts(self) -> Dict[str, int]:
        """Cumulative step-program access counts, host-side (the device-
        routed analog of Worker.stats; Server.locality_summary merges these
        as both pull and push — the fused step is one batched gather + one
        batched scatter of the same keys)."""
        with self.server._lock:
            self._drain_locstat()
            p, pl, o, ol = (int(v) for v in self._loc_host)
        return {"params": p, "params_local": pl, "ops": o, "ops_local": ol}

    def _shard_has_replicas(self) -> bool:
        srv = self.server
        if self._rep_version != srv.topology_version:
            self._has_replicas = bool(
                (srv.ab.cache_slot[self.shard] >= 0).any())
            self._rep_version = srv.topology_version
        return self._has_replicas

    def _local_neg_index(self):
        """(padded index [capacity], valid count) — padded to a power-of-two
        capacity so placement changes don't change the jit shape (only a
        capacity doubling recompiles). The index is sorted and the padding
        tail carries the dtype max so the alias path's searchsorted snap
        stays within the valid prefix."""
        srv = self.server
        li_ver = (srv.topology_version,
                  srv.tier.epoch if srv.tier is not None else -1)
        if self._li_version == li_ver and \
                self._local_index is not None:
            return self._local_index
        ab = srv.ab
        pop = self._neg_population if self._neg_population is not None \
            else np.arange(srv.num_keys, dtype=np.int64)
        from ..base import NO_SLOT
        from ..core.store import bucket_size
        local = (ab.owner[pop] == self.shard) | (
            ab.cache_slot[self.shard, pop] != NO_SLOT)
        if srv.tier is not None:
            # tiered storage: device-drawn negatives read/scatter main
            # rows in-program, which only works for DEVICE-RESIDENT
            # rows — restrict the draw population to hot-owned or
            # replicated keys (a residency change invalidates the index
            # via the epoch in li_ver). Sampling from the hot slice is
            # a valid negative draw; cold keys rejoin the population as
            # the promotion worker brings them up.
            cid = self.role_class[self.neg_role]
            res = srv.stores[cid].res
            o_sh, o_sl = ab.owner[pop], ab.slot[pop]
            owner_hot = np.zeros(len(pop), dtype=bool)
            m = (o_sh == self.shard) & (o_sl >= 0)
            if m.any():
                owner_hot[m] = res.dev_row[o_sh[m], o_sl[m]] >= 0
            local = owner_hot | (
                ab.cache_slot[self.shard, pop] != NO_SLOT)
        idx = pop[local]
        # fallback flag feeds _mark_neg_writes: full-population draws can
        # scatter into OTHER shards' main rows, so write tracking must
        # widen beyond this shard
        self._li_fallback = len(idx) == 0
        if len(idx) == 0:
            if srv.tier is not None:
                # tiered: the untiered fallback (draw from the FULL
                # population) would sample cold keys, whose mirror rows
                # are OOB — reads would silently return zeros and
                # scatters drop. Promote a bounded slice of the
                # population (wherever its rows are owned) and draw
                # from the device-resident subset; fail loudly if even
                # that cannot produce one resident key.
                cid = self.role_class[self.neg_role]
                res = srv.stores[cid].res
                take = pop[: 4096]
                srv.tier.ensure_hot(cid, ab.owner[take], ab.slot[take])
                o_sh, o_sl = ab.owner[pop], ab.slot[pop]
                ok = o_sl >= 0
                resident = np.zeros(len(pop), dtype=bool)
                resident[ok] = res.dev_row[o_sh[ok], o_sl[ok]] >= 0
                idx = pop[resident]
                if len(idx) == 0:
                    raise RuntimeError(
                        "tiered negative sampling: no device-resident "
                        "key in the population and promotion could not "
                        "produce one (hot pool full of pinned rows?) — "
                        "raise --sys.tier.hot_rows or signal intent on "
                        "the sampling population")
            else:
                idx = pop  # nothing local: draw from the full population
        cap = bucket_size(len(idx), minimum=64)
        kdt = _key_dtype(srv.num_keys)
        padded = np.full(cap, np.iinfo(kdt).max, dtype=kdt)
        padded[: len(idx)] = idx
        self._local_index = (srv.ctx.put_replicated(padded),
                             jnp.int32(len(idx)))
        self._li_version = li_ver
        return self._local_index

    def _check_batch(self, role_keys: Dict[str, np.ndarray]) -> None:
        srv = self.server
        if self.neg_role is not None and self.neg_role in role_keys:
            raise ValueError(
                f"role {self.neg_role!r} is sampled on device; caller-"
                "supplied keys for it would be silently discarded — drop "
                "them or build the runner without neg_role")
        from ..base import check_key_range
        for r, k in role_keys.items():
            k64 = np.asarray(k, dtype=np.int64)
            # on device, XLA clamps bad indices instead of raising — reject
            # out-of-range keys here, then fail fast on a wrong role->class
            # mapping (per-class slot indices gathered for the wrong pool
            # would corrupt rows; same check as build_routes)
            check_key_range(k64, srv.num_keys, f"role {r} key")
            kc = srv.ab.key_class[k64]
            assert (kc == self.role_class[r]).all(), (
                f"role {r}: keys span length classes {np.unique(kc)} but "
                f"role is mapped to class {self.role_class[r]}")
            # multi-process: device tables carry owner=-1 for keys owned by
            # another process — fetch them before routing on device
            srv.ensure_local(k64, self.shard)

    def __call__(self, role_keys: Dict[str, np.ndarray], aux, lr: float,
                 eps: float = 1e-10,
                 staged: Optional[StagedKeys] = None) -> jnp.ndarray:
        srv = self.server
        self._check_batch(role_keys)
        if staged is not None and not staged.matches(role_keys):
            raise ValueError(
                "staged keys differ from the step's batch — pass the "
                "handle prefetch_keys returned for THIS batch")
        with srv._lock:
            if srv.tier is not None:
                # tiered storage: the step reads main rows through the
                # hot pool — promote + pin the batch before the route
                # mirror is composed (ensure_hot bumps the residency
                # epoch, which router.tables() below picks up)
                srv.tier.pin_step_keys(self.role_class, role_keys)
            self._note_step_writes(role_keys)
            tables = self.router.tables()
            local_index = self._local_neg_index() \
                if self.neg_role is not None else None
            self._mark_neg_writes()
            sub = self._next_rng()
            # keys validated above to be inside [0, num_keys)
            kdtype = _key_dtype(srv.num_keys)
            put = srv.ctx.put_replicated  # the staging rule, mesh.py
            keys = staged.dev if staged is not None else \
                {r: put(np.asarray(k, dtype=kdtype))
                 for r, k in role_keys.items()}
            pools = tuple((s.main, s.cache, s.delta) for s in srv.stores)
            fn = self.step_fn if self._shard_has_replicas() \
                else self._step_fn_norep
            # dispatch under the gate, tracked on the "main" stream for
            # the executor's overlap accounting (enqueue-only: the jit
            # call returns as soon as the program is queued)
            with srv.exec.track("main"), _GATE:
                pools, self._locstat, loss = fn(
                    pools, self._locstat, tables, keys, local_index,
                    self._alias, sub, aux, self._scalar(lr),
                    self._scalar(eps))
                for st, (m, c, d) in zip(srv.stores, pools):
                    st.main, st.cache, st.delta = m, c, d
            self.steps += 1
            self._ensure_drain_every(role_keys)
            if self.steps % self._drain_every == 0:
                self._drain_locstat()
        return loss

    def _scan_fn(self, no_replicas: bool, has_aux: bool):
        key = (no_replicas, has_aux)
        fn = self._scan_fns.get(key)
        if fn is None:
            fn = self._scan_fns[key] = make_device_routed_scan(
                no_replicas=no_replicas, has_aux=has_aux,
                **self._mk_kwargs)
        return fn

    def run_scan(self, batches: Sequence[Dict[str, np.ndarray]], auxes,
                 lr: float, eps: float = 1e-10) -> np.ndarray:
        """Train K steps in ONE device dispatch (lax.scan over the stacked
        batches; make_device_routed_scan). Returns the [K] per-step losses
        (device array). All batches must share roles and shapes (one
        compiled variant per K). Placement freezes for the window — the
        planner's changes apply between scans, matching the apps'
        lookahead contract. `auxes` is a list of per-step aux pytrees, or
        None when the loss takes no aux."""
        srv = self.server
        K = len(batches)
        assert K >= 1, "empty scan window"
        for b in batches:
            self._check_batch(b)
        has_aux = auxes is not None
        if has_aux:
            assert len(auxes) == K, "one aux per batch"
        with srv._lock:
            if srv.tier is not None:
                # placement AND residency freeze for the scan window:
                # the route mirror is read ONCE for all K batches, so
                # the whole window's rows must be hot simultaneously —
                # pin the UNION (per-batch pinning would let a later
                # batch's forced eviction victimize an earlier one)
                union = {r: np.concatenate(
                    [np.asarray(b[r], dtype=np.int64).ravel()
                     for b in batches]) for r in batches[0]}
                srv.tier.pin_step_keys(self.role_class, union)
            for b in batches:
                self._note_step_writes(b)
            tables = self.router.tables()
            local_index = self._local_neg_index() \
                if self.neg_role is not None else None
            self._mark_neg_writes()
            # draw through _next_rng so the key sequence is IDENTICAL to K
            # sequential __call__ steps (refills included) — the scan-vs-
            # sequential equivalence depends on it when negatives are
            # drawn in-program
            rngs = jnp.stack([self._next_rng() for _ in range(K)])
            kdtype = _key_dtype(srv.num_keys)
            put = srv.ctx.put_replicated  # the staging rule, mesh.py
            keys = {r: put(np.stack([np.asarray(b[r], dtype=kdtype)
                                     for b in batches]))
                    for r in batches[0]}
            aux = None
            if has_aux:
                import jax.tree_util as jtu
                aux = jtu.tree_map(
                    lambda *xs: put(np.stack([np.asarray(x) for x in xs])),
                    *auxes)
            pools = tuple((s.main, s.cache, s.delta) for s in srv.stores)
            fn = self._scan_fn(no_replicas=not self._shard_has_replicas(),
                               has_aux=has_aux)
            with srv.exec.track("main"), _GATE:
                pools, self._locstat, losses = fn(
                    pools, self._locstat, tables, keys, local_index,
                    self._alias, rngs, aux, self._scalar(lr),
                    self._scalar(eps))
                for st, (m, c, d) in zip(srv.stores, pools):
                    st.main, st.cache, st.delta = m, c, d
            self.steps += K
            self._ensure_drain_every(batches[0])
            if self.steps // self._drain_every != \
                    (self.steps - K) // self._drain_every:
                self._drain_locstat()
        return losses


class FusedStepRunner:
    """Binds a fused step to a Server: swaps pools in/out of the ShardedStores
    so the PM view (Pull/Push/sync rounds) and the fused hot loop always see
    the same buffers."""

    def __init__(self, server, loss_fn, role_class: Dict[str, int],
                 role_dim: Dict[str, int], frozen_roles: Sequence[str] = ()):
        self.server = server
        self.role_class = role_class
        self.frozen_roles = frozenset(frozen_roles)
        self.step_fn = make_fused_adagrad_step(
            loss_fn, role_class, role_dim, frozen_roles)
        self.n_remote = 0
        self.steps = 0

    def routes_for(self, role_keys: Dict[str, np.ndarray],
                   shard: int) -> Dict[str, tuple]:
        out = {}
        for r, keys in role_keys.items():
            rt = build_routes(self.server, keys, shard,
                              expect_class=self.role_class[r])
            self.n_remote += rt.n_remote
            out[r] = rt.as_tuple()
        return out

    def __call__(self, role_keys: Dict[str, np.ndarray], aux, lr: float,
                 eps: float = 1e-10, shard: int = 0) -> jnp.ndarray:
        srv = self.server
        with srv._lock:
            # a fused step is a batched Push: invalidate staged pull
            # buffers of the trained keys (all roles are host-provided
            # here, so the written key set is exact)
            if srv.prefetch is not None and srv.prefetch._staged:
                srv._prefetch_note(np.concatenate(
                    [np.asarray(k, dtype=np.int64).ravel()
                     for k in role_keys.values()]))
            if srv.tier is not None:
                # pin the whole batch's rows hot as ONE union before any
                # role's routes are translated: build_routes resolves
                # slot->hot-row per role, and a later role's forced
                # eviction must never invalidate an earlier role's
                # already-translated rows. Localize process-remote keys
                # FIRST — pin_step_keys skips slot<0 entries, so a key
                # localized later (inside build_routes) would fall
                # outside the union's eviction protection
                for r, k in role_keys.items():
                    srv.ensure_local(np.asarray(k, dtype=np.int64)
                                     .ravel(), shard)
                srv.tier.pin_step_keys(self.role_class, role_keys)
            routes = self.routes_for(role_keys, shard)
            # mark the stores' dirty-delta tracking AFTER routes_for:
            # its ensure_local may localize keys, and the marking must
            # see the placement the step scatters into
            _mark_fused_writes(srv, shard, self.role_class, role_keys,
                               skip_roles=self.frozen_roles)
            pools = tuple((s.main, s.cache, s.delta) for s in srv.stores)
            with srv.exec.track("main"), _GATE:
                pools, loss = self.step_fn(
                    pools, routes, aux, jnp.float32(lr), jnp.float32(eps))
                for st, (m, c, d) in zip(srv.stores, pools):
                    st.main, st.cache, st.delta = m, c, d
        self.steps += 1
        return loss
