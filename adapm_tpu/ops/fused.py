"""Fused embedding-update steps: the TPU-native hot path.

Every reference app's inner loop is the same triad: Pull a handful of rows,
run a small dense compute + AdaGrad, Push additive updates (mf/update.h:32-70,
word2vec.cc:718-743, kge.cc:415-530). Translating that per-key loop would
leave the MXU idle; instead the whole triad over a *batch* of data points is
ONE jitted program on the sharded pools:

    gather rows -> model loss -> grad -> AdaGrad transform -> scatter-add

Updates remain *additive deltas*, so the parameter-manager semantics
(concurrent pushes merge at the main copy; replica writes land in the delta
pool and flow back through sync rounds) are preserved exactly — the fused
step is a batched `Push` in PM terms, not a bypass.

Value-row layout follows the reference convention of carrying optimizer
state inside the value (`param_len = 2*rank = [factor | adagrad]`,
matrix_factorization.cc:695-697): row = [emb (D) | adagrad acc (D)].

Routing (which shard/slot serves each key) is resolved on the host from the
Addressbook — exactly what `Server._pull`/`_push` do — and handed to the
program as index arrays, so relocation/replication decisions made by the
planner between steps are transparently picked up.
"""
from __future__ import annotations

from functools import partial
from typing import Callable, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.store import OOB


class Routes:
    """Device index arrays routing one role's key batch to pool rows.

    gather:  main[g_sh, g_sl] for owner-served keys, (cache+delta)[c_sh, c_sl]
             for replica-served keys (use_c mask).
    scatter: derived inside jit — owner path drops replica positions (OOB),
             delta path drops owner positions (mirrors Server._push).
    """

    __slots__ = ("g_sh", "g_sl", "c_sh", "c_sl", "use_c", "n_remote")

    def __init__(self, g_sh, g_sl, c_sh, c_sl, use_c, n_remote: int):
        self.g_sh, self.g_sl = g_sh, g_sl
        self.c_sh, self.c_sl = c_sh, c_sl
        self.use_c = use_c
        self.n_remote = n_remote

    def as_tuple(self):
        return (self.g_sh, self.g_sl, self.c_sh, self.c_sl, self.use_c)


def build_routes(server, keys: np.ndarray, shard: int,
                 expect_class: int = None) -> Routes:
    """Resolve keys (any shape) to pool coordinates for a worker on `shard`,
    via the one shared routing policy (Server._route: prefer a local replica,
    else the owner row). All keys must share a length class; pass
    `expect_class` to fail fast on a wrong role->class mapping (slots are
    per-class row indices, so a mismatch would corrupt another pool's rows).
    """
    keys = np.asarray(keys, dtype=np.int64)
    if expect_class is not None:
        kc = server.ab.key_class[keys]
        assert (kc == expect_class).all(), (
            f"keys span length classes {np.unique(kc)} but role is mapped "
            f"to class {expect_class}")
    o_sh, o_sl, c_sh, c_sl, use_c, n_remote = server._route(keys, shard)
    g_sl = np.where(use_c, OOB, o_sl).astype(np.int32)
    return Routes(jnp.asarray(o_sh), jnp.asarray(g_sl), jnp.asarray(c_sh),
                  jnp.asarray(c_sl), jnp.asarray(use_c), n_remote)


def _read_rows(main, cache, delta, route):
    g_sh, g_sl, c_sh, c_sl, use_c = route
    m = main.at[g_sh, g_sl].get(mode="fill", fill_value=0)
    c = (cache.at[c_sh, c_sl].get(mode="fill", fill_value=0)
         + delta.at[c_sh, c_sl].get(mode="fill", fill_value=0))
    return jnp.where(use_c[..., None], c, m)


def _scatter_update(main, delta, route, upd):
    g_sh, g_sl, c_sh, c_sl, use_c = route
    # owner path: g_sl already carries OOB at replica positions
    main = main.at[g_sh, g_sl].add(upd, mode="drop")
    # replica path: c_sl already carries OOB at owner positions
    delta = delta.at[c_sh, c_sl].add(upd, mode="drop")
    return main, delta


def make_fused_adagrad_step(
        loss_fn: Callable[..., jnp.ndarray],
        role_class: Dict[str, int],
        role_dim: Dict[str, int],
        frozen_roles: Sequence[str] = ()):
    """Build the jitted fused step.

    loss_fn(embs: dict role -> [..., D_role] array, aux) -> scalar mean loss.
    role_class: role -> length-class id (index into the pools argument).
    role_dim:   role -> embedding dim D (row length must be 2*D: [emb|acc]).
    frozen_roles: gathered for the forward pass but never updated.

    Returns step(pools, routes, aux, lr, eps) -> (pools, loss) where
      pools  = tuple over classes of (main, cache, delta)   [donated]
      routes = dict role -> Routes.as_tuple()
      aux    = arbitrary pytree handed to loss_fn (labels, weights, rng keys)
    """
    roles = sorted(role_class)
    trainable = [r for r in roles if r not in frozen_roles]

    @partial(jax.jit, donate_argnums=(0,))
    def step(pools, routes, aux, lr, eps):
        rows = {}
        for r in roles:
            main, cache, delta = pools[role_class[r]]
            rows[r] = _read_rows(main, cache, delta, routes[r])
        embs = {r: rows[r][..., : role_dim[r]] for r in roles}
        accs = {r: rows[r][..., role_dim[r]:] for r in roles}

        def objective(train_embs):
            merged = dict(embs)
            merged.update(train_embs)
            return loss_fn(merged, aux)

        loss, grads = jax.value_and_grad(objective)(
            {r: embs[r] for r in trainable})

        new_pools = list(pools)
        for r in trainable:
            g = grads[r]
            g2 = g * g
            # AdaGrad with the accumulator carried in the value row
            # (reference UpdateNsqlL2Adagrad, apps/mf/update.h:23-79)
            upd_emb = -lr * g * jax.lax.rsqrt(accs[r] + g2 + eps)
            upd = jnp.concatenate([upd_emb, g2], axis=-1)
            cid = role_class[r]
            main, cache, delta = new_pools[cid]
            main, delta = _scatter_update(main, delta, routes[r], upd)
            new_pools[cid] = (main, cache, delta)
        return tuple(new_pools), loss

    return step


class FusedStepRunner:
    """Binds a fused step to a Server: swaps pools in/out of the ShardedStores
    so the PM view (Pull/Push/sync rounds) and the fused hot loop always see
    the same buffers."""

    def __init__(self, server, loss_fn, role_class: Dict[str, int],
                 role_dim: Dict[str, int], frozen_roles: Sequence[str] = ()):
        self.server = server
        self.role_class = role_class
        self.step_fn = make_fused_adagrad_step(
            loss_fn, role_class, role_dim, frozen_roles)
        self.n_remote = 0
        self.steps = 0

    def routes_for(self, role_keys: Dict[str, np.ndarray],
                   shard: int) -> Dict[str, tuple]:
        out = {}
        for r, keys in role_keys.items():
            rt = build_routes(self.server, keys, shard,
                              expect_class=self.role_class[r])
            self.n_remote += rt.n_remote
            out[r] = rt.as_tuple()
        return out

    def __call__(self, role_keys: Dict[str, np.ndarray], aux, lr: float,
                 eps: float = 1e-10, shard: int = 0) -> jnp.ndarray:
        srv = self.server
        with srv._lock:
            routes = self.routes_for(role_keys, shard)
            pools = tuple((s.main, s.cache, s.delta) for s in srv.stores)
            pools, loss = self.step_fn(
                pools, routes, aux, jnp.float32(lr), jnp.float32(eps))
            for st, (m, c, d) in zip(srv.stores, pools):
                st.main, st.cache, st.delta = m, c, d
        self.steps += 1
        return loss
