"""Dequant-fused device programs for the quantized cold tier
(ISSUE 8; tier/quant.py holds the host twins of these transforms).

The co-design point (Tensor Casting, PAPERS.md): the cold store's wire
format is chosen so the ACCELERATOR inverts it inside the very gather /
scatter that consumes the rows — the host ships fp16/int8 payloads
(half / quarter the bytes of f32) and the dequant fuses into the
program instead of paying a separate host-side pass plus a full-width
upload:

  - `_gather_cold_fp16` / `_gather_cold_int8`: the cold-miss gather —
    `store._gather` with the cold override rows arriving in wire
    format, converted in-program (f16->f32 convert is exact; int8
    rows multiply by their per-row f32 scale);
  - `_write_main_rows_fp16` / `_write_main_rows_int8`: the promotion
    upload — dequantize into the donated hot-pool scatter
    (tier/promote.py double-buffers these on the `tier`/`tier_commit`
    streams, so host wire prep of chunk N+1 overlaps chunk N's device
    scatter).

Exactness contract: these programs and the numpy paths in
tier/quant.py apply the SAME IEEE f32 operations (convert, multiply),
so a cold row reads identical bits through the fused device gather,
the host bulk-read path, and a checkpoint.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@jax.jit
def _gather_cold_fp16(main, cache, delta, o_shard, o_row, c_shard,
                      c_slot, use_cache, cold_q, use_cold):
    """store._gather with an fp16 wire override for cold owner rows
    (cold_q: [b, L] f16). The f16->f32 convert is exact — fp16 cold
    rows read the same bits everywhere."""
    m = main.at[o_shard, o_row].get(mode="fill", fill_value=0)
    m = jnp.where(use_cold[:, None], cold_q.astype(main.dtype), m)
    c = (cache.at[c_shard, c_slot].get(mode="fill", fill_value=0)
         + delta.at[c_shard, c_slot].get(mode="fill", fill_value=0))
    return jnp.where(use_cache[:, None], c, m)


@jax.jit
def _gather_cold_int8(main, cache, delta, o_shard, o_row, c_shard,
                      c_slot, use_cache, cold_q, cold_scale, use_cold):
    """store._gather with an int8+per-row-scale wire override for cold
    owner rows (cold_q: [b, L] i8, cold_scale: [b] f32)."""
    m = main.at[o_shard, o_row].get(mode="fill", fill_value=0)
    deq = cold_q.astype(main.dtype) * cold_scale[:, None]
    m = jnp.where(use_cold[:, None], deq, m)
    c = (cache.at[c_shard, c_slot].get(mode="fill", fill_value=0)
         + delta.at[c_shard, c_slot].get(mode="fill", fill_value=0))
    return jnp.where(use_cache[:, None], c, m)


@partial(jax.jit, donate_argnums=(0,))
def _write_main_rows_fp16(main, sh, row, qvals):
    """Promotion upload, fp16 wire: dequantize fused into the donated
    hot-pool scatter (padding rows carry OOB and drop)."""
    return main.at[sh, row].set(qvals.astype(main.dtype), mode="drop")


@partial(jax.jit, donate_argnums=(0,))
def _write_main_rows_int8(main, sh, row, qvals, scales):
    """Promotion upload, int8 wire (scales: [b] f32 per-row)."""
    vals = qvals.astype(main.dtype) * scales[:, None]
    return main.at[sh, row].set(vals, mode="drop")
