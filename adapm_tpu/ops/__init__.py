"""Hot-path device programs: fused gather->grad->AdaGrad->scatter steps."""
from .fused import (FusedStepRunner, Routes, build_routes,  # noqa
                    make_fused_adagrad_step)
