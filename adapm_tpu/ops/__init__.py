"""Hot-path device programs: fused gather->grad->AdaGrad->scatter steps."""
from .fused import (DeviceRoutedRunner, DeviceRouter,  # noqa
                    FusedStepRunner, Routes, StagedKeys, build_routes,
                    make_device_routed_scan, make_device_routed_step,
                    make_fused_adagrad_step)
