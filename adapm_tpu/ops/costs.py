"""Measured kernel cost table (ISSUE 16 tentpole b).

The repo's dispatch-path choices — fused gather+pool vs gather-then-
host-pool for bag reads (serve/bags.py), and how many step batches an
episodic prep window should cover (device/episode.py) — are *measured*
questions: the answer depends on the backend, the row width, the batch
size, and the dtype, and hard-coding one preference bakes in whatever
machine the code was written on. This module measures each variant on
the live store and persists the result as a small versioned JSON
table:

    {"version": 1, "backend": "...", "entries": {
        "<variant>|<L>|<bucket>|<dtype>|<pooling>": <median µs>, ...}}

Variants probed by `calibrate_store`:

  - `gather`         — the flat row gather (readback included); the
                       per-class unit the episodic planner sizes prep
                       windows from;
  - `gather_pool`    — the fused gather+segment-pool program (pooled
                       readback only);
  - `gather_hostpool`— flat gather + `pool_bags_host` on the host (the
                       same bits, reduction on the wrong side of the
                       boundary);
  - `cold_wire_<m>`  — the tiered cold path through the quantized wire
                       (only on tiered stores with a non-fp32 cold
                       dtype);
  - `pallas_gather`  — ops/pallas_kernels.gather_rows, where the stack
                       supports it (TPU; skipped silently elsewhere).

Dispatch-time consult: `prefer_fused(L, n, dtype, pooling)` compares
the measured fused vs host-pool entries at the nearest calibrated
bucket — `None` (no data) leaves the caller's default choice alone, so
a missing or stale table can never change behavior, only a measured
one can. The choice moves WHERE the pooling runs, never what it
returns (the bit-identity contract, serve/bags.py).

Keyed by the PADDED bucket size (`core.store.bucket_size`), the same
shape key under which XLA caches the compiled program — costs are a
property of the compiled shape, not the raw batch length.
"""
from __future__ import annotations

import json
import os
import time
from typing import Dict, Iterable, List, Optional

import numpy as np

COSTS_SCHEMA_VERSION = 1

# prep-window budget for `suggest_episode_batches`: one episode's host
# prep should stage about this much measured gather work — windows
# scale down on slow/wide classes and up on fast/narrow ones
_PREP_BUDGET_US = 4000.0


def _median_us(samples: List[float]) -> float:
    samples = sorted(samples)
    return samples[len(samples) // 2] * 1e6


class KernelCostTable:
    """Measured per-(variant, L, bucket, dtype, pooling) dispatch costs
    in microseconds. Plain counters by default; `bind_metrics` swaps in
    registry-backed ones (the serve/replica registration discipline)."""

    def __init__(self, backend: str = "unknown"):
        self.backend = backend
        self._us: Dict[str, float] = {}
        from ..obs.metrics import Counter
        self.c_consults = Counter("device.costs_consults_total")
        self.c_overrides = Counter("device.costs_overrides_total")
        self.c_calibrations = Counter("device.costs_calibrations_total")

    def bind_metrics(self, reg) -> None:
        """Re-home the counters (and an entry-count gauge) in a metrics
        registry — `device.costs_*`, schema v12. Counts accumulated
        before the bind (a calibration pass runs first) carry over."""
        if reg is None or not reg.enabled:
            return
        self._rebind("c_consults",
                     reg.counter("device.costs_consults_total",
                                 shared=True))
        self._rebind("c_overrides",
                     reg.counter("device.costs_overrides_total",
                                 shared=True))
        self._rebind("c_calibrations",
                     reg.counter("device.costs_calibrations_total",
                                 shared=True))
        reg.gauge("device.costs_entries", shared=True,
                  fn=lambda: float(len(self._us)))

    def _rebind(self, attr: str, c) -> None:
        pre = int(getattr(self, attr).value)
        if pre:
            c.inc(pre)
        setattr(self, attr, c)

    # -- entries -------------------------------------------------------------

    @staticmethod
    def _key(variant: str, L: int, bucket: int, dtype: str,
             pooling: str) -> str:
        return f"{variant}|{int(L)}|{int(bucket)}|{dtype}|{pooling}"

    def record(self, variant: str, L: int, bucket: int, dtype: str,
               pooling: str, cost_us: float) -> None:
        self._us[self._key(variant, L, bucket, dtype,
                           pooling)] = float(cost_us)

    def cost_us(self, variant: str, L: int, bucket: int, dtype: str,
                pooling: str) -> Optional[float]:
        return self._us.get(self._key(variant, L, bucket, dtype,
                                      pooling))

    def __len__(self) -> int:
        return len(self._us)

    def entries(self) -> Dict[str, float]:
        """Copy of the measured entries (key -> median microseconds),
        sorted by key — the bench artifact's cost-table snapshot."""
        return dict(sorted(self._us.items()))

    def _nearest_bucket(self, variant: str, L: int, n: int, dtype: str,
                        pooling: str) -> Optional[int]:
        """The calibrated bucket closest (log-scale) to batch size `n`
        for this (variant, L, dtype, pooling) — costs are per compiled
        shape, so consult the nearest measured shape."""
        cands = []
        for k in self._us:
            v, kl, kb, kd, kp = k.split("|")
            if (v == variant and int(kl) == int(L) and kd == dtype
                    and kp == pooling):
                cands.append(int(kb))
        if not cands:
            return None
        n = max(1, int(n))
        return min(cands, key=lambda b: abs(np.log2(b) - np.log2(n)))

    # -- dispatch-time consult (serve/batcher.py) ----------------------------

    def prefer_fused(self, L: int, n: int, dtype: str,
                     pooling: str) -> Optional[bool]:
        """Measured verdict for a bag dispatch of `n` member rows of
        width `L`: True = the fused gather+pool is cheaper, False = the
        flat gather + host pool is, None = no measurement for this
        shape (caller keeps its default). Counts every consult; the
        caller counts overrides."""
        self.c_consults.inc()
        b = self._nearest_bucket("gather_pool", L, n, dtype, pooling)
        if b is None:
            return None
        fused = self.cost_us("gather_pool", L, b, dtype, pooling)
        host = self.cost_us("gather_hostpool", L, b, dtype, pooling)
        if fused is None or host is None:
            return None
        return fused <= host

    # -- episodic prep sizing (device/episode.py) ----------------------------

    def suggest_episode_batches(self, default: int,
                                lengths: Iterable[int],
                                dtype: str = "float32") -> int:
        """Size the episodic prep window from the measured per-class
        `gather` costs: one episode's prep should stage about
        `_PREP_BUDGET_US` of gather work, so slow/wide classes get
        shorter windows (prep must not outrun the overlapped commit)
        and fast/narrow ones longer, clamped to [1, 4*default]. With
        no relevant entries the `default` is returned untouched."""
        worst = 0.0
        for L in lengths:
            b = self._nearest_bucket("gather", int(L), 512, dtype,
                                     "sum")
            if b is None:
                continue
            c = self.cost_us("gather", int(L), b, dtype, "sum")
            if c is not None:
                worst = max(worst, c)
        if worst <= 0.0:
            return int(default)
        return int(np.clip(round(_PREP_BUDGET_US / worst), 1,
                           4 * max(1, int(default))))

    # -- persistence ---------------------------------------------------------

    def save(self, path: str) -> None:
        """Write the versioned JSON (atomic rename — a crashed
        calibration never leaves a torn table)."""
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"version": COSTS_SCHEMA_VERSION,
                       "backend": self.backend,
                       "entries": self._us}, f, indent=1,
                      sort_keys=True)
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str) -> "KernelCostTable":
        """Load a persisted table; ValueError on a version mismatch
        (recalibrate — entry semantics may have changed), the usual
        OSError family when the file is missing/unreadable."""
        with open(path) as f:
            doc = json.load(f)
        ver = doc.get("version")
        if ver != COSTS_SCHEMA_VERSION:
            raise ValueError(
                f"cost table {path!r} has schema version {ver!r}, "
                f"expected {COSTS_SCHEMA_VERSION} — recalibrate "
                f"(--sys.costs.calibrate)")
        t = cls(backend=str(doc.get("backend", "unknown")))
        for k, v in doc.get("entries", {}).items():
            t._us[str(k)] = float(v)
        return t


# -- calibration -------------------------------------------------------------


def _time_median(fn, repeats: int) -> float:
    """Median wall-clock of `repeats` calls, in µs (one warmup call —
    the first dispatch of a shape pays XLA compilation)."""
    fn()
    samples = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        samples.append(time.perf_counter() - t0)
    return _median_us(samples)


def calibrate_store(store, table: KernelCostTable,
                    buckets: Iterable[int] = (64, 512),
                    poolings: Iterable[str] = ("sum", "mean"),
                    repeats: int = 5,
                    rng: Optional[np.random.Generator] = None) -> None:
    """Measure every applicable variant on one live ShardedStore and
    record the results into `table`. Deterministic member indices
    (seeded rng); every probe includes the host readback — the cost a
    dispatch site actually pays."""
    from ..core.store import OOB, bucket_size
    from ..serve.bags import pool_bags_host
    rng = rng or np.random.default_rng(0)
    L = int(store.value_length)
    dtype = np.dtype(store.dtype).name
    S = store.ctx.num_shards
    for n in buckets:
        n = int(n)
        b = bucket_size(n, store.bucket_min)
        o_sh = rng.integers(0, S, size=n).astype(np.int32)
        o_sl = rng.integers(0, store.main_slots,
                            size=n).astype(np.int32)
        c_sh = np.zeros(n, np.int32)
        c_sl = np.full(n, OOB, np.int32)
        use_c = np.zeros(n, bool)
        nbags = max(1, n // 8)
        seg = np.minimum(np.arange(n, dtype=np.int64) // 8,
                         nbags - 1).astype(np.int32)

        def _flat_gather():
            return np.asarray(store.gather(o_sh, o_sl, c_sh, c_sl,
                                           use_c))[:n]

        table.record("gather", L, b, dtype, "sum",
                     _time_median(_flat_gather, repeats))
        for pooling in poolings:
            table.record(
                "gather_pool", L, b, dtype, pooling,
                _time_median(
                    lambda: np.asarray(store.gather_pool(
                        o_sh, o_sl, c_sh, c_sl, use_c, seg, nbags,
                        pooling=pooling))[:nbags],
                    repeats))
            table.record(
                "gather_hostpool", L, b, dtype, pooling,
                _time_median(
                    lambda: pool_bags_host(_flat_gather(), seg,
                                           nbags, pooling),
                    repeats))
        if store.res is not None and store.coldq is not None \
                and store.coldq.mode != "fp32":
            # tiered cold-wire ingest: force the wire path by probing
            # slots past the device-hot set (split_owner routes them
            # cold; the wire variant quantizes/dequantizes en route)
            hot = store.res.hot_rows
            if store.main_slots > hot:
                cold_sl = (hot + rng.integers(
                    0, store.main_slots - hot,
                    size=n)).astype(np.int32)
                table.record(
                    f"cold_wire_{store.coldq.mode}", L, b, dtype,
                    "sum",
                    _time_median(
                        lambda: np.asarray(store.gather(
                            o_sh, cold_sl, c_sh, c_sl, use_c))[:n],
                        repeats))
        # Pallas block gather (ops/pallas_kernels.py): TPU-only — on
        # stacks without Pallas lowering the first call raises and the
        # variant is simply absent from the table
        try:
            import jax.numpy as jnp
            from .pallas_kernels import gather_rows
            pool2d = jnp.zeros((max(8 * 8, store.main_slots), L),
                               dtype=np.dtype(store.dtype))
            idx = jnp.asarray(rng.integers(
                0, pool2d.shape[0] // 8, size=max(1, n // 8)),
                dtype=jnp.int32)
            table.record(
                "pallas_gather", L, b, dtype, "sum",
                _time_median(
                    lambda: np.asarray(gather_rows(pool2d, idx)),
                    repeats))
        except Exception:  # noqa: BLE001 — unsupported stack, not an error
            pass


def calibrate_server(server, buckets: Iterable[int] = (64, 512),
                     repeats: int = 5) -> KernelCostTable:
    """One calibration pass over every length class of a live Server.
    Returns the populated table (caller persists via `table.save`)."""
    table = KernelCostTable(
        backend=getattr(server.stores[0].port, "name", "unknown")
        if server.stores else "unknown")
    rng = np.random.default_rng(0)
    for st in server.stores:
        calibrate_store(st, table, buckets=buckets, repeats=repeats,
                        rng=rng)
    table.c_calibrations.inc()
    return table
