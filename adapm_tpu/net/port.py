"""The NetPort protocol: framed, checksummed PM wire messages.

One frame per message, fixed binary header + pickled payload:

    offset  size  field
    0       4     magic  b"APMN"
    4       2     wire version (u16; WIRE_VERSION)
    6       1     family (u8; FAMILY_*)
    7       1     flags  (u8; bit0 REPLY, bit1 POST — no reply expected)
    8       8     request id (u64; per-sender monotonic)
    16      4     sender rank (u32)
    20      4     payload length (u32)
    24      4     crc32 of payload (u32)
    28      ...   payload (pickle protocol 5)

Decode failures raise NAMED errors before any server mutation — the
corruption quartet (truncated / flipped byte / wrong version / spliced
frame) maps to FrameTruncatedError / FrameChecksumError /
FrameVersionError / FrameSpliceError, mirroring the r15 checkpoint and
r18 wtrace integrity discipline.

The five wire families follow the reference van's message taxonomy
(PAPER.md L0/L1):

    FAMILY_SYNC   replica delta ship/unsubscribe ("sync", "unsub") —
                  deltas travel in the r13 fp16/int8 EF-compressed
                  tuples produced by _extract_deltas, so the compressed
                  sync format IS the network encoding
    FAMILY_RELOC  intent-driven relocation/replication with
                  residual-carrying value rows ("intent")
    FAMILY_OWNER  ownership/addressbook moves ("owner_update")
    FAMILY_SERVE  forwarded reads/writes ("pull", "push", "set")
    FAMILY_CTRL   membership + heartbeat control ("beat", "leave",
                  "join", net/membership.py)

`NetPort` is the base class owning the codec, the request-id demux
(pending-future table), reply-error propagation, the receiver-side
at-most-once dedup cache, and the msgs/bytes accounting — so a backend
(loopback fabric, TCP socket) only supplies byte transport. That is
what makes socket.py "one class by construction" (the r17 DevicePort
recipe, applied to the network)."""
from __future__ import annotations

import pickle
import struct
import threading
import zlib
from collections import OrderedDict
from typing import Callable, Dict, Optional

WIRE_VERSION = 1
_MAGIC = b"APMN"
_HEADER = struct.Struct("!4sHBBQIII")
HEADER_SIZE = _HEADER.size  # 28

FAMILY_SYNC = 1
FAMILY_RELOC = 2
FAMILY_OWNER = 3
FAMILY_SERVE = 4
FAMILY_CTRL = 5
_FAMILIES = (FAMILY_SYNC, FAMILY_RELOC, FAMILY_OWNER, FAMILY_SERVE,
             FAMILY_CTRL)
FAMILY_NAMES = {FAMILY_SYNC: "sync", FAMILY_RELOC: "reloc",
                FAMILY_OWNER: "owner", FAMILY_SERVE: "serve",
                FAMILY_CTRL: "ctrl"}

FLAG_REPLY = 0x01
FLAG_POST = 0x02   # fire-and-forget (heartbeats): no reply is produced

# op string (msg[0]) -> wire family; replies reuse the request's family
_OP_FAMILY = {"sync": FAMILY_SYNC, "unsub": FAMILY_SYNC,
              "intent": FAMILY_RELOC,
              "owner_update": FAMILY_OWNER,
              "pull": FAMILY_SERVE, "push": FAMILY_SERVE,
              "set": FAMILY_SERVE,
              "beat": FAMILY_CTRL, "leave": FAMILY_CTRL,
              "join": FAMILY_CTRL}


# ---------------------------------------------------------------------------
# named errors
# ---------------------------------------------------------------------------


class NetError(RuntimeError):
    """Base class for every transport-plane failure."""


class NetDecodeError(NetError):
    """Base for frame-integrity failures: raised by decode_frame BEFORE
    the payload reaches any handler, so a corrupt frame can never
    mutate server state."""


class FrameTruncatedError(NetDecodeError):
    """Frame shorter than its header, or than the declared payload."""


class FrameChecksumError(NetDecodeError):
    """Payload crc32 does not match the header (flipped byte)."""


class FrameVersionError(NetDecodeError):
    """Wire version is not WIRE_VERSION (cross-version peer)."""


class FrameSpliceError(NetDecodeError):
    """Bad magic: the byte stream lost framing (spliced/misaligned)."""


class FrameFamilyError(NetDecodeError):
    """Unknown message family byte."""


class NetTimeoutError(NetError):
    """A request exhausted its timeout budget (including retransmits),
    or a fabric barrier timed out."""


class NetPeerDeadError(NetError):
    """The destination is known dead (killed, left, or declared dead by
    membership) — fail fast instead of burning the timeout."""


# ---------------------------------------------------------------------------
# codec
# ---------------------------------------------------------------------------


def encode_frame(family: int, rid: int, src: int, obj,
                 flags: int = 0) -> bytes:
    payload = pickle.dumps(obj, protocol=5)
    return _HEADER.pack(_MAGIC, WIRE_VERSION, family, flags, rid, src,
                        len(payload), zlib.crc32(payload)) + payload


def decode_header(buf: bytes):
    """(family, flags, rid, src, payload_len, crc) — validates magic /
    version / family / length, raising the named errors. Used by both
    decode_frame (whole-buffer backends) and the TCP stream reader
    (header-first reads)."""
    if len(buf) < HEADER_SIZE:
        raise FrameTruncatedError(
            f"frame header truncated: {len(buf)} < {HEADER_SIZE} bytes")
    magic, ver, family, flags, rid, src, plen, crc = \
        _HEADER.unpack_from(buf)
    if magic != _MAGIC:
        raise FrameSpliceError(
            f"bad frame magic {magic!r} (expected {_MAGIC!r}): "
            f"spliced or misaligned byte stream")
    if ver != WIRE_VERSION:
        raise FrameVersionError(
            f"wire version {ver} != {WIRE_VERSION}")
    if family not in _FAMILIES:
        raise FrameFamilyError(f"unknown message family {family}")
    return family, flags, rid, src, plen, crc


def decode_frame(buf: bytes):
    """(family, flags, rid, src, obj) or a named NetDecodeError."""
    family, flags, rid, src, plen, crc = decode_header(buf)
    if len(buf) != HEADER_SIZE + plen:
        raise FrameTruncatedError(
            f"frame payload truncated: have {len(buf) - HEADER_SIZE} "
            f"of {plen} declared bytes")
    payload = buf[HEADER_SIZE:]
    if zlib.crc32(payload) != crc:
        raise FrameChecksumError(
            f"payload crc mismatch (family="
            f"{FAMILY_NAMES.get(family, family)}, rid={rid})")
    return family, flags, rid, src, pickle.loads(payload)


def family_for_msg(msg) -> int:
    """Wire family for a PM op tuple; unknown ops ride FAMILY_SERVE."""
    if isinstance(msg, tuple) and msg and isinstance(msg[0], str):
        return _OP_FAMILY.get(msg[0], FAMILY_SERVE)
    return FAMILY_SERVE


# ---------------------------------------------------------------------------
# the port base class
# ---------------------------------------------------------------------------


class _Pending:
    __slots__ = ("event", "reply", "error", "peer")

    def __init__(self, peer: int = -1):
        self.event = threading.Event()
        self.reply = None
        self.error: Optional[BaseException] = None
        self.peer = peer


class NetPort:
    """Request/reply demux + at-most-once execution over any byte
    transport. Subclasses implement `_send_bytes(dest, buf)` and feed
    received buffers to `_on_frame(buf)`; everything else — rid
    allocation, pending futures, reply-error propagation, the
    receiver-side rid dedup cache (pushes are additive, NOT idempotent:
    a retransmitted request must re-send the cached reply, never
    re-execute), and msgs/bytes accounting — lives here."""

    DEDUP_CACHE = 4096

    def __init__(self, pid: int, num: int,
                 handler: Callable[[object], object],
                 ctrl_handler: Optional[Callable[[int, object], None]]
                 = None):
        self.pid = int(pid)
        self.num = int(num)
        self.handler = handler
        # CTRL frames (membership/heartbeat) bypass the PM handler
        self.ctrl_handler = ctrl_handler
        self._rid_lock = threading.Lock()
        self._rid = 0
        self._pending: Dict[int, _Pending] = {}
        self._pending_lock = threading.Lock()
        # (src, rid) -> encoded reply bytes; OrderedDict as bounded LRU
        self._served: "OrderedDict[tuple, bytes]" = OrderedDict()
        self._served_lock = threading.Lock()
        # accounting (plain ints under one lock: the snapshot-side
        # NetPlane reads them; no registry names unless a plane exists)
        self._stats_lock = threading.Lock()
        self.stats = {"msgs_out": 0, "msgs_in": 0,
                      "bytes_out": 0, "bytes_in": 0,
                      "replies_out": 0, "retransmits": 0,
                      "dup_suppressed": 0, "decode_errors": 0,
                      "dropped_frames": 0}
        for name in FAMILY_NAMES.values():
            self.stats[f"msgs_{name}"] = 0

    # -- subclass surface ----------------------------------------------------

    def _send_bytes(self, dest: int, buf: bytes) -> None:
        raise NotImplementedError

    def start(self) -> None:  # lifecycle parity with DcnChannel
        pass

    def shutdown(self) -> None:
        pass

    # -- accounting ----------------------------------------------------------

    def _acct(self, **deltas) -> None:
        with self._stats_lock:
            for k, v in deltas.items():
                self.stats[k] += v

    # -- requests ------------------------------------------------------------

    def _next_rid(self) -> int:
        with self._rid_lock:
            self._rid += 1
            return self._rid

    def request(self, peer: int, msg, timeout_s: float = 30.0,
                retries: int = 0):
        """Synchronous round-trip. Raises RuntimeError on remote error
        (DcnChannel parity), NetTimeoutError when the budget (timeout
        per attempt x (retries + 1)) is exhausted, NetPeerDeadError
        when the backend knows the peer is gone. Retransmits reuse the
        SAME rid, so the receiver's dedup cache guarantees at-most-once
        execution under duplicate delivery."""
        assert peer != self.pid, "use local ops, not a self-request"
        rid = self._next_rid()
        family = family_for_msg(msg)
        buf = encode_frame(family, rid, self.pid, msg)
        pend = _Pending(peer)
        with self._pending_lock:
            self._pending[rid] = pend
        try:
            attempt = 0
            while True:
                self._send_bytes(peer, buf)
                self._acct(msgs_out=1, bytes_out=len(buf),
                           **{f"msgs_{FAMILY_NAMES[family]}": 1})
                if pend.event.wait(timeout_s):
                    break
                attempt += 1
                if attempt > retries:
                    raise NetTimeoutError(
                        f"no reply from peer {peer} for "
                        f"{FAMILY_NAMES[family]} rid={rid} after "
                        f"{attempt} attempt(s) x {timeout_s:g}s")
                self._acct(retransmits=1)
        finally:
            with self._pending_lock:
                self._pending.pop(rid, None)
        if pend.error is not None:
            raise pend.error
        reply = pend.reply
        if isinstance(reply, tuple) and reply \
                and isinstance(reply[0], str) and reply[0] == "error":
            raise RuntimeError(f"peer {peer}: {reply[1]}")
        return reply

    def post(self, peer: int, msg) -> None:
        """Fire-and-forget (heartbeats/membership control): no pending
        entry, no reply, loss is acceptable by design."""
        family = family_for_msg(msg)
        buf = encode_frame(family, self._next_rid(), self.pid, msg,
                           flags=FLAG_POST)
        self._send_bytes(peer, buf)
        self._acct(msgs_out=1, bytes_out=len(buf),
                   **{f"msgs_{FAMILY_NAMES[family]}": 1})

    def fail_pending_to(self, peer: int, err: BaseException) -> None:
        """Fail every request currently awaiting `peer` (dead-peer
        cleanup: the requester raises the named error instead of
        burning its full timeout budget)."""
        with self._pending_lock:
            pend = [p for p in self._pending.values() if p.peer == peer]
        for p in pend:
            if not p.event.is_set():
                p.error = err
                p.event.set()

    # -- receive path --------------------------------------------------------

    def _on_frame(self, buf: bytes) -> None:
        """Decode + dispatch one received frame. Decode errors are
        COUNTED and re-raised to the backend (which drops the frame —
        the named error surfaces to tests via decode_frame directly,
        and a production backend logs it); they can never reach the
        handler, so no server mutation happens on a corrupt frame."""
        try:
            family, flags, rid, src, obj = decode_frame(buf)
        except NetDecodeError:
            self._acct(decode_errors=1)
            raise
        self._acct(msgs_in=1, bytes_in=len(buf))
        if flags & FLAG_REPLY:
            with self._pending_lock:
                pend = self._pending.get(rid)
            if pend is not None and not pend.event.is_set():
                pend.reply = obj
                pend.event.set()
            return
        if family == FAMILY_CTRL and self.ctrl_handler is not None:
            self.ctrl_handler(src, obj)
            return
        if flags & FLAG_POST:
            # fire-and-forget for a non-ctrl family: execute, no reply
            self.handler(obj)
            return
        key = (src, rid)
        with self._served_lock:
            cached = self._served.get(key)
            if cached is not None:
                self._served.move_to_end(key)
        if cached is not None:
            # duplicate delivery (retransmit or net.dup): at-most-once
            # execution — re-send the cached reply, never re-run the
            # handler (pushes are additive; double-apply corrupts)
            self._acct(dup_suppressed=1)
            self._send_reply_bytes(src, cached)
            return
        try:
            reply = self.handler(obj)
        except Exception as e:  # noqa: BLE001 — ship errors to requester
            reply = ("error", f"{type(e).__name__}: {e}")
        out = encode_frame(family, rid, self.pid, reply,
                           flags=FLAG_REPLY)
        with self._served_lock:
            self._served[key] = out
            while len(self._served) > self.DEDUP_CACHE:
                self._served.popitem(last=False)
        self._send_reply_bytes(src, out)

    def _send_reply_bytes(self, dest: int, buf: bytes) -> None:
        try:
            self._send_bytes(dest, buf)
            self._acct(replies_out=1, bytes_out=len(buf))
        except NetError:
            # requester is gone/partitioned: it will retransmit or fail
            # on its own timeout; the reply stays in the dedup cache
            self._acct(dropped_frames=1)

    def stats_snapshot(self) -> Dict[str, int]:
        with self._stats_lock:
            return dict(self.stats)


# ---------------------------------------------------------------------------
# node abstraction: what GlobalPM/Server need from "the cluster"
# ---------------------------------------------------------------------------


class NetNode:
    """The narrow surface GlobalPM and Server consume: identity, a
    request channel, barriers, liveness. Implementations: DcnNode (the
    real multi-process default — jax.distributed control plane + the
    DCN data channel), LoopbackNode (in-process fabric), and a TCP
    flavor of DcnNode (--sys.net.backend tcp)."""

    kind = "abstract"
    pid: int
    num_procs: int

    def make_channel(self, handler, serve_threads: int):
        raise NotImplementedError

    def barrier(self, name: Optional[str] = None) -> None:
        raise NotImplementedError

    def dead_peers(self, max_age_s: float = 10.0) -> list:
        return []

    def start_heartbeat(self, interval_s: float) -> None:
        pass

    def stop_heartbeat(self) -> None:
        pass

    def pre_down(self) -> None:
        """Called at the top of GlobalPM.shutdown, before the pm-pre-
        down barrier: announce a graceful leave so peers never mistake
        this teardown for a death (loopback membership)."""

    def net_plane(self):
        """The NetPlane stats surface (snapshot `net` section), or None
        for the legacy DCN backend (its accounting lives in `pm`)."""
        return None


class DcnNode(NetNode):
    """Default multi-process node: identity + barriers from the
    jax.distributed control plane (parallel/control.py), data plane
    from DcnChannel — byte-identical to pre-NetPort behavior — or, with
    `--sys.net.backend tcp`, from the TcpNetPort speaking NetPort
    frames over the same coordinator-KV rendezvous."""

    kind = "dcn"

    def __init__(self, opts=None):
        from ..parallel import control
        self.pid = control.process_id()
        self.num_procs = control.num_processes()
        self.opts = opts
        self._chan = None

    def make_channel(self, handler, serve_threads: int):
        backend = getattr(self.opts, "net_backend", "auto") \
            if self.opts is not None else "auto"
        if backend == "tcp":
            from .socket import TcpNetPort, coordinator_rendezvous
            self._chan = TcpNetPort(
                self.pid, self.num_procs, handler,
                rendezvous=coordinator_rendezvous,
                serve_threads=serve_threads,
                timeout_s=(getattr(self.opts, "net_timeout_ms", 30_000.0)
                           * 1e-3))
        else:
            from ..parallel.dcn import DcnChannel
            self._chan = DcnChannel(self.pid, self.num_procs, handler,
                                    serve_threads=serve_threads)
        return self._chan

    def barrier(self, name: Optional[str] = None) -> None:
        from ..parallel import control
        if name is None:
            control.barrier()
        else:
            control.barrier(name)

    def dead_peers(self, max_age_s: float = 10.0) -> list:
        from ..parallel import control
        return control.dead_processes(max_age_s)

    def start_heartbeat(self, interval_s: float) -> None:
        from ..parallel import control
        control.start_heartbeat(interval_s)

    def stop_heartbeat(self) -> None:
        from ..parallel import control
        control.stop_heartbeat()
