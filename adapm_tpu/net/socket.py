"""TCP backend for the NetPort — one class by construction.

`NetPort` (port.py) already owns the codec, rid demux, reply-error
propagation, at-most-once dedup, and accounting; `TcpNetPort` only adds
byte transport: a listener, lazily-connected per-peer sockets (peer
addresses rendezvoused through a pluggable key-value store — the
jax.distributed coordinator in real launches, a dict in tests), and
reader threads that reassemble frames header-first with the SAME
`decode_header` the loopback and the corruption quartet exercise.

Every reader feeds `_on_frame`, which dispatches requests AND resolves
replies — so it does not matter which of the pair's two sockets a frame
arrives on, and the whole class stays under ~150 lines. Stream-level
decode errors (bad magic = lost framing) close the connection: unlike a
datagram fabric there is no way to resynchronize a spliced TCP stream,
and the peer's retransmit path re-establishes it."""
from __future__ import annotations

import socket
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, Optional

from .port import (HEADER_SIZE, NetDecodeError, NetPeerDeadError,
                   NetPort, decode_header)


class DictRendezvous:
    """In-process key-value rendezvous for tests: the coordinator's
    set/blocking-get surface over a plain dict + condition."""

    def __init__(self):
        self._kv: Dict[str, str] = {}
        self._cond = threading.Condition()

    def set(self, key: str, value: str) -> None:
        with self._cond:
            self._kv[key] = value
            self._cond.notify_all()

    def get(self, key: str, timeout_ms: int = 60_000) -> str:
        with self._cond:
            ok = self._cond.wait_for(lambda: key in self._kv,
                                     timeout_ms * 1e-3)
            if not ok:
                raise TimeoutError(f"rendezvous key {key!r} never set")
            return self._kv[key]


class _CoordinatorRendezvous:
    """The real thing: the jax.distributed coordinator's KV store
    (same store parallel/dcn.py rendezvouses through)."""

    def _client(self):
        from jax._src import distributed
        client = distributed.global_state.client
        assert client is not None, "jax.distributed not initialized"
        return client

    def set(self, key: str, value: str) -> None:
        self._client().key_value_set(key, value)

    def get(self, key: str, timeout_ms: int = 60_000) -> str:
        return self._client().blocking_key_value_get(key, timeout_ms)


def coordinator_rendezvous():
    return _CoordinatorRendezvous()


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


class TcpNetPort(NetPort):
    """NetPort frames over TCP (see module docstring)."""

    def __init__(self, pid: int, num: int, handler: Callable,
                 rendezvous=coordinator_rendezvous,
                 serve_threads: int = 4, timeout_s: float = 30.0,
                 ctrl_handler=None, kv_prefix: str = "adapm/net"):
        super().__init__(pid, num, handler, ctrl_handler=ctrl_handler)
        self.rv = rendezvous() if callable(rendezvous) else rendezvous
        self.timeout_s = float(timeout_s)
        self.kv_prefix = kv_prefix
        self._listener: Optional[socket.socket] = None
        self._peers: Dict[int, socket.socket] = {}
        self._send_locks: Dict[int, threading.Lock] = {}
        self._resolve_lock = threading.Lock()
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, serve_threads),
            thread_name_prefix="adapm-net-h")
        self._stop = threading.Event()
        self._threads = []

    def request(self, peer, msg, timeout_s: Optional[float] = None,
                retries: int = 1):
        return super().request(
            peer, msg,
            timeout_s=self.timeout_s if timeout_s is None else timeout_s,
            retries=retries)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._listener = socket.socket()
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind(("0.0.0.0", 0))
        self._listener.listen(self.num)
        port = self._listener.getsockname()[1]
        self.rv.set(f"{self.kv_prefix}/{self.pid}",
                    f"{socket.gethostname()}:{port}")
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name=f"adapm-net-accept{self.pid}")
        t.start()
        self._threads.append(t)

    def shutdown(self) -> None:
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        with self._resolve_lock:
            socks = list(self._peers.values())
            self._peers.clear()
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
        self._pool.shutdown(wait=False)

    # -- transport -----------------------------------------------------------

    def _send_bytes(self, dest: int, buf: bytes) -> None:
        try:
            sock, lock = self._resolve(dest)
            with lock:
                sock.sendall(buf)
        except (OSError, TimeoutError) as e:
            # drop the dead socket so a retransmit re-resolves (a
            # restarted peer re-rendezvouses; a dead one fails again)
            with self._resolve_lock:
                if self._peers.get(dest) is not None:
                    try:
                        self._peers.pop(dest).close()
                    except OSError:
                        pass
            raise NetPeerDeadError(
                f"send to peer {dest} failed: "
                f"{type(e).__name__}: {e}") from e

    def _resolve(self, peer: int):
        with self._resolve_lock:
            sock = self._peers.get(peer)
            if sock is not None:
                return sock, self._send_locks[peer]
            addr = self.rv.get(f"{self.kv_prefix}/{peer}",
                               int(self.timeout_s * 1e3))
            host, port = addr.rsplit(":", 1)
            sock = socket.create_connection((host, int(port)),
                                            timeout=self.timeout_s)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._peers[peer] = sock
            lock = self._send_locks[peer] = threading.Lock()
            t = threading.Thread(target=self._read_loop, args=(sock,),
                                 daemon=True,
                                 name=f"adapm-net-r{self.pid}.{peer}")
            t.start()
            self._threads.append(t)
            return sock, lock

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._read_loop, args=(conn,),
                                 daemon=True,
                                 name=f"adapm-net-s{self.pid}")
            t.start()
            self._threads.append(t)

    def _read_loop(self, sock: socket.socket) -> None:
        """Header-first frame reassembly; every frame — request or
        reply — goes through _on_frame on the serve pool."""
        while not self._stop.is_set():
            try:
                head = _recv_exact(sock, HEADER_SIZE)
                if head is None:
                    return
                try:
                    plen = decode_header(head)[4]
                except NetDecodeError:
                    # lost framing on a byte stream is unrecoverable:
                    # count + drop the connection (peer re-resolves)
                    self._acct(decode_errors=1, dropped_frames=1)
                    try:
                        sock.close()
                    except OSError:
                        pass
                    return
                body = _recv_exact(sock, plen)
                if body is None:
                    return
            except OSError:
                return
            self._pool.submit(self._dispatch, head + body)

    def _dispatch(self, buf: bytes) -> None:
        try:
            self._on_frame(buf)
        except NetDecodeError:
            self._acct(dropped_frames=1)
