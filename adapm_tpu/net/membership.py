"""Elastic membership + dead-peer failover for the loopback backend.

Every node runs one Membership plane: a daemon thread that beats
(FAMILY_CTRL posts, fire-and-forget — loss is absorbed by the next
beat) every `heartbeat_s` and monitors peer staleness. The state
machine per peer:

    live --(no beat for DEAD_AFTER_BEATS intervals)--> dead
    live --("leave" ctrl msg, graceful shutdown)-----> left
    dead --("beat" ctrl msg, restore drill)----------> live (rejoin)

`left` is terminal for a teardown and NEVER triggers failover —
GlobalPM.shutdown announces the leave via `NetNode.pre_down` BEFORE the
pm-pre-down barrier, so a graceful exit cannot be mistaken for a death
even though the executor (and its beats-carrying streams — beats ride
their own thread precisely so they DON'T) is already closed.

`dead` triggers failover exactly once per transition: pending requests
to the corpse fail fast with NetPeerDeadError, then
`GlobalPM.failover_dead_peer` promotes every replica of a dead-owned
key to main through the existing `_adopt` path (`Server.
_topology_mutation` discipline — the same replica→main upgrade intent
uses, so pending sync deltas merge instead of dropping). Keys the dead
rank owned WITHOUT a live replica are lost — counted, surfaced in
`net.lost_keys`, and subsequent reads raise NetPeerDeadError rather
than hang. Wall-clock from detection to served-again is recorded in
`net.failover_s` (bounded by the storm check + bench `net` phase).

The plane IS the snapshot `net` section (schema v15) and registers the
`net.*` registry names — both exist only when a loopback node is
attached, so the default single-process/DCN server keeps zero net cost
(metrics_overhead_check.py pins plane-off: no object, no names)."""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from .port import NetPeerDeadError

DEAD_AFTER_BEATS = 5  # missed-beat count before declaring a peer dead


class Membership:
    """Per-node membership/heartbeat/failover plane (module docstring).
    Doubles as the NetPlane: stats() feeds the snapshot `net` section,
    and net.* registry gauges read through it."""

    def __init__(self, node, server, heartbeat_s: float = 0.1):
        self.node = node
        self.server = server
        self.port = node.port
        self.heartbeat_s = max(1e-3, float(heartbeat_s))
        self._lock = threading.Lock()
        now = time.monotonic()
        self.state: Dict[int, str] = {
            r: "live" for r in range(node.num_procs)}
        self._last_beat: Dict[int, float] = {
            r: now for r in range(node.num_procs)}
        # monitor-loop iteration counter + per-peer last-seen tick:
        # death needs BOTH the wall-clock horizon AND DEAD_AFTER_BEATS
        # of OUR OWN completed loop iterations since the last beat — a
        # whole-process stall (GIL, XLA compile) freezes the tick
        # counter along with the peers' beat threads, so it can never
        # read as everyone dying at once
        self._tick = 0
        self._tick_seen: Dict[int, int] = {
            r: 0 for r in range(node.num_procs)}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.beats_out = 0
        self.joins = 0
        self.leaves = 0
        self.failovers = 0
        self.failover_s = 0.0   # most recent detection->promoted wall
        self.promoted_keys = 0
        self.lost_keys = 0
        self._register_metrics(server.obs)

    def _register_metrics(self, registry) -> None:
        # net.* names exist ONLY when a plane exists (r7 discipline;
        # metrics_overhead_check.py pins the registry empty of them on
        # a default server). Shared: a rebuilt plane rebinds readers.
        if registry is None or not registry.enabled:
            return
        for key in ("msgs_out", "msgs_in", "bytes_out", "bytes_in",
                    "retransmits", "dup_suppressed", "decode_errors",
                    "dropped_frames"):
            registry.gauge(f"net.{key}", shared=True,
                           fn=lambda k=key: self.port.stats[k])
        registry.gauge("net.peers_live", shared=True,
                       fn=lambda: self.live_count())
        registry.gauge("net.peers_total", shared=True,
                       fn=lambda: self.node.num_procs)
        registry.gauge("net.peers_dead", shared=True,
                       fn=lambda: len(self.dead_peers()))
        registry.gauge("net.failovers", shared=True,
                       fn=lambda: self.failovers)
        registry.gauge("net.failover_s", unit="s", shared=True,
                       fn=lambda: self.failover_s)
        registry.gauge("net.lost_keys", shared=True,
                       fn=lambda: self.lost_keys)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        t = threading.Thread(target=self._loop, daemon=True,
                             name=f"adapm-net-beat{self.node.pid}")
        self._thread = t
        t.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def announce_leave(self) -> None:
        """Graceful-leave broadcast (NetNode.pre_down): peers mark this
        rank `left` so the teardown never reads as a death."""
        for peer in self._peers("live"):
            try:
                self.port.post(peer, ("leave", self.node.pid))
            except NetPeerDeadError:
                pass

    # -- beat/monitor loop ---------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.wait(self.heartbeat_s):
            me = self.node.pid
            for peer in self._peers("live"):
                try:
                    self.port.post(peer, ("beat", me))
                    self.beats_out += 1
                except NetPeerDeadError:
                    pass  # staleness, not send failure, declares death
            self._tick += 1
            self._check_stale()

    def _check_stale(self) -> None:
        horizon = time.monotonic() - DEAD_AFTER_BEATS * self.heartbeat_s
        for peer in self._peers("live"):
            if self._last_beat.get(peer, 0.0) < horizon and \
                    self._tick - self._tick_seen.get(peer, 0) > \
                    DEAD_AFTER_BEATS:
                self._mark_dead(peer)

    def _peers(self, state: str) -> List[int]:
        me = self.node.pid
        with self._lock:
            return [r for r, s in self.state.items()
                    if s == state and r != me]

    # -- ctrl plane ----------------------------------------------------------

    def on_ctrl(self, src: int, msg) -> None:
        op = msg[0] if isinstance(msg, tuple) and msg else msg
        now = time.monotonic()
        with self._lock:
            self._last_beat[src] = now
            self._tick_seen[src] = self._tick
            prev = self.state.get(src, "live")
            if op == "leave":
                self.state[src] = "left"
                self.leaves += 1
                return
            if op in ("beat", "join") and prev == "dead":
                # restore drill: a corpse beating again rejoins live
                self.state[src] = "live"
                self.joins += 1

    # -- death + failover ----------------------------------------------------

    def _mark_dead(self, peer: int) -> None:
        with self._lock:
            if self.state.get(peer) != "live":
                return  # already dead/left; failover ran once
            self.state[peer] = "dead"
        t0 = time.monotonic()
        self.port.fail_pending_to(
            peer, NetPeerDeadError(
                f"peer {peer} declared dead (no beat for "
                f"{DEAD_AFTER_BEATS:g} x {self.heartbeat_s:g}s)"))
        glob = getattr(self.server, "glob", None)
        promoted = lost = 0
        if glob is not None:
            try:
                promoted, lost = glob.failover_dead_peer(peer)
            except Exception:  # noqa: BLE001 — a failed failover must
                # not kill the beat thread; the keys stay dead-owned
                # and reads surface NetPeerDeadError per-key
                pass
        with self._lock:
            self.failovers += 1
            self.failover_s = time.monotonic() - t0
            self.promoted_keys += promoted
            self.lost_keys += lost

    # -- liveness surface (NetNode.dead_peers / serve/health.py) -------------

    def dead_peers(self) -> List[int]:
        with self._lock:
            return sorted(r for r, s in self.state.items()
                          if s == "dead")

    def live_count(self) -> int:
        with self._lock:
            return sum(1 for s in self.state.values() if s == "live")

    def peer_states(self) -> Dict[int, str]:
        with self._lock:
            return dict(self.state)

    # -- snapshot `net` section (schema v15) ---------------------------------

    def stats(self) -> Dict:
        out: Dict = dict(self.port.stats_snapshot())
        with self._lock:
            out.update({
                "backend": self.node.kind,
                "peers_total": self.node.num_procs,
                "peers_live": sum(1 for s in self.state.values()
                                  if s == "live"),
                "peers_dead": sum(1 for s in self.state.values()
                                  if s == "dead"),
                "peers_left": sum(1 for s in self.state.values()
                                  if s == "left"),
                "beats_out": self.beats_out,
                "joins": self.joins,
                "leaves": self.leaves,
                "failovers": self.failovers,
                "failover_s": self.failover_s,
                "promoted_keys": self.promoted_keys,
                "lost_keys": self.lost_keys,
            })
        return out
