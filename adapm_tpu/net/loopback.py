"""In-process loopback backend: the whole mp matrix in one container.

`LoopbackFabric` is the wire: per-(src, dst) bounded FIFO queues of
ENCODED frames (bytes — the codec genuinely runs, so corruption and
version drills exercise the same decode path a socket would), a
pairwise partition table, a kill switch per rank, and generation-
counted barriers over the LIVE member set (a killed rank never wedges
a survivor's barrier).

`LoopbackPort` is the per-node endpoint. Inbound frames drain on the
owning server's r11 executor, one `net.<peer>` stream per source —
ordered FIFO per peer, visible in exec.* accounting, overlapping
across peers (NestPipe's overlap structure for lookup/sync traffic
across shards). During teardown the executor closes BEFORE the PM's
pm-pre-down barrier (Server.shutdown step 7 vs 10, same order as the
real DCN path, where serving rides the channel's own pool) — so each
port keeps one fallback drain thread that takes over the moment the
executor stops accepting programs; late peer requests are still served
and the shutdown barriers converge.

Fault injection (r15 plane, `--sys.fault.spec`): the named wire points

    net.send       outbound frame dropped at the sender
    net.recv       inbound frame dropped at the receiver
    net.delay      outbound frame delayed ~5 ms
    net.dup        outbound frame delivered twice
    net.partition  the (src, dst) link misbehaves for this frame

are evaluated with `FaultPlane.draw` (seeded, per-point streams) —
non-raising: a dropped/duplicated frame is the fault, and the
at-most-once machinery (rid dedup + retransmit) must absorb it
bit-identically, which scripts/net_storm_check.py pins."""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Set, Tuple

from .port import (FAMILY_CTRL, NetDecodeError, NetNode,
                   NetPeerDeadError, NetPort, NetTimeoutError)

_FAMILY_CTRL_BYTE = FAMILY_CTRL  # header family byte sits at offset 6

# fabric-level barrier bound: generous next to the per-request timeout
# (--sys.net.timeout_ms) — a barrier wedging for this long means a
# driver thread died without leaving, which should fail loudly
_BARRIER_TIMEOUT_S = 60.0
_DELAY_S = 0.005  # net.delay injected latency per fired frame


class LoopbackFabric:
    """The shared in-process wire between `world` loopback nodes."""

    def __init__(self, world: int, queue: int = 64,
                 timeout_ms: float = 2000.0, retries: int = 16,
                 heartbeat_ms: float = 100.0):
        assert world >= 1
        self.world = int(world)
        self.queue = max(1, int(queue))
        self.timeout_s = float(timeout_ms) * 1e-3
        self.retries = int(retries)
        self.heartbeat_s = float(heartbeat_ms) * 1e-3
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self.ports: Dict[int, "LoopbackPort"] = {}
        self.killed: Set[int] = set()
        self.left: Set[int] = set()
        self._partitioned: Set[frozenset] = set()
        # name -> (generation, set of arrived ranks)
        self._barriers: Dict[str, Tuple[int, set]] = {}

    # -- membership of the wire ---------------------------------------------

    def register(self, port: "LoopbackPort") -> None:
        with self._lock:
            self.ports[port.pid] = port

    def live_ranks(self) -> List[int]:
        with self._lock:
            return [r for r in range(self.world)
                    if r not in self.killed and r not in self.left]

    def kill(self, rank: int) -> None:
        """Hard-kill `rank`: sever every link NOW (sends to and from it
        raise NetPeerDeadError, queued frames are dropped), fail its
        peers' pending requests, and release any barrier it was
        blocking. Its heartbeats stop with its port — survivors DETECT
        the death through beat staleness (net/membership.py), which is
        what the failover drill exercises."""
        with self._lock:
            self.killed.add(rank)
            self._cond.notify_all()
        err = NetPeerDeadError(f"rank {rank} was killed")
        for r, port in list(self.ports.items()):
            port.fail_pending_to(rank, err)
            port.drop_queues_from(rank)
        victim = self.ports.get(rank)
        if victim is not None:
            victim.fail_all_pending(NetPeerDeadError(
                f"rank {rank} was killed (self)"))

    def mark_left(self, rank: int) -> None:
        with self._lock:
            self.left.add(rank)
            self._cond.notify_all()

    def partition(self, a: int, b: int) -> None:
        """Deterministically block the (a, b) link both ways until
        heal() — drill API; the probabilistic net.partition point is
        per-frame."""
        with self._lock:
            self._partitioned.add(frozenset((a, b)))

    def heal(self, a: int, b: int) -> None:
        with self._lock:
            self._partitioned.discard(frozenset((a, b)))

    def link_blocked(self, a: int, b: int) -> bool:
        with self._lock:
            return frozenset((a, b)) in self._partitioned

    def is_dead(self, rank: int) -> bool:
        with self._lock:
            return rank in self.killed

    # -- barriers ------------------------------------------------------------

    def barrier(self, name: str, rank: int,
                timeout_s: float = _BARRIER_TIMEOUT_S) -> None:
        """Generation-counted barrier over the LIVE ranks. A rank that
        dies (kill) or leaves mid-wait shrinks the quorum, so the
        survivors converge instead of hanging — the property the
        kill/restore drill needs from pm-pre-down/pm-down."""
        deadline = time.monotonic() + timeout_s
        with self._lock:
            gen, arrived = self._barriers.get(name, (0, set()))
            my_gen = gen
            arrived = set(arrived)
            arrived.add(rank)
            self._barriers[name] = (gen, arrived)
            self._cond.notify_all()
            while True:
                gen, arrived = self._barriers.get(name, (0, set()))
                if gen != my_gen:
                    return  # generation completed while we waited
                live = {r for r in range(self.world)
                        if r not in self.killed and r not in self.left}
                if arrived >= live:
                    self._barriers[name] = (my_gen + 1, set())
                    self._cond.notify_all()
                    return
                rem = deadline - time.monotonic()
                if rem <= 0:
                    raise NetTimeoutError(
                        f"loopback barrier {name!r} gen {my_gen} timed "
                        f"out at rank {rank}: arrived={sorted(arrived)} "
                        f"live={sorted(live)}")
                self._cond.wait(min(rem, 0.25))


class LoopbackPort(NetPort):
    """One node's endpoint on the fabric (see module docstring)."""

    def __init__(self, fabric: LoopbackFabric, pid: int, handler,
                 ctrl_handler=None):
        super().__init__(pid, fabric.world, handler,
                         ctrl_handler=ctrl_handler)
        self.fabric = fabric
        # (src -> deque of frames) + per-src claimed flag: exactly one
        # drainer (executor program OR the fallback thread) owns a
        # queue at a time, so per-peer FIFO order holds no matter who
        # drains
        self._in_lock = threading.Lock()
        self._in_cond = threading.Condition(self._in_lock)
        self._inbox: Dict[int, deque] = {}
        self._claimed: Set[int] = set()
        self._closed = False
        # late-bound by LoopbackNode.bind(server): the executor the
        # net.<peer> streams run on, and the fault plane for the wire
        # points (None = no injection, zero cost)
        self._exec = None
        self.fault = None
        self._fallback: Optional[threading.Thread] = None
        fabric.register(self)

    # -- wiring --------------------------------------------------------------

    def bind(self, executor, fault) -> None:
        self._exec = executor
        self.fault = fault

    def request(self, peer: int, msg, timeout_s: Optional[float] = None,
                retries: Optional[int] = None):
        return super().request(
            peer, msg,
            timeout_s=self.fabric.timeout_s if timeout_s is None
            else timeout_s,
            retries=self.fabric.retries if retries is None else retries)

    # -- send side -----------------------------------------------------------

    def _send_bytes(self, dest: int, buf: bytes) -> None:
        fab = self.fabric
        if fab.is_dead(dest):
            raise NetPeerDeadError(f"peer {dest} is dead")
        if fab.is_dead(self.pid):
            raise NetPeerDeadError(f"rank {self.pid} was killed")
        f = self.fault
        if f is not None:
            if fab.link_blocked(self.pid, dest) or \
                    f.draw("net.partition"):
                self._acct(dropped_frames=1)
                return  # the link ate it; retransmit absorbs
            if f.draw("net.send"):
                self._acct(dropped_frames=1)
                return
            if f.draw("net.delay"):
                time.sleep(_DELAY_S)
            copies = 2 if f.draw("net.dup") else 1
        else:
            if fab.link_blocked(self.pid, dest):
                self._acct(dropped_frames=1)
                return
            copies = 1
        port = fab.ports.get(dest)
        if port is None:
            raise NetPeerDeadError(f"peer {dest} has no port")
        # CTRL frames (beats/membership) bypass the data queues and
        # deliver inline on the sender's thread: heartbeats ride the
        # CONTROL plane, exactly as the real DCN path's beats ride the
        # jax coordinator, never the data channel — so a data-plane
        # backlog (busy executor, full queue) can not fake a death
        if buf[6] == _FAMILY_CTRL_BYTE:
            try:
                for _ in range(copies):
                    port._on_frame(buf)
            except NetDecodeError:
                self._acct(dropped_frames=1)
            return
        for _ in range(copies):
            port._enqueue(self.pid, buf)

    # -- receive side --------------------------------------------------------

    def _enqueue(self, src: int, buf: bytes) -> None:
        """Called on the SENDER's thread: append to the bounded per-src
        FIFO (blocking briefly on backpressure), then kick a drain."""
        deadline = time.monotonic() + self.fabric.timeout_s
        with self._in_cond:
            if self._closed:
                return
            q = self._inbox.get(src)
            if q is None:
                q = self._inbox[src] = deque()
            while len(q) >= self.fabric.queue:
                rem = deadline - time.monotonic()
                if rem <= 0:
                    # bounded queue stayed full past the timeout: the
                    # frame is dropped; requester retransmits
                    self._acct(dropped_frames=1)
                    return
                self._in_cond.wait(min(rem, 0.05))
                if self._closed:
                    return
            q.append(buf)
            self._in_cond.notify_all()
        self._kick(src)

    def _kick(self, src: int) -> None:
        ex = self._exec
        if ex is not None and not ex.closed:
            c = ex.submit(f"net.{src}", lambda: self._drain(src),
                          label=f"net.drain.{src}",
                          coalesce_key=f"net.drain.{src}")
            if not c.cancelled:
                return
        # executor gone (teardown window between exec.close and the
        # pm-down barriers): the fallback thread serves late peers
        self._ensure_fallback()

    def _ensure_fallback(self) -> None:
        with self._in_cond:
            if self._fallback is not None and self._fallback.is_alive():
                self._in_cond.notify_all()
                return
            t = threading.Thread(target=self._fallback_loop,
                                 daemon=True,
                                 name=f"adapm-net-drain{self.pid}")
            self._fallback = t
        t.start()

    def _fallback_loop(self) -> None:
        while True:
            with self._in_cond:
                if self._closed:
                    return
                srcs = [s for s, q in self._inbox.items()
                        if q and s not in self._claimed]
                if not srcs:
                    if not self._in_cond.wait(1.0):
                        # idle for a second — park until re-kicked
                        if not any(self._inbox.values()):
                            self._fallback = None
                            return
                    continue
            for s in srcs:
                self._drain(s)

    def _drain(self, src: int) -> None:
        """Drain src's queue FIFO. Claim discipline: one drainer per
        src at a time (executor FIFO usually guarantees it; the claim
        closes the executor/fallback handover race)."""
        with self._in_cond:
            if src in self._claimed:
                return
            self._claimed.add(src)
        try:
            while True:
                with self._in_cond:
                    q = self._inbox.get(src)
                    if not q:
                        return
                    buf = q.popleft()
                    self._in_cond.notify_all()
                f = self.fault
                if f is not None and f.draw("net.recv"):
                    self._acct(dropped_frames=1)
                    continue
                try:
                    self._on_frame(buf)
                except NetDecodeError:
                    # counted in _on_frame; a corrupt frame is dropped
                    # before any server mutation
                    continue
        finally:
            with self._in_cond:
                self._claimed.discard(src)

    def drop_queues_from(self, src: int) -> None:
        with self._in_cond:
            q = self._inbox.get(src)
            if q is not None:
                q.clear()
            self._in_cond.notify_all()

    def fail_all_pending(self, err: BaseException) -> None:
        with self._pending_lock:
            pend = list(self._pending.values())
        for p in pend:
            if not p.event.is_set():
                p.error = err
                p.event.set()

    def shutdown(self) -> None:
        with self._in_cond:
            self._closed = True
            self._inbox.clear()
            self._in_cond.notify_all()


class LoopbackNode(NetNode):
    """NetNode over a LoopbackFabric: identity, channel, barriers,
    membership-backed liveness. One per in-process 'node'."""

    kind = "loopback"

    def __init__(self, fabric: LoopbackFabric, rank: int):
        self.fabric = fabric
        self.pid = int(rank)
        self.num_procs = fabric.world
        self.port: Optional[LoopbackPort] = None
        self.membership = None  # net/membership.py, built at bind
        self.server = None

    def make_channel(self, handler, serve_threads: int):
        self.port = LoopbackPort(
            self.fabric, self.pid, handler,
            ctrl_handler=self._on_ctrl)
        return self.port

    def _on_ctrl(self, src: int, msg) -> None:
        m = self.membership
        if m is not None:
            m.on_ctrl(src, msg)

    def bind(self, server) -> None:
        """Called by Server.__init__ once the executor and fault plane
        exist; the membership plane starts beating here."""
        self.server = server
        if self.port is not None:
            self.port.bind(server.exec, server.fault)
        from .membership import Membership
        self.membership = Membership(self, server,
                                     heartbeat_s=self.fabric.heartbeat_s)
        self.membership.start()

    def barrier(self, name: Optional[str] = None) -> None:
        self.fabric.barrier(name or "adapm", self.pid)

    def dead_peers(self, max_age_s: float = 10.0) -> list:
        m = self.membership
        if m is not None:
            return m.dead_peers()
        return sorted(self.fabric.killed)

    def pre_down(self) -> None:
        if self.membership is not None:
            self.membership.announce_leave()
            self.membership.stop()
        self.fabric.mark_left(self.pid)

    def net_plane(self):
        return self.membership


class LoopbackCluster:
    """N full Servers in one process, wired through the fabric — the
    loopback analog of tests/test_multiprocess.py's run_mp. Servers
    are constructed on per-rank threads (the pm-up barrier rendezvouses
    exactly like a real launch), and `run(fn)` drives one callable per
    rank the way mp_scenarios drives one process per rank."""

    def __init__(self, world: int, num_keys: int, value_lengths,
                 opts_factory=None, queue: int = 64,
                 timeout_ms: float = 2000.0, heartbeat_ms: float = 50.0,
                 retries: int = 16, num_workers: Optional[int] = None):
        from ..config import SystemOptions
        self.fabric = LoopbackFabric(world, queue=queue,
                                     timeout_ms=timeout_ms,
                                     retries=retries,
                                     heartbeat_ms=heartbeat_ms)
        self.nodes = [LoopbackNode(self.fabric, r) for r in range(world)]
        self.servers: List = [None] * world
        errs: List = [None] * world

        def build(rank: int) -> None:
            from ..core.kv import Server
            opts = opts_factory(rank) if opts_factory is not None \
                else SystemOptions(sync_max_per_sec=0, prefetch=False)
            try:
                self.servers[rank] = Server(
                    num_keys, value_lengths, opts=opts,
                    num_workers=num_workers, net_node=self.nodes[rank])
            except BaseException as e:  # noqa: BLE001 — surfaced below
                errs[rank] = e
                self.fabric.mark_left(rank)  # unblock peers' pm-up

        threads = [threading.Thread(target=build, args=(r,),
                                    name=f"adapm-loop-build{r}")
                   for r in range(world)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(_BARRIER_TIMEOUT_S)
        for e in errs:
            if e is not None:
                raise e

    def run(self, fn, ranks: Optional[List[int]] = None) -> List:
        """Drive `fn(rank, server)` on one thread per rank; re-raise
        the first failure. `ranks` restricts to survivors after a
        kill."""
        ranks = list(range(self.fabric.world)) if ranks is None else ranks
        out: List = [None] * self.fabric.world
        errs: List = [None] * self.fabric.world

        def drive(rank: int) -> None:
            try:
                out[rank] = fn(rank, self.servers[rank])
            except BaseException as e:  # noqa: BLE001 — surfaced below
                errs[rank] = e

        threads = [threading.Thread(target=drive, args=(r,),
                                    name=f"adapm-loop-run{r}")
                   for r in ranks]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for e in errs:
            if e is not None:
                raise e
        return out

    def kill(self, rank: int) -> None:
        self.fabric.kill(rank)

    def shutdown(self, ranks: Optional[List[int]] = None) -> None:
        ranks = [r for r in (ranks if ranks is not None
                             else range(self.fabric.world))
                 if r not in self.fabric.killed
                 and self.servers[r] is not None]
        self.run(lambda r, srv: srv.shutdown(), ranks=ranks)
