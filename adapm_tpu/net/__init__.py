"""NetPort transport plane (ISSUE 19; docs/NETWORK.md).

The PM's cross-process traffic — sync deltas (in the r13 compressed
wire format), relocations, ownership moves, serve forwards, and
membership control — rides a narrow `NetPort` carrying versioned,
checksummed frames. Three backends:

  - `loopback.py` — in-process fabric: per-peer bounded FIFO queues
    drained on the r11 executor's `net.<peer>` streams, so EVERY
    multi-node path runs, storm-tests, and fault-drills in one
    container, bit-identically to a single-process shadow.
  - `socket.py` — the TCP backend, one class by construction: it adds
    sockets to the frame/demux machinery the base class owns.
  - the legacy DCN channel (parallel/dcn.py), wrapped by `DcnNode` —
    the default for real multi-process launches, byte-identical to
    pre-NetPort behavior.

`membership.py` adds elastic shard join/leave and dead-peer failover
(replica -> main promotion through `Server._topology_mutation`)."""
from .port import (NetPort, NetNode, DcnNode, NetError, NetDecodeError,
                   FrameTruncatedError, FrameChecksumError,
                   FrameVersionError, FrameSpliceError, FrameFamilyError,
                   NetTimeoutError, NetPeerDeadError,
                   FAMILY_SYNC, FAMILY_RELOC, FAMILY_OWNER, FAMILY_SERVE,
                   FAMILY_CTRL, WIRE_VERSION)
from .loopback import LoopbackFabric, LoopbackNode, LoopbackCluster
from .membership import Membership

__all__ = [
    "NetPort", "NetNode", "DcnNode", "NetError", "NetDecodeError",
    "FrameTruncatedError", "FrameChecksumError", "FrameVersionError",
    "FrameSpliceError", "FrameFamilyError", "NetTimeoutError",
    "NetPeerDeadError", "FAMILY_SYNC", "FAMILY_RELOC", "FAMILY_OWNER",
    "FAMILY_SERVE", "FAMILY_CTRL", "WIRE_VERSION", "LoopbackFabric",
    "LoopbackNode", "LoopbackCluster", "Membership",
]
